"""Chaos harness tests (raftsql_tpu/chaos/).

Fast tier-1 scenarios: seeded drops/delays/partitions, crash+restart
of the fused runtime AND the lockstep RaftNode cluster, injected fsync
failures and mid-record power loss — with the four invariants
(durability, single leader per term, log matching, KV linearizability)
checked inside the runners (a violation raises and fails the test).
The full acceptance-scale sweeps are `slow`-marked; `make chaos
SEED=...` drives the same runner from the CLI, twice, and compares
digests.
"""
import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raftsql_tpu.chaos import (ChaosSchedule, FsyncFault, FusedChaosRunner,
                               NodeClusterChaosRunner, SkewWindow,
                               SnapshotChaosRunner, TcpClusterChaosRunner,
                               TornWriteFault, generate, generate_asym,
                               generate_compact, generate_corrupt_plan,
                               generate_enospc, generate_node_plan,
                               generate_skew, generate_snapshot_plan,
                               generate_stall, generate_tcp_plan)
from raftsql_tpu.config import RaftConfig
from raftsql_tpu.core.cluster import empty_cluster_inbox
from raftsql_tpu.storage import fsio
from raftsql_tpu.transport.faults import hold_messages, release_messages


# -- schedules ---------------------------------------------------------

def test_schedule_generation_deterministic_and_meets_floors():
    a = generate(12, ticks=240)
    b = generate(12, ticks=240)
    assert a == b and a.digest() == b.digest()
    assert a.ticks >= 200
    assert len(a.partitions) >= 2
    assert len(a.crashes) >= 2
    assert len(a.fsync_faults) >= 1
    assert len(a.torn_writes) >= 1
    assert generate(13, ticks=240).digest() != a.digest()


# -- the storage fault seam (storage/fsio.py) --------------------------

def test_fsio_fail_silent_tear_and_drop(tmp_path):
    inj = fsio.StorageFaultInjector()
    inj.add_rule(str(tmp_path), fail_at=(2,))
    p = str(tmp_path / "f.log")
    with fsio.installed(inj):
        f = open(p, "ab")
        fsio.write(f, b"A" * 10)
        fsio.fsync_file(f)                       # op 1: real sync
        fsio.write(f, b"B" * 10)
        with pytest.raises(fsio.FsyncFaultError):
            fsio.fsync_file(f)                   # op 2: injected fail
        f.close()
    assert inj.synced_size[p] == 10
    # A tear cuts into the unsynced record but never below the synced
    # prefix; dropping unsynced bytes restores exactly the synced size.
    assert inj.tear_last_write(p)
    assert 10 <= os.path.getsize(p) < 20
    inj.drop_unsynced(p)
    assert os.path.getsize(p) == 10


def test_fsio_crash_point_fires_after_the_write_lands(tmp_path):
    inj = fsio.StorageFaultInjector()
    inj.add_rule(str(tmp_path), crash_write_at=(2,), tag=7)
    p = str(tmp_path / "g.log")
    with fsio.installed(inj):
        f = open(p, "ab")
        fsio.write(f, b"first|")
        with pytest.raises(fsio.CrashPointError) as ei:
            fsio.write(f, b"second")
        assert ei.value.tag == 7
        f.close()
    # Page-cache semantics: the crashing write reached the file; the
    # power-loss simulation then tears it mid-record.
    assert os.path.getsize(p) == len(b"first|second")
    assert inj.tear_last_write(p)
    assert len(b"first|") <= os.path.getsize(p) < len(b"first|second")


def test_fsio_active_forces_python_wal_backend(tmp_path):
    from raftsql_tpu.storage.wal import WAL

    with fsio.installed(fsio.StorageFaultInjector()):
        w = WAL(str(tmp_path / "w"))
        assert not w.is_native
        w.append_entry(0, 1, 1, b"x")
        w.sync()
        w.close()
    logs = WAL.replay(str(tmp_path / "w"))
    assert [d for (_, d) in logs[0].entries] == [b"x"]


# -- message-plane delay masks -----------------------------------------

def test_hold_release_messages_roundtrip():
    cfg = RaftConfig(num_groups=2, num_peers=3, log_window=32,
                     max_entries_per_msg=4)
    ones = jax.tree.map(lambda x: jnp.ones_like(x),
                        empty_cluster_inbox(cfg))
    mask = np.zeros(ones.v_type.shape, bool)
    mask[0] = True                       # delay everything sent to peer 0
    delivered, held = hold_messages(ones, jnp.asarray(mask))
    assert int(np.asarray(delivered.v_type)[0].sum()) == 0
    assert int(np.asarray(held.v_type)[1:].sum()) == 0
    merged = release_messages(delivered, held)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(ones)):
        assert (np.asarray(a) == np.asarray(b)).all()


# -- fused-runtime scenarios (fast tier) -------------------------------

def test_fused_scenario_fast_invariants(tmp_path):
    """Seeded drops + delays + partitions (one leader-targeted) +
    crashes + a failed fsync + a torn write, 150 ticks.  Invariants
    are enforced inside the runner every tick."""
    sched = generate(5, ticks=150)
    r = FusedChaosRunner(sched, str(tmp_path / "a")).run()
    assert r["committed_entries"] > 0
    assert r["reads_checked"] > 0
    assert r["crashes"] >= len(sched.crashes)
    assert r["partitions"] >= 2
    assert r["safety_observations"] > 100


def test_fused_scenario_reproduces_bit_for_bit(tmp_path):
    """Same seed, fresh data dirs: the entire run — schedule, fault
    firings, committed history, reads — reproduces identically."""
    sched = generate(9, ticks=120)
    r1 = FusedChaosRunner(sched, str(tmp_path / "a")).run()
    r2 = FusedChaosRunner(sched, str(tmp_path / "b")).run()
    assert r1 == r2
    assert r1["result_digest"] == r2["result_digest"]


def test_torn_write_power_loss_repairs(tmp_path):
    """A mid-record power loss alone: the torn record is dropped by
    WAL._repair_tail on restart and every published entry survives
    (the durability ledger is verified at the restart)."""
    sched = ChaosSchedule(seed=3, ticks=100,
                          torn_writes=(TornWriteFault(1, 40),))
    r = FusedChaosRunner(sched, str(tmp_path)).run()
    assert r["torn_write_faults"] == 1
    assert r["torn_writes"] >= 1
    assert r["committed_entries"] > 0


def test_fsync_fault_is_fatal_and_recovers(tmp_path):
    """An injected fsync failure crashes the process (etcd posture)
    and the restart serves on from the durable prefix."""
    sched = ChaosSchedule(seed=4, ticks=100,
                          fsync_faults=(FsyncFault(0, 20),))
    r = FusedChaosRunner(sched, str(tmp_path)).run()
    assert r["fsync_faults"] == 1
    assert r["committed_entries"] > 0


def test_fused_scenario_multistep_epoch_framing(tmp_path):
    """The same chaos under RAFTSQL_FUSED_STEPS-style multi-step
    dispatch: crashes now interact with epoch framing (repair_epochs
    drops uncommitted dispatch frames on restart)."""
    sched = ChaosSchedule(seed=6, ticks=100,
                          torn_writes=(TornWriteFault(0, 50),))
    r = FusedChaosRunner(sched, str(tmp_path), steps=2).run()
    assert r["committed_entries"] > 0
    assert r["crashes"] >= 1


# -- the extended fault matrix (one fast seed per family) --------------

def test_fsio_enospc_fires_once_before_the_write(tmp_path):
    """ENOSPC raises BEFORE any byte lands (clean tail) and the trigger
    is consumed: the post-restart retry of the same record succeeds."""
    inj = fsio.StorageFaultInjector()
    inj.add_rule(str(tmp_path), enospc_write_at=(2,))
    p = str(tmp_path / "e.log")
    with fsio.installed(inj):
        f = open(p, "ab")
        fsio.write(f, b"A" * 10)
        with pytest.raises(fsio.EnospcError):
            fsio.write(f, b"B" * 10)
        assert os.path.getsize(p) == 10        # nothing landed
        fsio.write(f, b"B" * 10)               # consumed: retry lands
        f.close()
    assert os.path.getsize(p) == 20
    assert inj.enospc_hits == 1


def test_fsio_stall_counts_and_still_syncs(tmp_path):
    import time as _time
    inj = fsio.StorageFaultInjector()
    inj.add_rule(str(tmp_path), stall_at=(1,), stall_s=0.05)
    p = str(tmp_path / "s.log")
    with fsio.installed(inj):
        f = open(p, "ab")
        fsio.write(f, b"X")
        t0 = _time.monotonic()
        fsio.fsync_file(f)
        assert _time.monotonic() - t0 >= 0.05   # it stalled ...
        f.close()
    assert inj.fsync_stalls == 1
    assert inj.synced_size[p] == 1              # ... but synced for real


def test_family_asym_partition(tmp_path):
    """One-directional partitions (leader-deafness + a random link cut)
    + a crash: all invariants in-run, counters reported."""
    r = FusedChaosRunner(generate_asym(2, ticks=110),
                         str(tmp_path)).run()
    assert r["asym_partitions"] == 2
    assert r["crashes"] >= 1
    assert r["committed_entries"] > 0


def test_family_clock_skew_changes_elections(tmp_path):
    """The lockstep-timer assumption is the suspect one (ROADMAP): the
    SAME seed run lockstep vs with per-peer timer skew must elect
    DIFFERENT leaders somewhere — proof the per-peer timer_inc really
    reaches the device step — while both runs keep every invariant."""
    sk = generate_skew(0, ticks=120)
    lock = dataclasses.replace(sk, skews=())
    ra = FusedChaosRunner(lock, str(tmp_path / "lock"))
    rep_a = ra.run()
    rb = FusedChaosRunner(sk, str(tmp_path / "skew"))
    rep_b = rb.run()
    assert rep_b["skew_ticks"] > 0 and rep_a["skew_ticks"] == 0
    # Election behavior diverges: some (group, term) elected a
    # different leader (both runs' ElectionSafety maps are complete
    # run histories, so comparing them compares every election).
    assert ra.safety._leader_of_term != rb.safety._leader_of_term
    assert rep_a["result_digest"] != rep_b["result_digest"]
    # And the skewed run's fault counters export through NodeMetrics.
    assert rb.final_metrics.faults_skew_ticks == rep_b["skew_ticks"]
    assert rb.final_metrics.snapshot()["faults"]["skew_ticks"] \
        == rep_b["skew_ticks"]


def test_family_skew_reproduces(tmp_path):
    sk = generate_skew(4, ticks=100)
    r1 = FusedChaosRunner(sk, str(tmp_path / "a")).run()
    r2 = FusedChaosRunner(sk, str(tmp_path / "b")).run()
    assert r1 == r2


def test_family_enospc(tmp_path):
    """Disk-full on WAL append is fatal (etcd posture), restart serves
    on from a clean tail, and the counter exports."""
    runner = FusedChaosRunner(generate_enospc(1, ticks=110),
                              str(tmp_path))
    r = runner.run()
    assert r["enospc_hits"] == 2
    assert r["crashes"] >= 2
    assert r["committed_entries"] > 0
    assert runner.final_metrics.faults_enospc == 2
    assert runner.final_metrics.snapshot()["faults"]["enospc"] == 2


def test_family_fsync_stall(tmp_path):
    """Slow-disk fsync stalls: latency, never corruption — the run
    completes with every invariant and counts each stall."""
    runner = FusedChaosRunner(generate_stall(1, ticks=100),
                              str(tmp_path))
    r = runner.run()
    assert r["fsync_stalls"] > 0
    assert r["committed_entries"] > 0
    assert runner.final_metrics.faults_fsync_stalls == r["fsync_stalls"]


def test_family_compact_crash_interleaving(tmp_path):
    """Aggressive compaction under crashes (one a torn-write power
    loss): restart replays COMPACT-marked WALs, the durability audit
    and log matching run floor-aware, and the KV state survives through
    the ledger's snapshot stand-in."""
    r = FusedChaosRunner(generate_compact(3, ticks=160),
                         str(tmp_path)).run()
    assert r["compactions"] > 0
    assert r["crashes"] >= 2
    assert r["torn_write_faults"] >= 1
    assert r["committed_entries"] > 40


def test_family_corrupt_frames_node_plane(tmp_path):
    """Byzantine frame corruption on the lockstep wire plane: every
    mangled frame is CRC-dropped (counted into the receiving node's
    metrics), consensus rides out the loss, and the run reproduces."""
    plan = generate_corrupt_plan(1, ticks=200)
    r1 = NodeClusterChaosRunner(plan, str(tmp_path / "a")).run()
    assert r1["corrupt_frames"] > 0
    assert r1["commits"] > 20
    r2 = NodeClusterChaosRunner(plan, str(tmp_path / "b")).run()
    assert r1["result_digest"] == r2["result_digest"]


def test_family_skew_node_plane(tmp_path):
    """Per-peer timer skew on the lockstep RaftNode plane: each node
    ticks with its own timer_inc (0 = stalled clock, 2 = fast) while a
    crash interleaves — invariants hold, counters export."""
    plan = dataclasses.replace(generate_node_plan(2, ticks=240),
                               skews=(SkewWindow(60, 120, (2, 1, 0)),))
    r = NodeClusterChaosRunner(plan, str(tmp_path)).run()
    assert r["skew_ticks"] > 0
    assert r["commits"] > 20


def test_family_snapshot_install_convergence(tmp_path):
    """Compaction + InstallSnapshot + crash interleaving: a follower
    crashed past every retained floor is rebuilt by a full state
    transfer, a second (leader-targeted) crash lands later, and after
    the heal window the survivors CONVERGE (the new invariant)."""
    plan = generate_snapshot_plan(0)
    r = SnapshotChaosRunner(plan, str(tmp_path)).run()
    assert r["snapshots_installed"] > 0
    assert r["compactions"] > 0
    assert r["crashes"] == 2
    assert r["commits"] > 100


def test_family_tcp_transport(tmp_path):
    """Chaos under the REAL TCP transport: send-side drops, asymmetric
    blocks, frame corruption, delays.  Invariants hold on every run
    (this plane is not bit-reproducible — kernel-scheduled arrival);
    every corrupt frame is dropped + counted at the receivers."""
    plan = generate_tcp_plan(1, ticks=140)
    r = TcpClusterChaosRunner(plan, str(tmp_path)).run()
    assert r["sent_corrupted"] > 0
    assert r["corrupt_frames_dropped"] > 0
    assert r["sent_dropped"] > 0
    assert r["asym_partitions"] == 1
    assert r["commits"] > 20


# -- leadership-transfer nemesis (PR 11) -------------------------------

def test_family_transfer_under_nemesis(tmp_path):
    """Graceful transfers racing drops, a leader-targeted partition, an
    asym cut, skew and a crash under acked-PUT load — every transfer
    resolves, at least one completes, post-transfer probes commit, and
    the run reproduces bit-for-bit."""
    from raftsql_tpu.chaos import TransferChaosRunner, generate_transfers
    plan = generate_transfers(0)
    r1 = TransferChaosRunner(plan, str(tmp_path / "a")).run()
    r2 = TransferChaosRunner(plan, str(tmp_path / "b")).run()
    assert r1 == r2
    assert r1["transfers_requested"] >= 6
    assert r1["transfers_completed"] >= 1
    assert r1["transfer_probes_confirmed"] >= 1
    assert r1["partitions"] >= 1 and r1["crashes"] >= 1
    assert r1["plan_digest"] == plan.digest()


def test_transfer_falsification_pair(tmp_path, monkeypatch):
    """The robustness headline: the SAME directed lagging-target
    schedule must CATCH the deliberately broken transfer kernel
    (unsafe_transfer: depose the leader before the target caught up —
    the target cannot win the election, the transfer aborts) and PASS
    the correct kernel (catch-up gate holds the TimeoutNow until the
    target's match_index is current, then it wins immediately)."""
    from raftsql_tpu.chaos import (TransferChaosRunner,
                                   falsification_transfer_plan)
    from raftsql_tpu.chaos.invariants import InvariantViolation
    monkeypatch.setenv("RAFTSQL_FLIGHT_DIR", str(tmp_path / "flight"))
    with pytest.raises(InvariantViolation,
                       match="TRANSFER-AVAILABILITY"):
        TransferChaosRunner(falsification_transfer_plan(0, broken=True),
                            str(tmp_path / "broken")).run()
    r = TransferChaosRunner(falsification_transfer_plan(0, broken=False),
                            str(tmp_path / "ok")).run()
    assert r["transfers_completed"] == 1
    assert r["max_transfer_stall"] <= 60


# -- threaded RaftNode cluster scenarios -------------------------------

def test_node_cluster_partition_leader_kill_restart(tmp_path):
    """Lockstep 3-node RaftNode cluster: a partition window, a
    leader-targeted kill and a follower kill (hard crashes), each
    restarted from its WAL.  Election safety, per-node durability
    across restart, and cross-node log matching are enforced in-run."""
    plan = generate_node_plan(7, ticks=280)
    r = NodeClusterChaosRunner(plan, str(tmp_path)).run()
    assert r["crashes"] == 2
    assert r["restarts"] == 2
    assert r["partitions"] == 1
    assert r["commits"] > 20


# -- deep sweeps (slow tier) -------------------------------------------

@pytest.mark.slow
def test_chaos_seed_sweep_deep(tmp_path):
    """Acceptance-scale sweep: several seeds at >= 240 ticks, each run
    twice — every run must pass all invariants and reproduce
    bit-for-bit."""
    for seed in range(4):
        sched = generate(seed, ticks=240)
        r1 = FusedChaosRunner(sched, str(tmp_path / f"s{seed}a")).run()
        r2 = FusedChaosRunner(sched, str(tmp_path / f"s{seed}b")).run()
        assert r1 == r2, f"seed {seed} diverged"
        assert r1["fsync_faults"] >= 1
        assert r1["torn_writes"] >= 1


@pytest.mark.slow
def test_node_cluster_seed_sweep(tmp_path):
    for seed in range(3):
        plan = generate_node_plan(seed, ticks=400)
        r = NodeClusterChaosRunner(plan,
                                   str(tmp_path / f"s{seed}")).run()
        assert r["commits"] > 20, f"seed {seed} starved"


@pytest.mark.slow
def test_matrix_seed_sweep(tmp_path):
    """Acceptance-scale matrix sweep: several seeds through every
    family via the `make chaos-matrix` entry point (deterministic
    families digest-compared inside)."""
    from raftsql_tpu.chaos.run import run_matrix
    for seed in range(3):
        assert run_matrix(seed) == 0, f"seed {seed} failed"


@pytest.mark.slow
def test_snapshot_family_seed_sweep(tmp_path):
    for seed in range(3):
        plan = generate_snapshot_plan(seed)
        r = SnapshotChaosRunner(plan, str(tmp_path / f"s{seed}")).run()
        assert r["snapshots_installed"] > 0, f"seed {seed}: no install"
