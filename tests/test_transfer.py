"""Leadership-transfer plane (PR 11): the TimeoutNow device kernel
(core/step.py Phases 1b/6/9) through the host latch
(runtime/hostplane.py transfer_leadership/_transfer_arm/
_transfer_advance), refusal taxonomy, abort-on-deadline re-opening the
group, the TransferAvailability chaos invariant, the placement
controller's balancing decision, and transfer-plan digest stability.
The transfer-under-nemesis family itself runs in `make chaos-transfer`
(tests/test_chaos.py smoke-gates it).
"""
import pytest

from raftsql_tpu.chaos.invariants import (InvariantViolation,
                                          TransferAvailability)
from raftsql_tpu.config import RaftConfig
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.runtime.node import TransferRefused
from raftsql_tpu.transport.faults import partition_peer


def mkcfg(groups=2):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, election_ticks=10,
                      tick_interval_s=0.0)


def elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


def settle(node, group, target, max_ticks=80):
    """Tick until `group`'s hint names `target` AND the latch cleared
    (completion is recorded one hint-refresh after the election)."""
    for _ in range(max_ticks):
        node.tick()
        if int(node._hints[group]) == target \
                and group not in node.transferring_groups():
            return
    raise AssertionError(
        f"transfer never settled: hint={int(node._hints[group])} "
        f"inflight={node.transferring_groups()}")


def test_transfer_completes_and_logs_event(tmp_path):
    node = FusedClusterNode(mkcfg(), str(tmp_path))
    try:
        elect(node)
        g = 0
        old = int(node._hints[g])
        target = (old + 1) % 3
        node.propose_many(g, [b"SET k0 v0"])
        got = node.transfer_leadership(g, target)
        assert got["from"] == old + 1 and got["target"] == target + 1
        assert g in node.transferring_groups()
        settle(node, g, target)
        doc = node.transfers_doc()
        assert doc["in_flight"] == {}
        ev = doc["recent"][-1]
        assert ev["outcome"] == "completed"
        assert ev["group"] == g and ev["to"] == target + 1
        assert ev["stall_ticks"] >= 0
        assert node.metrics.transfers_initiated == 1
        assert node.metrics.transfers_completed == 1
        assert node.metrics.transfers_aborted == 0
        assert sum(node.metrics.transfer_stall_hist.values()) == 1
        # The group serves under its new leader: a post-transfer
        # proposal must commit.
        before = int(node._hard[0, g, 2])
        node.propose_many(g, [b"SET k0 v1"])
        for _ in range(20):
            node.tick()
        assert int(node._hard[0, g, 2]) > before
    finally:
        node.stop()


def test_transfer_refusal_taxonomy(tmp_path):
    node = FusedClusterNode(mkcfg(), str(tmp_path))
    try:
        with pytest.raises(ValueError):
            node.transfer_leadership(99, 0)
        with pytest.raises(ValueError):
            node.transfer_leadership(0, 99)
        # Nothing elected yet: no leader to transfer from.
        with pytest.raises(TransferRefused, match="no leader"):
            node.transfer_leadership(0, 0)
        elect(node)
        lead = int(node._hints[0])
        with pytest.raises(TransferRefused, match="already leads"):
            node.transfer_leadership(0, lead)
        # One in flight per group: the second request bounces off the
        # latch without touching device state.
        target = (lead + 1) % 3
        node.transfer_leadership(0, target)
        with pytest.raises(TransferRefused, match="in flight"):
            node.transfer_leadership(0, (lead + 2) % 3)
        # Only engine refusals count — range errors are caller bugs.
        assert node.metrics.transfers_refused == 3
        settle(node, 0, target)
    finally:
        node.stop()


def test_transfer_aborts_on_deadline_and_group_reopens(tmp_path):
    node = FusedClusterNode(mkcfg(), str(tmp_path))
    try:
        elect(node)
        g = 0
        old = int(node._hints[g])
        target = (old + 1) % 3
        # Freeze the target's replication: the catch-up gate (Phase 9)
        # can never observe a caught-up match_index, so the latch must
        # hit its deadline and clear.
        node.propose_many(g, [b"SET k0 v0", b"SET k0 v1"])
        node.transfer_leadership(g, target, deadline_ticks=12)
        for _ in range(40):
            node.inboxes = partition_peer(node.inboxes, target)
            node.tick()
            if g not in node.transferring_groups():
                break
        doc = node.transfers_doc()
        assert doc["in_flight"] == {}
        assert doc["recent"][-1]["outcome"] == "aborted"
        assert node.metrics.transfers_aborted == 1
        # Aborted transfer re-opens the group under the OLD leader:
        # intake resumes and commits advance.
        assert int(node._hints[g]) == old
        before = int(node._hard[0, g, 2])
        node.propose_many(g, [b"SET k0 v2"])
        for _ in range(20):
            node.tick()
        assert int(node._hard[0, g, 2]) > before
    finally:
        node.stop()


# -- TransferAvailability invariant (pure host logic) -------------------


def _avail():
    return TransferAvailability(election_ticks=10, deadline_ticks=40,
                                max_stall_ticks=30, probe_ticks=20)


def test_availability_must_complete_abort_fires():
    a = _avail()
    a.note_issued(5, 0, must_complete=True)
    with pytest.raises(InvariantViolation,
                       match="TRANSFER-AVAILABILITY"):
        a.note_outcome(21, 0, "aborted", 16)


def test_availability_stall_bound_fires():
    a = _avail()
    a.note_issued(5, 0, must_complete=True)
    with pytest.raises(InvariantViolation, match="stalled"):
        a.note_outcome(50, 0, "completed", 45)


def test_availability_ordinary_abort_is_legal():
    a = _avail()
    a.note_issued(5, 0, must_complete=False)
    a.note_outcome(21, 0, "aborted", 16)
    assert a.aborted == 1 and a.max_stall == 16
    a.check(200)                       # nothing pending: no violation


def test_availability_stuck_latch_fires():
    a = _avail()
    a.note_issued(5, 1, must_complete=False)
    a.check(5 + 40 + 2 * 10)           # exactly at the margin: fine
    with pytest.raises(InvariantViolation, match="unresolved"):
        a.check(5 + 40 + 2 * 10 + 1)
    with pytest.raises(InvariantViolation, match="never resolved"):
        a.final_check(199)


def test_availability_probe_deadline_and_crash_void():
    a = _avail()
    a.arm_probe(10, 0, "v7")
    a.probe_committed("v7")
    assert a.probes_confirmed == 1
    a.check(100)
    a.arm_probe(100, 1, "v8")
    with pytest.raises(InvariantViolation, match="stopped serving"):
        a.check(121)
    # Crash voids in-flight probes and pending transfers.
    a = _avail()
    a.note_issued(5, 0, must_complete=True)
    a.arm_probe(5, 0, "v9")
    a.note_crash()
    a.check(500)
    a.final_check(500)


# -- placement controller ----------------------------------------------


class _FakeEngine:
    """Minimal engine surface for PlacementController: a real
    GroupTraffic feed plus scripted leaders and a recording
    transfer_leadership."""

    def __init__(self, leaders, rates):
        from raftsql_tpu.utils.metrics import GroupTraffic
        self.cfg = RaftConfig(num_groups=len(leaders), num_peers=3,
                              tick_interval_s=0.0)
        self.traffic = GroupTraffic(len(leaders), alpha=1.0)
        for g, n in enumerate(rates):
            self.traffic.add_propose(g, n)
        # One whole EWMA window so add_propose counts become rates.
        self.traffic._last_t -= 1.0
        self.leaders = list(leaders)
        self.transfers = []
        self.refuse = False

    def leader_of(self, g):
        return self.leaders[g]

    def transfer_leadership(self, g, target):
        if self.refuse:
            raise TransferRefused(g, "transfer already in flight")
        self.transfers.append((g, target))


def test_placement_moves_hot_group_to_cold_peer():
    from raftsql_tpu.placement.controller import PlacementController
    # Peer 0 leads two hot groups; peer 2 leads nothing.
    eng = _FakeEngine(leaders=[0, 0, 1, 1], rates=[60, 40, 8, 0])
    pc = PlacementController(eng, imbalance=2.0, min_rate=1.0)
    d = pc.evaluate()
    assert d is not None and d["outcome"] == "pending"
    # The hottest group (60/s) exceeds half the gap (100 vs 0) is
    # false — 60 > 50 — so the mover must pick the 40/s group.
    assert eng.transfers == [(1, 2)]
    assert d["group"] == 1 and d["to"] == 3
    assert pc.issued == 1


def test_placement_idle_cluster_never_churns():
    from raftsql_tpu.placement.controller import PlacementController
    eng = _FakeEngine(leaders=[0, 0, 1, 2], rates=[0, 0, 0, 0])
    pc = PlacementController(eng, imbalance=2.0, min_rate=1.0)
    assert pc.evaluate() is None
    assert eng.transfers == []


def test_placement_refusal_backs_off():
    from raftsql_tpu.placement.controller import PlacementController
    eng = _FakeEngine(leaders=[0, 0], rates=[50, 30])
    eng.refuse = True
    pc = PlacementController(eng, imbalance=2.0, min_rate=1.0)
    d = pc.evaluate()
    assert d["outcome"].startswith("refused")
    assert pc.refused == 1
    # The refused group is in backoff; the pass may fall through to
    # another candidate or to None, but must NOT re-issue group 1.
    eng2_calls = len(eng.transfers)
    pc.evaluate()
    assert len(eng.transfers) == eng2_calls
    assert pc.metrics_doc()["backoff_groups"] >= 1


# -- plan + digest stability -------------------------------------------


def test_transfer_plan_digests_are_stable():
    from raftsql_tpu.chaos.schedule import (falsification_transfer_plan,
                                            generate_transfers)
    p1, p2 = generate_transfers(7), generate_transfers(7)
    assert p1 == p2 and p1.digest() == p2.digest()
    assert generate_transfers(8).digest() != p1.digest()
    broken = falsification_transfer_plan(0, broken=True)
    correct = falsification_transfer_plan(0, broken=False)
    assert broken.unsafe_transfer and not correct.unsafe_transfer
    # Identical SCHEDULE, differing only in which kernel compiles in —
    # the falsification pair's whole point.
    db, dc = broken.describe(), correct.describe()
    db.pop("unsafe_transfer"), dc.pop("unsafe_transfer")
    assert db == dc
    ev = broken.transfers[0]
    assert ev.must_complete and ev.tick == broken.partitions[0].end


def test_split_hottest_partitions_by_slot_traffic():
    """The split verb must divide the hot group's observed LOAD, not
    its slot count: count-halving under a skewed workload can hand the
    hot slots themselves to dst, crowning it the new hottest group
    (scripts/bench_reshard.py demonstrates the regression end to
    end)."""
    from raftsql_tpu.placement.controller import PlacementController
    from raftsql_tpu.reshard.keymap import KeyMap

    class _FakePlane:
        def __init__(self, km):
            self.keymap = km
            self.slot_hits = [0] * km.nslots
            self.calls = []

        def enqueue(self, verb, src, dst, slots=None):
            self.calls.append((verb, src, dst, list(slots)))
            return {"verb": verb, "src": src, "dst": dst,
                    "slots": list(slots)}

    # Group 0 owns slots 0,2,4,6 (stripe of G=2 over 8 slots) and is
    # the rate-EWMA hottest; slot 0 carries most of its traffic.
    eng = _FakeEngine(leaders=[0, 0], rates=[90, 5])
    pc = PlacementController(eng)
    plane = _FakePlane(KeyMap.initial(2, nslots=8))
    pc.reshard = plane
    plane.slot_hits[0] = 100
    plane.slot_hits[2] = 10
    plane.slot_hits[4] = 6
    plane.slot_hits[6] = 5
    doc = pc.split_hottest()
    assert doc is not None and plane.calls == [("split", 0, 1, [2, 4, 6])]
    # The hot slot STAYS with src: src keeps ~100 hits, dst gets ~21.

    # Without a per-slot signal the verb falls back to count-halving.
    plane.calls.clear()
    plane.slot_hits = [0] * 8
    assert pc.split_hottest() is not None
    assert plane.calls == [("split", 0, 1, [0, 2])]
