"""TcpTransport — the DCN peer plane (the rafthttp analog).

The reference trusts vendored etcd/rafthttp streams (reference
raft.go:170-184, 248-266); transport/tcp.py is our from-scratch framed-TCP
replacement, so its wire handling gets direct tests: frame reassembly
across arbitrary recv boundaries, oversized-frame defense, reconnect after
peer restart, and drop-oldest backpressure.
"""
import queue
import socket
import threading
import time


from conftest import free_port
from raftsql_tpu.transport.base import (AppendRec, ProposalRec, SnapshotRec,
                                        TickBatch, VoteRec)
from raftsql_tpu.transport.codec import encode_batch_framed
from raftsql_tpu.transport.tcp import (_FRAME, _QUEUE_CAP, _PeerSender,
                                       TcpTransport, parse_peer_url)

TIMEOUT = 10.0


def sample_batch() -> TickBatch:
    return TickBatch(
        votes=[VoteRec(group=3, type=1, term=7, last_idx=4, last_term=2,
                       granted=True)],
        appends=[AppendRec(group=1, type=1, term=7, prev_idx=9, prev_term=6,
                           ent_terms=[7, 7], payloads=[b"a", b"bb"],
                           commit=8, seq=41)],
        proposals=[ProposalRec(group=0, payload=b"INSERT")],
        snapshots=[SnapshotRec(group=2, last_idx=11, last_term=5, term=7,
                               blob=b"\x00blob")])


def assert_batches_equal(got: TickBatch, want: TickBatch) -> None:
    assert got.votes == want.votes
    assert got.appends == want.appends
    assert got.proposals == want.proposals
    assert got.snapshots == want.snapshots


class Receiver:
    """One TcpTransport listening on a free port, collecting deliveries
    (slot 1 of a 2-node topology; slot 0 is never bound)."""

    def __init__(self):
        self.port = free_port()
        urls = [f"http://127.0.0.1:{free_port()}",
                f"http://127.0.0.1:{self.port}"]
        self.transport = TcpTransport(urls, 1)
        self.got: "queue.Queue" = queue.Queue()
        self.errors = []
        self.transport.start(2, self._deliver, self.errors.append)

    def _deliver(self, src, batch):
        self.got.put((src, batch))

    def stop(self):
        self.transport.stop()


class TestWire:
    def test_parse_peer_url(self):
        assert parse_peer_url("http://127.0.0.1:12379") == ("127.0.0.1",
                                                            12379)
        assert parse_peer_url("10.0.0.2:99") == ("10.0.0.2", 99)
        assert parse_peer_url("http://h:1/") == ("h", 1)

    def test_frame_reassembly_byte_by_byte(self):
        """Frames split at every possible recv boundary must reassemble."""
        rx = Receiver()
        try:
            blob = encode_batch_framed(sample_batch())
            wire = _FRAME.pack(len(blob), 1) + blob
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                for i in range(len(wire)):
                    s.sendall(wire[i:i + 1])
            src, got = rx.got.get(timeout=TIMEOUT)
            assert src == 1
            assert_batches_equal(got, sample_batch())
        finally:
            rx.stop()

    def test_many_frames_in_one_segment(self):
        """Multiple frames coalesced into one send must all deliver, in
        order."""
        rx = Receiver()
        try:
            frames = b""
            for k in range(5):
                b = TickBatch(proposals=[ProposalRec(group=0,
                                                     payload=b"p%d" % k)])
                blob = encode_batch_framed(b)
                frames += _FRAME.pack(len(blob), 1) + blob
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(frames)
            for k in range(5):
                _, got = rx.got.get(timeout=TIMEOUT)
                assert got.proposals[0].payload == b"p%d" % k
        finally:
            rx.stop()

    def test_oversized_frame_drops_connection(self):
        """A length field over _MAX_FRAME must drop the connection without
        delivering anything or buffering 4 GiB."""
        rx = Receiver()
        try:
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(_FRAME.pack(1 << 31, 1))
                s.settimeout(TIMEOUT)
                # Receiver closes its side; recv unblocks with EOF (or a
                # reset, also acceptable).
                try:
                    assert s.recv(1) == b""
                except OSError:
                    pass
            assert rx.got.empty()
            assert rx.errors == []      # bad peer is not fatal locally
        finally:
            rx.stop()

    def test_garbage_after_valid_frame(self):
        """A valid frame followed by an oversized header: the first frame
        delivers, then the connection drops."""
        rx = Receiver()
        try:
            blob = encode_batch_framed(sample_batch())
            wire = _FRAME.pack(len(blob), 1) + blob \
                + _FRAME.pack(0xFFFFFFFF, 1)
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(wire)
            src, got = rx.got.get(timeout=TIMEOUT)
            assert_batches_equal(got, sample_batch())
            assert rx.got.empty()
        finally:
            rx.stop()

    def test_corrupt_frame_skipped_connection_survives(self):
        """A CRC-corrupt frame is dropped + counted, and the SAME
        connection keeps delivering later frames — the recv loop must
        not die with the frame (the pre-hardening behavior killed the
        thread silently)."""
        rx = Receiver()
        try:
            good = encode_batch_framed(sample_batch())
            bad = bytearray(good)
            bad[len(bad) // 2] ^= 0x5A
            wire = (_FRAME.pack(len(bad), 1) + bytes(bad)
                    + _FRAME.pack(len(good), 1) + good)
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(wire)
                src, got = rx.got.get(timeout=TIMEOUT)
            assert src == 1
            assert_batches_equal(got, sample_batch())
            assert rx.transport.metrics.faults_corrupt_frames == 1
            assert rx.errors == []      # never fatal locally
        finally:
            rx.stop()

    def test_malformed_counts_dropped_not_fatal(self):
        """A frame whose CRC is valid but whose declared record counts
        exceed its bytes (a Byzantine sender) is dropped by the codec's
        bounds validation, and later frames still deliver."""
        import struct as _struct
        import zlib as _zlib
        rx = Receiver()
        try:
            # Declares 1000 votes, carries none.
            payload = _struct.pack("<I", 1000)
            evil = _struct.pack("<I", _zlib.crc32(payload)) + payload
            good = encode_batch_framed(sample_batch())
            wire = (_FRAME.pack(len(evil), 1) + evil
                    + _FRAME.pack(len(good), 1) + good)
            with socket.create_connection(("127.0.0.1", rx.port),
                                          timeout=5) as s:
                s.sendall(wire)
                src, got = rx.got.get(timeout=TIMEOUT)
            assert_batches_equal(got, sample_batch())
            assert rx.transport.metrics.faults_corrupt_frames == 1
        finally:
            rx.stop()


class TestSendFaults:
    def test_send_faults_corrupt_caught_by_receiver(self):
        """End-to-end over real sockets: the send-side fault seam
        corrupts frames, the receiver's CRC drops + counts every one,
        and clean frames still flow once rates reset."""
        from raftsql_tpu.transport.tcp import SendFaults
        rx_port = free_port()
        urls = [f"http://127.0.0.1:{free_port()}",
                f"http://127.0.0.1:{rx_port}"]
        got: "queue.Queue" = queue.Queue()
        rx = TcpTransport(urls, 1)
        rx.start(2, lambda s, b: got.put((s, b)), lambda e: None)
        tx = TcpTransport(urls, 0)
        tx.faults = SendFaults(seed=7)
        tx.faults.set_rates(p_corrupt=1.0)
        tx.start(1, lambda s, b: None, lambda e: None)
        try:
            deadline = time.monotonic() + TIMEOUT
            while rx.metrics.faults_corrupt_frames == 0 \
                    and time.monotonic() < deadline:
                tx.send(2, sample_batch())
                time.sleep(0.05)
            assert rx.metrics.faults_corrupt_frames > 0
            assert tx.faults.corrupted > 0
            assert got.empty()          # nothing corrupt delivered
            tx.faults.set_rates()       # heal: clean frames deliver
            deadline = time.monotonic() + TIMEOUT
            while got.empty() and time.monotonic() < deadline:
                tx.send(2, sample_batch())
                time.sleep(0.05)
            src, batch = got.get(timeout=1)
            assert_batches_equal(batch, sample_batch())
        finally:
            tx.stop()
            rx.stop()

    def test_send_faults_block_is_one_directional(self):
        """block(dst) drops at send; drop/delay counters track."""
        from raftsql_tpu.transport.tcp import SendFaults
        f = SendFaults(seed=0)
        f.block(2)
        assert f.apply(2, b"x") is None
        assert f.apply(3, b"x") == (b"x", 0.0)
        assert f.dropped == 1
        f.heal()
        assert f.apply(2, b"x") == (b"x", 0.0)
        f.set_rates(p_delay=1.0, delay_s=0.25)
        blob, delay = f.apply(2, b"y")
        assert blob == b"y" and delay == 0.25
        assert f.delayed == 1


class TestSenderBackpressure:
    def test_drop_oldest_when_queue_full(self):
        """offer() on a full queue evicts the oldest blob (raft re-sends;
        freshest state wins)."""
        sender = _PeerSender(1, ("127.0.0.1", 1), threading.Event())
        # Not started: queue fills without draining.
        for k in range(_QUEUE_CAP):
            sender.offer(b"old%d" % k)
        assert sender.q.qsize() == _QUEUE_CAP
        sender.offer(b"new")
        assert sender.q.qsize() == _QUEUE_CAP
        drained = []
        while True:
            try:
                drained.append(sender.q.get_nowait())
            except queue.Empty:
                break
        assert b"old0" not in drained       # oldest evicted
        assert drained[-1] == b"new"        # newest kept

    def test_send_to_down_peer_does_not_block(self):
        """send() must return immediately with the peer down (the tick
        loop can never stall on a dead peer)."""
        port = free_port()
        urls = [f"http://127.0.0.1:{port}",
                f"http://127.0.0.1:{free_port()}"]
        tr = TcpTransport(urls, 0)
        tr.start(1, lambda s, b: None, lambda e: None)
        try:
            t0 = time.monotonic()
            for _ in range(50):
                tr.send(2, sample_batch())
            assert time.monotonic() - t0 < 1.0
        finally:
            tr.stop()


class TestReconnect:
    def test_sender_reconnects_after_peer_restart(self):
        """Kill the receiving transport, restart it on the same port, and
        the sender's retry loop must re-deliver without intervention."""
        rx_port = free_port()
        tx_port = free_port()
        urls = [f"http://127.0.0.1:{tx_port}", f"http://127.0.0.1:{rx_port}"]

        got: "queue.Queue" = queue.Queue()
        rx = TcpTransport(urls, 1)
        rx.start(2, lambda s, b: got.put((s, b)), lambda e: None)

        tx = TcpTransport(urls, 0)
        tx.start(1, lambda s, b: None, lambda e: None)
        try:
            deadline = time.monotonic() + TIMEOUT
            while got.empty() and time.monotonic() < deadline:
                tx.send(2, sample_batch())
                time.sleep(0.05)
            src, batch = got.get(timeout=1)
            assert src == 1
            assert_batches_equal(batch, sample_batch())

            rx.stop()
            time.sleep(0.3)             # let the sender's socket die
            while not got.empty():      # drop leftover phase-1 deliveries
                got.get_nowait()        # (phase 2 must prove rx2 receives)
            rx2 = TcpTransport(urls, 1)
            rx2.start(2, lambda s, b: got.put((s, b)), lambda e: None)
            try:
                deadline = time.monotonic() + TIMEOUT
                while got.empty() and time.monotonic() < deadline:
                    tx.send(2, sample_batch())
                    time.sleep(0.05)
                src, batch = got.get(timeout=1)
                assert src == 1
                assert_batches_equal(batch, sample_batch())
            finally:
                rx2.stop()
        finally:
            tx.stop()
            if not rx._stop_evt.is_set():
                rx.stop()

    def test_bind_failure_is_fatal_locally(self):
        """A local listener failure must surface via on_error (reference
        raft.go:237-239: local transport error tears the node down)."""
        port = free_port()
        blocker = socket.socket()
        blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 0)
        blocker.bind(("127.0.0.1", port))
        blocker.listen(1)
        try:
            errors = []
            urls = [f"http://127.0.0.1:{port}",
                    f"http://127.0.0.1:{free_port()}"]
            tr = TcpTransport(urls, 0)
            tr.start(1, lambda s, b: None, errors.append)
            try:
                assert errors, "bind conflict must report an error"
            finally:
                tr.stop()
        finally:
            blocker.close()
