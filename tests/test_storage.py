"""WAL + codec unit tests (durability and wire layers)."""
import os

import pytest

from raftsql_tpu.config import MSG_REQ, MSG_RESP
from raftsql_tpu.storage.wal import WAL, wal_exists
from raftsql_tpu.transport.base import (AppendRec, ProposalRec, TickBatch,
                                        VoteRec)
from raftsql_tpu.transport.codec import decode_batch, encode_batch


class TestWAL:
    def test_roundtrip(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"CREATE TABLE t")
        w.append_entry(0, 2, 1, b"INSERT 1")
        w.append_entry(1, 1, 2, b"other group")
        w.set_hardstate(0, 1, 0, 2)
        w.sync()
        w.close()
        assert wal_exists(d)
        groups = WAL.replay(d)
        assert groups[0].log_len == 2
        assert groups[0].entries == [(1, b"CREATE TABLE t"), (1, b"INSERT 1")]
        assert groups[0].hard.term == 1
        assert groups[0].hard.commit == 2
        assert groups[1].entries == [(2, b"other group")]

    def test_conflict_truncation_on_replay(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"a")
        w.append_entry(0, 2, 1, b"b")
        w.append_entry(0, 3, 1, b"c")
        # Overwrite index 2 with a term-2 entry (leader change).
        w.append_entry(0, 2, 2, b"b2")
        w.close()
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"a"), (2, b"b2")]

    def test_same_term_overlap_keeps_suffix(self, tmp_path):
        """A re-accepted duplicate append (same index+term, e.g. a stale
        retransmission) must NOT truncate durably-acked suffix entries —
        same index+term implies same entry (raft log matching)."""
        d = str(tmp_path / "w")
        w = WAL(d)
        for i in range(1, 6):
            w.append_entry(0, i, 1, f"e{i}".encode())
        w.append_entry(0, 3, 1, b"e3")      # stale duplicate of entry 3
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.entries == [(1, f"e{i}".encode()) for i in range(1, 6)]

    def test_torn_tail_dropped(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"good")
        w.close()
        path = os.path.join(d, "wal-0.log")
        with open(path, "ab") as f:
            f.write(b"\x01\x02\x03garbage")
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"good")]

    def test_append_after_reopen(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"one")
        w.close()
        w2 = WAL(d)
        w2.append_entry(0, 2, 1, b"two")
        w2.close()
        groups = WAL.replay(d)
        assert [e[1] for e in groups[0].entries] == [b"one", b"two"]

    def test_empty_replay(self, tmp_path):
        assert WAL.replay(str(tmp_path / "nope")) == {}


class TestSegmentation:
    """Segmented WAL: rotation at sync boundaries, replay concatenation,
    compaction by whole-segment deletion (etcd/wal's segment-dir shape,
    reference raft.go:99-117)."""

    def test_rotation_and_replay(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=256)
        for i in range(1, 41):
            w.append_entry(0, i, 1, f"entry-{i:03d}".encode())
            w.set_hardstate(0, 1, -1, i)
            w.sync()
        w.close()
        segs = sorted(p.name for p in (tmp_path / "w").glob("wal-*.log"))
        assert len(segs) > 2, segs           # actually rotated
        gl = WAL.replay(d)[0]
        assert gl.log_len == 40
        assert [e[1] for e in gl.entries] == [
            f"entry-{i:03d}".encode() for i in range(1, 41)]
        assert gl.hard.commit == 40          # last hardstate wins

    def test_reopen_appends_to_highest_segment(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=128)
        for i in range(1, 11):
            w.append_entry(0, i, 1, b"x" * 20)
            w.sync()
        w.close()
        n_before = len(list((tmp_path / "w").glob("wal-*.log")))
        w2 = WAL(d, segment_bytes=128)
        w2.append_entry(0, 11, 1, b"after-reopen")
        w2.sync()
        w2.close()
        assert len(list((tmp_path / "w").glob("wal-*.log"))) >= n_before
        gl = WAL.replay(d)[0]
        assert gl.log_len == 11
        assert gl.entries[-1] == (1, b"after-reopen")

    def test_compact_deletes_covered_segments(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=256)
        for i in range(1, 41):
            w.append_entry(0, i, 2, f"e{i}".encode())
            w.set_hardstate(0, 2, 0, i)
            w.sync()
        segs0 = sorted((tmp_path / "w").glob("wal-*.log"))
        assert len(segs0) > 3
        deleted = w.compact({0: (30, 2)}, {0: (2, 0, 40)})
        assert deleted > 0
        segs1 = sorted((tmp_path / "w").glob("wal-*.log"))
        assert len(segs1) < len(segs0)
        # Replay after dropping segments: floor honored, suffix intact.
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.start == 30
        assert gl.start_term == 2
        assert gl.log_len == 40
        assert [e[1] for e in gl.entries] == [
            f"e{i}".encode() for i in range(31, 41)]
        assert gl.hard == type(gl.hard)(term=2, vote=0, commit=40)

    def test_compact_never_deletes_uncovered(self, tmp_path):
        """A segment holding entries above the floor must survive, and
        so must everything after it (contiguity)."""
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=256)
        for i in range(1, 41):
            w.append_entry(0, i, 1, f"e{i}".encode())
            w.sync()
        deleted = w.compact({0: (5, 1)}, {0: (1, -1, 40)})
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.start == 5
        assert gl.log_len == 40
        assert [e[1] for e in gl.entries] == [
            f"e{i}".encode() for i in range(6, 41)]

    def test_compact_multi_group_blocks_on_uncompacted_group(self,
                                                             tmp_path):
        """A segment is only deletable when EVERY group's records in it
        are covered; one lagging group pins it."""
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=200)
        for i in range(1, 21):
            w.append_entry(0, i, 1, f"a{i}".encode())
            w.append_entry(1, i, 1, f"b{i}".encode())
            w.sync()
        # Only group 0 has a floor; group 1 pins every segment.
        assert w.compact({0: (15, 1)}, {0: (1, -1, 20),
                                        1: (1, -1, 20)}) == 0
        # Give group 1 a floor too: early segments can go.
        assert w.compact({0: (15, 1), 1: (15, 1)},
                         {0: (1, -1, 20), 1: (1, -1, 20)}) > 0
        w.close()
        groups = WAL.replay(d)
        assert groups[0].start == 15 and groups[1].start == 15
        assert groups[0].log_len == 20 and groups[1].log_len == 20

    def test_compact_marker_replay_keeps_suffix(self, tmp_path):
        """REC_COMPACT drops only the covered prefix (REC_SNAPSHOT also
        drops the suffix — different semantics, both replayed here)."""
        d = str(tmp_path / "w")
        w = WAL(d)
        for i in range(1, 11):
            w.append_entry(0, i, 1, f"e{i}".encode())
        w.mark_compact(0, 4, 1)
        w.append_entry(1, 1, 1, b"x1")
        w.set_snapshot(1, 7, 3)              # install: suffix must go too
        w.close()
        groups = WAL.replay(d)
        assert groups[0].start == 4
        assert [e[1] for e in groups[0].entries] == [
            f"e{i}".encode() for i in range(5, 11)]
        assert groups[1].start == 7
        assert groups[1].entries == []

    def test_dedup_baseline_replay_highest_floor_wins(self, tmp_path):
        """REC_DEDUP replay: the baseline comes back verbatim, and a
        later (higher-floor) record supersedes an earlier one."""
        d = str(tmp_path / "w")
        w = WAL(d, native=False)
        w.append_entry(0, 1, 1, b"e1")
        assert w.set_dedup(0, 1, [(1, 42)])
        w.append_entry(0, 2, 1, b"e2")
        w.append_entry(0, 3, 1, b"e3")
        assert w.set_dedup(0, 2, [(1, 42), (2, 77)])
        w.sync()
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.dedup == (2, [(1, 42), (2, 77)])

    def test_dedup_baseline_survives_segment_unlink(self, tmp_path):
        """The dedup baseline obeys the hard-state survival contract:
        compaction re-asserts it into the active segment before
        unlinking the closed segment that held it — the doomed segment
        may hold the only record scrubbing a compacted-away
        forward-retry duplicate."""
        d = str(tmp_path / "w")
        w = WAL(d, native=False, segment_bytes=256)
        w.append_entry(0, 1, 1, b"first-copy")
        assert w.set_dedup(0, 1, [(1, 42)])
        for i in range(2, 41):
            w.append_entry(0, i, 1, f"e{i}".encode())
            w.set_hardstate(0, 1, -1, i)
            w.sync()
        assert w.compact({0: (30, 1)}, {0: (1, -1, 40)}) > 0
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.start == 30
        assert gl.dedup == (1, [(1, 42)])

    def test_torn_mid_sequence_drops_later_segments(self, tmp_path):
        """A tear in a non-final segment is real corruption: replay keeps
        only the clean prefix, never skips over the damage."""
        d = str(tmp_path / "w")
        w = WAL(d, segment_bytes=64)
        for i in range(1, 9):
            w.append_entry(0, i, 1, b"y" * 30)
            w.sync()
        w.close()
        segs = sorted((tmp_path / "w").glob("wal-*.log"))
        assert len(segs) >= 3
        # Corrupt the middle segment's first record.
        mid = segs[len(segs) // 2]
        blob = bytearray(mid.read_bytes())
        blob[10] ^= 0xFF
        mid.write_bytes(bytes(blob))
        gl = WAL.replay(d)[0]
        assert 0 < gl.log_len < 8


class TestCodec:
    def test_roundtrip(self):
        batch = TickBatch(
            votes=[VoteRec(group=3, type=MSG_REQ, term=7, last_idx=9,
                           last_term=6),
                   VoteRec(group=0, type=MSG_RESP, term=7, granted=True)],
            appends=[
                AppendRec(group=2, type=MSG_REQ, term=5, prev_idx=10,
                          prev_term=4, ent_terms=[5, 5],
                          payloads=[b"INSERT a", b""], commit=9),
                AppendRec(group=2, type=MSG_RESP, term=5, success=True,
                          match=12),
            ],
            proposals=[ProposalRec(group=1, payload=b"CREATE TABLE x")])
        out = decode_batch(encode_batch(batch))
        assert out == batch

    def test_columnar_roundtrip(self):
        import numpy as np

        from raftsql_tpu.transport.base import ColRecs

        def cols(nv, na):
            c = ColRecs()
            if nv:
                c.v_group = np.arange(nv, dtype=np.int32)
                c.v_type = np.full(nv, MSG_REQ, np.int32)
                c.v_term = np.arange(nv, dtype=np.int32) + 3
                c.v_last_idx = np.arange(nv, dtype=np.int32) * 2
                c.v_last_term = np.arange(nv, dtype=np.int32)
                c.v_granted = (np.arange(nv, dtype=np.int32) % 2)
            if na:
                c.a_group = np.arange(na, dtype=np.int32) + 1
                c.a_type = np.full(na, MSG_RESP, np.int32)
                c.a_term = np.arange(na, dtype=np.int32) + 9
                c.a_prev_idx = np.arange(na, dtype=np.int32)
                c.a_prev_term = np.arange(na, dtype=np.int32)
                c.a_commit = np.arange(na, dtype=np.int32) * 3
                c.a_success = (np.arange(na, dtype=np.int32) % 2)
                c.a_match = np.arange(na, dtype=np.int32) + 5
                c.a_seq = np.arange(na, dtype=np.int64) + (1 << 40)
            return c

        for nv, na in ((2, 3), (2, 0), (0, 3)):
            # Mixed with record sections: both must survive together.
            b = TickBatch(appends=[AppendRec(
                group=0, type=MSG_REQ, term=1, ent_terms=[1],
                payloads=[b"x"], seq=4)])
            b.cols = cols(nv, na)
            out = decode_batch(encode_batch(b))
            assert out.appends == b.appends
            assert (out.cols is not None) == bool(nv or na)
            for f in ("v_group", "v_type", "v_term", "v_last_idx",
                      "v_last_term", "v_granted"):
                want = getattr(b.cols, f)
                got = getattr(out.cols, f)
                if nv:
                    assert (np.asarray(got) == np.asarray(want)).all(), f
                else:
                    assert got is None or len(got) == 0
            for f in ("a_group", "a_type", "a_term", "a_prev_idx",
                      "a_prev_term", "a_commit", "a_success", "a_match",
                      "a_seq"):
                want = getattr(b.cols, f)
                got = getattr(out.cols, f)
                if na:
                    assert (np.asarray(got) == np.asarray(want)).all(), f
                    if f == "a_seq":
                        assert got.dtype == np.int64
                else:
                    assert got is None or len(got) == 0

    def test_empty(self):
        assert decode_batch(encode_batch(TickBatch())).empty()

    def test_payload_count_mismatch_asserts(self):
        bad = TickBatch(appends=[AppendRec(
            group=0, type=MSG_REQ, term=1, ent_terms=[1], payloads=[])])
        with pytest.raises(AssertionError):
            encode_batch(bad)

    def test_truncated_columnar_section_is_codec_error(self):
        """A truncated/corrupt trailing ColSection must fail as a codec
        error (struct.error, like the record sections), not a ValueError
        deep inside numpy frombuffer."""
        import struct

        import numpy as np

        from raftsql_tpu.transport.base import ColRecs

        c = ColRecs()
        c.a_group = np.arange(4, dtype=np.int32)
        c.a_type = np.full(4, MSG_RESP, np.int32)
        c.a_term = np.ones(4, np.int32)
        c.a_prev_idx = np.zeros(4, np.int32)
        c.a_prev_term = np.zeros(4, np.int32)
        c.a_commit = np.zeros(4, np.int32)
        c.a_success = np.ones(4, np.int32)
        c.a_match = np.arange(4, dtype=np.int32)
        c.a_seq = np.arange(4, dtype=np.int64)
        blob = encode_batch(TickBatch(cols=c))
        # Drop tail bytes at several depths: mid-a_seq, mid-columns, and
        # right after the declared count.
        for cut in (8, len(blob) // 2, len(blob) - 4):
            with pytest.raises(struct.error):
                decode_batch(blob[:len(blob) - cut])
        # Corrupt count: a huge declared na over an empty remainder.
        head = encode_batch(TickBatch())
        with pytest.raises(struct.error):
            decode_batch(head + struct.pack("<I", 0)
                         + struct.pack("<I", 1 << 28))


class TestEnvelope:
    def test_wrap_unwrap(self):
        from raftsql_tpu.runtime.envelope import unwrap, wrap
        data = wrap(b"INSERT INTO t VALUES (1)")
        pid, payload = unwrap(data)
        assert pid is not None
        assert payload == b"INSERT INTO t VALUES (1)"

    def test_bare_entries_pass_through(self):
        from raftsql_tpu.runtime.envelope import unwrap
        assert unwrap(b"") == (None, b"")

    def test_distinct_ids(self):
        from raftsql_tpu.runtime.envelope import unwrap, wrap
        a, b = wrap(b"x"), wrap(b"x")
        assert a != b
        assert unwrap(a)[1] == unwrap(b)[1] == b"x"

    def test_dedup_window(self):
        from raftsql_tpu.runtime.envelope import DedupWindow
        w = DedupWindow(cap=3)
        assert not w.seen(1)
        assert w.seen(1)           # duplicate caught
        assert not w.seen(2)
        assert not w.seen(3)
        assert not w.seen(4)       # evicts 1
        assert not w.seen(1)       # 1 slid out of the window

    def test_dedup_pairs_upto_and_restore(self):
        """The window snapshots consistently at an applied index: a
        transfer at idx 20 must ship ids applied at or below 20 and NOT
        the live tail beyond it (runtime/node.py InstallSnapshot)."""
        from raftsql_tpu.runtime.envelope import DedupWindow
        w = DedupWindow()
        for idx, pid in ((10, 100), (20, 200), (30, 300)):
            assert not w.seen(pid, idx)
        pairs = w.pairs_upto(20)
        assert pairs == [(10, 100), (20, 200)]
        r = DedupWindow()
        r.restore(pairs)
        assert r.seen(100) and r.seen(200)
        assert not r.seen(300)      # beyond the transfer: not skipped

    def test_snapshot_blob_framing(self):
        from raftsql_tpu.runtime.envelope import (unwrap_snapshot,
                                                  wrap_snapshot)
        pairs = [(5, 111), (9, 2**63 + 7)]
        blob = wrap_snapshot(pairs, b"sm-state-bytes")
        got, sm = unwrap_snapshot(blob)
        assert got == pairs
        assert sm == b"sm-state-bytes"

    def test_snapshot_blob_bare_fallback(self):
        """Blobs without the framing magic are treated as bare SM state
        (back-compat with directly staged SnapshotRecs in tests)."""
        from raftsql_tpu.runtime.envelope import unwrap_snapshot
        assert unwrap_snapshot(b"{}") == (None, b"{}")
        assert unwrap_snapshot(b"") == (None, b"")


class TestPayloadLog:
    def test_try_term_of(self):
        """Floor-safe term lookup for client-thread callers (ReadIndex):
        below-floor and beyond-log return None, never an assert/wrap."""
        from raftsql_tpu.storage.log import PayloadLog
        pl = PayloadLog(1)
        pl.put(0, 1, [b"a", b"b", b"c", b"d"], [1, 1, 2, 2])
        assert pl.try_term_of(0, 0) == 0
        assert pl.try_term_of(0, 3) == 2
        assert pl.try_term_of(0, 5) is None       # beyond the log
        pl.compact(0, 2, 1)
        assert pl.try_term_of(0, 2) == 1          # boundary term kept
        assert pl.try_term_of(0, 1) is None       # below the floor

    def test_try_slice_floor_race_paths(self):
        """try_slice degrades to None when the requested range dips
        below a (concurrently advancing) compaction floor — the atomic
        check-then-slice the send path relies on."""
        from raftsql_tpu.storage.log import PayloadLog
        pl = PayloadLog(1)
        pl.put(0, 1, [b"a", b"b", b"c", b"d", b"e"], [1] * 5)
        assert pl.try_slice(0, 2, 3) == [b"b", b"c", b"d"]
        pl.compact(0, 3, 1)
        assert pl.try_slice(0, 2, 3) is None      # starts below floor
        assert pl.try_slice(0, 4, 2) == [b"d", b"e"]
        # A short tail read returns what exists (caller length-checks),
        # never wraps to the list head.
        assert pl.try_slice(0, 5, 4) == [b"e"]

    def test_try_tail_with_terms_boundary(self):
        """Atomic (prev_term, entries) read for catch-up appends: the
        floor's retained boundary term serves prev_term exactly at the
        edge, and a compacted-away start returns None (InstallSnapshot
        territory)."""
        from raftsql_tpu.storage.log import PayloadLog
        pl = PayloadLog(1)
        pl.put(0, 1, [b"a", b"b", b"c", b"d"], [1, 2, 2, 3])
        prev, ents = pl.try_tail_with_terms(0, 1, 2)
        assert prev == 0 and ents == [(1, b"a"), (2, b"b")]
        pl.compact(0, 2, 2)
        assert pl.try_tail_with_terms(0, 2, 2) is None   # at the floor
        prev, ents = pl.try_tail_with_terms(0, 3, 4)
        assert prev == 2                  # boundary term, not a wrap
        assert ents == [(2, b"c"), (3, b"d")]

    def test_try_accessors_race_live_compactor(self):
        """Hammer try_term_of/try_slice/try_tail_with_terms from a
        reader thread while the owner thread compacts: every result is
        either None or internally consistent (terms match what was
        written at those absolute positions) — no asserts, no wrapped
        negative indexes, no torn (start, lists) reads."""
        import threading
        from raftsql_tpu.storage.log import PayloadLog
        pl = PayloadLog(1)
        N = 400
        pl.put(0, 1, [b"%d" % i for i in range(1, N + 1)],
               list(range(1, N + 1)))        # term i at index i
        stop = threading.Event()
        errors = []

        def reader():
            try:
                while not stop.is_set():
                    for idx in (1, N // 3, N // 2, N):
                        t = pl.try_term_of(0, idx)
                        assert t is None or t == idx, (idx, t)
                        got = pl.try_slice(0, idx, 3)
                        assert got is None \
                            or got == [b"%d" % i for i in
                                       range(idx, min(idx + 3, N + 1))]
                        tail = pl.try_tail_with_terms(0, idx, 2)
                        if tail is not None:
                            prev, ents = tail
                            assert prev == idx - 1
                            assert all(t == i for (t, _), i in
                                       zip(ents, range(idx, idx + 2)))
            except Exception as e:          # pragma: no cover - failure
                errors.append(e)

        th = threading.Thread(target=reader)
        th.start()
        try:
            for floor in range(2, N, 7):
                pl.compact(0, floor, floor)
        finally:
            stop.set()
            th.join(timeout=10)
        assert not errors, errors[0]


class TestNativeWAL:
    """The C++ write path (native/wal.cc) must be byte-identical to the
    Python writer and fully interoperable with Python replay."""

    @pytest.fixture()
    def native(self):
        from raftsql_tpu.native.build import load_native_wal
        lib = load_native_wal()
        if lib is None:
            pytest.skip("native toolchain unavailable")
        return lib

    @staticmethod
    def _write_all(w: WAL) -> None:
        w.append_entry(0, 1, 1, b"CREATE TABLE t")
        w.append_entry(0, 2, 1, b"")
        w.append_entry(7, 1, 3, b"x" * 1000)
        w.set_hardstate(0, 1, -1, 2)
        w.set_hardstate(7, 3, 2, 1)
        w.append_entries([1, 1], [1, 2], [2, 2], [b"batch-a", b"batch-b"])
        w.sync()
        w.close()

    def test_byte_identical_to_python(self, native, tmp_path):
        dn, dp = str(tmp_path / "n"), str(tmp_path / "p")
        wn, wp = WAL(dn, native=True), WAL(dp, native=False)
        assert wn.is_native and not wp.is_native
        self._write_all(wn)
        self._write_all(wp)
        with open(wn.path, "rb") as f:
            n_bytes = f.read()
        with open(wp.path, "rb") as f:
            p_bytes = f.read()
        assert n_bytes == p_bytes
        assert len(n_bytes) > 0

    def test_native_write_python_replay(self, native, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=True)
        self._write_all(w)
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"CREATE TABLE t"), (1, b"")]
        assert groups[0].hard.vote == -1
        assert groups[7].entries == [(3, b"x" * 1000)]
        assert groups[7].hard.vote == 2
        assert groups[1].entries == [(2, b"batch-a"), (2, b"batch-b")]

    def test_reopen_across_backends(self, native, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=True)
        w.append_entry(0, 1, 1, b"one")
        w.sync()
        w.close()
        w2 = WAL(d, native=False)
        w2.append_entry(0, 2, 1, b"two")
        w2.close()
        groups = WAL.replay(d)
        assert [e[1] for e in groups[0].entries] == [b"one", b"two"]


class TestBatchedHardstates:
    def test_batched_hardstates_replay(self, tmp_path):
        """set_hardstates (one native call per tick) must replay exactly
        like per-group set_hardstate, including NO_VOTE (-1) votes."""
        import numpy as np
        d = str(tmp_path / "hsb")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"a")
        w.append_entry(2, 1, 1, b"b")
        w.set_hardstates(np.asarray([0, 2, 5]),
                         np.asarray([3, 4, 9]),
                         np.asarray([-1, 1, 0]),
                         np.asarray([1, 1, 0]))
        w.sync()
        w.close()
        groups = WAL.replay(d)
        h0, h2, h5 = groups[0].hard, groups[2].hard, groups[5].hard
        assert (h0.term, h0.vote, h0.commit) == (3, -1, 1)
        assert (h2.term, h2.vote, h2.commit) == (4, 1, 1)
        assert (h5.term, h5.vote, h5.commit) == (9, 0, 0)

    def test_batched_hardstates_python_fallback(self, tmp_path):
        import numpy as np
        d = str(tmp_path / "hsf")
        w = WAL(d, native=False)
        assert w._lib is None
        w.set_hardstates(np.asarray([1]), np.asarray([7]),
                         np.asarray([-1]), np.asarray([5]))
        w.sync()
        w.close()
        h = WAL.replay(d)[1].hard
        assert (h.term, h.vote, h.commit) == (7, -1, 5)


class TestRangeRecords:
    """Type-5 RANGE records: one framed record per same-term entry run
    (the fused tick's batched WAL form).  Replay must expand a RANGE to
    exactly the entry sequence its per-entry form would produce."""

    def test_roundtrip_equivalent_to_entries(self, tmp_path):
        dr, de = str(tmp_path / "r"), str(tmp_path / "e")
        wr, we = WAL(dr, native=False), WAL(de, native=False)
        datas = [b"a", b"", b"ccc", b"dd", b"e"]
        wr.append_ranges([0, 0, 3], [1, 4, 1], [3, 2, 0], [1, 1, 2],
                         datas)
        for i, d in enumerate(datas):
            we.append_entry(0, i + 1, 1, d)
        wr.close()
        we.close()
        gr, ge = WAL.replay(dr), WAL.replay(de)
        assert gr[0].entries == ge[0].entries
        assert 3 not in gr          # zero-count range writes nothing
        # ...including its segment stats: a phantom (group, start-1)
        # max-index entry would block compaction of the segment for a
        # group that may never earn a durable floor.
        assert 3 not in wr._active_stats.max_idx
        # And the range file is smaller: one header per run, not entry.
        assert os.path.getsize(wr.path) < os.path.getsize(we.path)

    def test_native_byte_identical(self, tmp_path):
        from raftsql_tpu.native.build import load_native_wal
        if load_native_wal() is None:
            pytest.skip("native toolchain unavailable")
        dn, dp = str(tmp_path / "n"), str(tmp_path / "p")
        wn, wp = WAL(dn, native=True), WAL(dp, native=False)
        for w in (wn, wp):
            w.append_ranges([2, 5], [1, 11], [2, 3], [4, 9],
                            [b"x", b"yy", b"", b"zzz", b"w" * 300])
            w.sync()
            w.close()
        with open(wn.path, "rb") as f:
            nb = f.read()
        with open(wp.path, "rb") as f:
            pb = f.read()
        assert nb == pb and len(nb) > 0
        g = WAL.replay(dn)
        assert g[2].entries == [(4, b"x"), (4, b"yy")]
        assert g[5].entries == [(9, b""), (9, b"zzz"), (9, b"w" * 300)]

    def test_range_conflict_truncates(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=False)
        w.append_ranges([0], [1], [4], [1], [b"a", b"b", b"c", b"d"])
        # New-term range overwriting 3.. truncates the old suffix.
        w.append_ranges([0], [3], [2], [2], [b"c2", b"d2"])
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.entries == [(1, b"a"), (1, b"b"), (2, b"c2"), (2, b"d2")]

    def test_range_torn_tail(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=False)
        w.append_ranges([0], [1], [2], [1], [b"good1", b"good2"])
        w.sync()
        w.append_ranges([0], [3], [2], [1], [b"lost1", b"lost2"])
        w.close()
        with open(w.path, "r+b") as f:
            f.truncate(os.path.getsize(w.path) - 3)   # tear mid-record
        gl = WAL.replay(d)[0]
        assert gl.entries == [(1, b"good1"), (1, b"good2")]

    def test_range_segment_stats_gate_compaction(self, tmp_path):
        """_stats_for must see RANGE max indexes: a closed segment whose
        ranges are NOT covered by the floor must survive compact()."""
        d = str(tmp_path / "w")
        w = WAL(d, native=False, segment_bytes=64)
        w.append_ranges([0], [1], [4], [1], [b"a" * 30] * 4)
        w.sync()                       # exceeds 64 bytes -> rotates
        w.append_ranges([0], [5], [2], [1], [b"b" * 30] * 2)
        w.sync()
        assert len(sorted((tmp_path / "w").glob("wal-*.log"))) >= 2
        # Drop the stats cache so compact() re-scans the closed segment
        # from bytes (the _stats_for parse under test).
        w._closed_stats.clear()
        # Floor at 2 does not cover the first segment's range 1-4.
        removed = w.compact({0: (2, 1)}, {0: (1, -1, 0)})
        assert removed == 0
        # Floor at 6 covers both closed ranges.
        removed = w.compact({0: (6, 1)}, {0: (1, -1, 0)})
        assert removed >= 1
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.start == 6 and gl.log_len == 6 and gl.entries == []
