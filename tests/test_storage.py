"""WAL + codec unit tests (durability and wire layers)."""
import os

import pytest

from raftsql_tpu.config import MSG_REQ, MSG_RESP
from raftsql_tpu.storage.wal import WAL, wal_exists
from raftsql_tpu.transport.base import (AppendRec, ProposalRec, TickBatch,
                                        VoteRec)
from raftsql_tpu.transport.codec import decode_batch, encode_batch


class TestWAL:
    def test_roundtrip(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"CREATE TABLE t")
        w.append_entry(0, 2, 1, b"INSERT 1")
        w.append_entry(1, 1, 2, b"other group")
        w.set_hardstate(0, 1, 0, 2)
        w.sync()
        w.close()
        assert wal_exists(d)
        groups = WAL.replay(d)
        assert groups[0].log_len == 2
        assert groups[0].entries == [(1, b"CREATE TABLE t"), (1, b"INSERT 1")]
        assert groups[0].hard.term == 1
        assert groups[0].hard.commit == 2
        assert groups[1].entries == [(2, b"other group")]

    def test_conflict_truncation_on_replay(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"a")
        w.append_entry(0, 2, 1, b"b")
        w.append_entry(0, 3, 1, b"c")
        # Overwrite index 2 with a term-2 entry (leader change).
        w.append_entry(0, 2, 2, b"b2")
        w.close()
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"a"), (2, b"b2")]

    def test_same_term_overlap_keeps_suffix(self, tmp_path):
        """A re-accepted duplicate append (same index+term, e.g. a stale
        retransmission) must NOT truncate durably-acked suffix entries —
        same index+term implies same entry (raft log matching)."""
        d = str(tmp_path / "w")
        w = WAL(d)
        for i in range(1, 6):
            w.append_entry(0, i, 1, f"e{i}".encode())
        w.append_entry(0, 3, 1, b"e3")      # stale duplicate of entry 3
        w.close()
        gl = WAL.replay(d)[0]
        assert gl.entries == [(1, f"e{i}".encode()) for i in range(1, 6)]

    def test_torn_tail_dropped(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"good")
        w.close()
        path = os.path.join(d, "wal-0.log")
        with open(path, "ab") as f:
            f.write(b"\x01\x02\x03garbage")
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"good")]

    def test_append_after_reopen(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        w.append_entry(0, 1, 1, b"one")
        w.close()
        w2 = WAL(d)
        w2.append_entry(0, 2, 1, b"two")
        w2.close()
        groups = WAL.replay(d)
        assert [e[1] for e in groups[0].entries] == [b"one", b"two"]

    def test_empty_replay(self, tmp_path):
        assert WAL.replay(str(tmp_path / "nope")) == {}


class TestCodec:
    def test_roundtrip(self):
        batch = TickBatch(
            votes=[VoteRec(group=3, type=MSG_REQ, term=7, last_idx=9,
                           last_term=6),
                   VoteRec(group=0, type=MSG_RESP, term=7, granted=True)],
            appends=[
                AppendRec(group=2, type=MSG_REQ, term=5, prev_idx=10,
                          prev_term=4, ent_terms=[5, 5],
                          payloads=[b"INSERT a", b""], commit=9),
                AppendRec(group=2, type=MSG_RESP, term=5, success=True,
                          match=12),
            ],
            proposals=[ProposalRec(group=1, payload=b"CREATE TABLE x")])
        out = decode_batch(encode_batch(batch))
        assert out == batch

    def test_empty(self):
        assert decode_batch(encode_batch(TickBatch())).empty()

    def test_payload_count_mismatch_asserts(self):
        bad = TickBatch(appends=[AppendRec(
            group=0, type=MSG_REQ, term=1, ent_terms=[1], payloads=[])])
        with pytest.raises(AssertionError):
            encode_batch(bad)


class TestEnvelope:
    def test_wrap_unwrap(self):
        from raftsql_tpu.runtime.envelope import unwrap, wrap
        data = wrap(b"INSERT INTO t VALUES (1)")
        pid, payload = unwrap(data)
        assert pid is not None
        assert payload == b"INSERT INTO t VALUES (1)"

    def test_bare_entries_pass_through(self):
        from raftsql_tpu.runtime.envelope import unwrap
        assert unwrap(b"") == (None, b"")

    def test_distinct_ids(self):
        from raftsql_tpu.runtime.envelope import unwrap, wrap
        a, b = wrap(b"x"), wrap(b"x")
        assert a != b
        assert unwrap(a)[1] == unwrap(b)[1] == b"x"

    def test_dedup_window(self):
        from raftsql_tpu.runtime.envelope import DedupWindow
        w = DedupWindow(cap=3)
        assert not w.seen(1)
        assert w.seen(1)           # duplicate caught
        assert not w.seen(2)
        assert not w.seen(3)
        assert not w.seen(4)       # evicts 1
        assert not w.seen(1)       # 1 slid out of the window


class TestNativeWAL:
    """The C++ write path (native/wal.cc) must be byte-identical to the
    Python writer and fully interoperable with Python replay."""

    @pytest.fixture()
    def native(self):
        from raftsql_tpu.native.build import load_native_wal
        lib = load_native_wal()
        if lib is None:
            pytest.skip("native toolchain unavailable")
        return lib

    @staticmethod
    def _write_all(w: WAL) -> None:
        w.append_entry(0, 1, 1, b"CREATE TABLE t")
        w.append_entry(0, 2, 1, b"")
        w.append_entry(7, 1, 3, b"x" * 1000)
        w.set_hardstate(0, 1, -1, 2)
        w.set_hardstate(7, 3, 2, 1)
        w.append_entries([1, 1], [1, 2], [2, 2], [b"batch-a", b"batch-b"])
        w.sync()
        w.close()

    def test_byte_identical_to_python(self, native, tmp_path):
        dn, dp = str(tmp_path / "n"), str(tmp_path / "p")
        wn, wp = WAL(dn, native=True), WAL(dp, native=False)
        assert wn.is_native and not wp.is_native
        self._write_all(wn)
        self._write_all(wp)
        with open(wn.path, "rb") as f:
            n_bytes = f.read()
        with open(wp.path, "rb") as f:
            p_bytes = f.read()
        assert n_bytes == p_bytes
        assert len(n_bytes) > 0

    def test_native_write_python_replay(self, native, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=True)
        self._write_all(w)
        groups = WAL.replay(d)
        assert groups[0].entries == [(1, b"CREATE TABLE t"), (1, b"")]
        assert groups[0].hard.vote == -1
        assert groups[7].entries == [(3, b"x" * 1000)]
        assert groups[7].hard.vote == 2
        assert groups[1].entries == [(2, b"batch-a"), (2, b"batch-b")]

    def test_reopen_across_backends(self, native, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d, native=True)
        w.append_entry(0, 1, 1, b"one")
        w.sync()
        w.close()
        w2 = WAL(d, native=False)
        w2.append_entry(0, 2, 1, b"two")
        w2.close()
        groups = WAL.replay(d)
        assert [e[1] for e in groups[0].entries] == [b"one", b"two"]
