"""Dynamic membership tests (raftsql_tpu/membership/).

Four layers, mirroring the subsystem's planes:

  * mask-weighted quorum kernels (ops/quorum.py, ops/commit_scan.py,
    ops/pallas_quorum.py): a FULL voter mask must reproduce the static
    fixed-quorum kernels bit for bit (property-tested across all three
    commit rules), plus the degenerate configs — single voter,
    even-size joint C_old,new, all-learner group that can never elect
    or commit;
  * the host manager (membership/manager.py): change validation, the
    one-in-flight latch, two-phase joint flow, idempotent apply,
    restart restore;
  * the wire/durability planes: conf-entry codec framing, WAL REC_CONF
    baselines surviving replay AND segment compaction;
  * the runtimes: the fused cluster's full add-learner -> promote
    (joint) -> remove lifecycle with per-group configs inside one
    dispatch + restart recovery; the lockstep RaftNode cluster's
    node-replacement story under chaos (SIGKILL a voter, boot a fresh
    machine, add/promote/remove) — digest-reproducible across two runs
    of one plan with zero lost acked writes; TCP-plane crash/restart
    with port rebinding; the admin HTTP API on both serving planes.
"""
import http.client
import json
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.membership import MembershipError, MembershipManager
from raftsql_tpu.ops.commit_scan import (masked_windowed_commit_index,
                                         windowed_commit_index)
from raftsql_tpu.ops.pallas_quorum import (pallas_masked_quorum_commit_index,
                                           pallas_quorum_commit_index)
from raftsql_tpu.ops.quorum import (mask_majority, masked_quorum_commit_index,
                                    masked_quorum_match_index,
                                    masked_vote_win, quorum_commit_index,
                                    quorum_match_index, vote_count)
from raftsql_tpu.storage import fsio
from raftsql_tpu.storage.wal import WAL
from raftsql_tpu.transport.codec import (CONF_KIND_ENTER_JOINT,
                                         CONF_KIND_LEARNER,
                                         CONF_KIND_LEAVE_JOINT,
                                         decode_conf_entry,
                                         encode_conf_entry, is_conf_entry)


def _rand_state(rng, G, P, W):
    """A plausible random per-group consensus snapshot for the commit
    kernels (both kernel families compute the same function of these,
    so consistency beyond index ranges is not required)."""
    log_len = rng.integers(0, W + 1, G)
    match = (rng.random((G, P)) * (log_len[:, None] + 1)).astype(np.int64)
    commit = (rng.random(G) * (log_len + 1)).astype(np.int64)
    ring = rng.integers(1, 4, (G, W))
    term = rng.integers(1, 4, G)
    leader = rng.random(G) < 0.7
    j = lambda x: jnp.asarray(x, jnp.int32)
    return (j(match), j(ring), j(log_len), j(commit), j(term),
            jnp.asarray(leader))


# -- full voter mask == static quorum, bit for bit ---------------------

@pytest.mark.parametrize("P", [3, 4, 5])
def test_masked_kernels_match_static_full_mask(P):
    """The acceptance property: with every slot a voter, all three
    mask-weighted commit rules and the vote tally reproduce the static
    fixed-quorum kernels exactly (CPU point, windowed, AND Pallas)."""
    G, W = 16, 8
    q = P // 2 + 1
    rng = np.random.default_rng(100 + P)
    full = jnp.ones((G, P), bool)
    for trial in range(8):
        match, ring, log_len, commit, term, leader = \
            _rand_state(rng, G, P, W)
        assert (quorum_match_index(match, q)
                == masked_quorum_match_index(match, full)).all()
        want = quorum_commit_index(match, ring, log_len, commit, term,
                                   leader, quorum=q, window=W)
        got = masked_quorum_commit_index(
            match, ring, log_len, commit, term, leader,
            voters=full, voters_joint=full, window=W)
        assert (want == got).all(), trial
        want_w = windowed_commit_index(match, ring, log_len, commit,
                                       term, leader, quorum=q, window=W)
        got_w = masked_windowed_commit_index(
            match, ring, log_len, commit, term, leader,
            voters=full, voters_joint=full, window=W)
        assert (want_w == got_w).all(), trial
        want_p = pallas_quorum_commit_index(
            match, ring, log_len, commit, term, leader,
            quorum=q, window=W)
        got_p = pallas_masked_quorum_commit_index(
            match, ring, log_len, commit, term, leader,
            voters=full, voters_joint=full, window=W)
        assert (want_p == got_p).all(), trial
        votes = jnp.asarray(rng.random((G, P)) < 0.5)
        assert (masked_vote_win(votes, full, full)
                == (vote_count(votes) >= q)).all(), trial


def test_mask_majority_thresholds():
    m = jnp.asarray([[1, 1, 1, 0], [1, 1, 1, 1], [1, 0, 0, 0],
                     [0, 0, 0, 0]], bool)
    assert mask_majority(m).tolist() == [2, 3, 1, 1]


def test_masked_quorum_degenerate_configs():
    """Single voter, even-size joint C_old,new, and the all-learner
    group that must never commit."""
    W = 8
    ring = jnp.ones((3, W), jnp.int32)
    log_len = jnp.asarray([5, 5, 5], jnp.int32)
    commit = jnp.zeros(3, jnp.int32)
    term = jnp.ones(3, jnp.int32)
    leader = jnp.asarray([True, True, True])
    match = jnp.asarray([[5, 0, 0, 0],
                         [5, 4, 1, 0],
                         [5, 5, 5, 5]], jnp.int32)
    # g0: single voter (slot 0) — its own match IS the quorum index.
    # g1: joint config mid-promote of slot 3: C_new {0,1,2,3} needs 3,
    #     C_old {0,1,2} needs 2 — the commit candidate is the MIN of
    #     the two quorum indexes (3rd of [5,4,1,0] = 1; 2nd of [5,4,1]
    #     = 4) = 1.
    # g2: all-learner group: empty masks, no quorum can ever form.
    voters = jnp.asarray([[1, 0, 0, 0],
                          [1, 1, 1, 1],
                          [0, 0, 0, 0]], bool)
    jvot = jnp.asarray([[1, 0, 0, 0],
                        [1, 1, 1, 0],
                        [0, 0, 0, 0]], bool)
    got = masked_quorum_commit_index(
        match, ring, log_len, commit, term, leader,
        voters=voters, voters_joint=jvot, window=W)
    assert got.tolist() == [5, 1, 0]
    got_p = pallas_masked_quorum_commit_index(
        match, ring, log_len, commit, term, leader,
        voters=voters, voters_joint=jvot, window=W)
    assert got_p.tolist() == [5, 1, 0]
    got_w = masked_windowed_commit_index(
        match, ring, log_len, commit, term, leader,
        voters=voters, voters_joint=jvot, window=W)
    assert got_w.tolist() == [5, 1, 0]
    # The all-learner group can never elect either: every vote granted
    # still loses under an empty mask.
    votes = jnp.ones((3, 4), bool)
    win = masked_vote_win(votes, voters, jvot)
    assert win.tolist() == [True, True, False]


# -- conf-entry codec --------------------------------------------------

def test_conf_entry_codec_roundtrip():
    e = encode_conf_entry(CONF_KIND_ENTER_JOINT, 0b1110, 0b0111, 0b0001)
    assert is_conf_entry(e)
    assert decode_conf_entry(e) == (CONF_KIND_ENTER_JOINT, 0b1110,
                                    0b0111, 0b0001)
    # Discriminates against the other payload shapes on the wire.
    for other in (b"", b"SET k v", b"\x01envelope", e + b"x", e[:-1]):
        assert not is_conf_entry(other)
        assert decode_conf_entry(other) is None


# -- the host manager --------------------------------------------------

def test_manager_change_validation_and_one_in_flight():
    mm = MembershipManager(4, 1, initial_voters=(0, 1, 2))
    with pytest.raises(MembershipError):
        mm.make_change(0, "add_learner", 0)     # already a voter
    with pytest.raises(MembershipError):
        mm.make_change(0, "promote", 3)         # not a learner yet
    with pytest.raises(MembershipError):
        mm.make_change(0, "bogus", 3)
    with pytest.raises(MembershipError):
        mm.make_change(0, "add_learner", 9)     # slot out of range
    e = mm.make_change(0, "add_learner", 3)
    assert decode_conf_entry(e)[3] == 0b1000
    with pytest.raises(MembershipError):        # one in flight per group
        mm.make_change(0, "add_learner", 3)
    mm.abort_pending(0)
    mm.make_change(0, "add_learner", 3)         # latch released


def test_manager_joint_promote_flow_and_idempotent_apply():
    mm = MembershipManager(4, 1, initial_voters=(0, 1, 2))
    assert mm.apply(0, 1, mm.make_change(0, "add_learner", 3)) \
        is not None
    c = mm.config(0)
    assert c.learners == 0b1000 and not c.is_joint
    enter = mm.make_change(0, "promote", 3)
    assert mm.apply(0, 2, enter).is_joint
    assert mm.voter_mask(0) == 0b1111           # both masks count
    # While joint: no new change may start, but the leader drives the
    # LEAVE_JOINT (rate-limited re-propose).
    with pytest.raises(MembershipError):
        mm.make_change(0, "remove", 0)
    leave = mm.maybe_leave(0, tick_no=10, cooldown=40)
    assert leave is not None
    assert mm.maybe_leave(0, tick_no=20, cooldown=40) is None
    c = mm.apply(0, 3, leave)
    assert c.voters == 0b1111 and not c.is_joint
    # Replay/redelivery below the applied baseline is a no-op.
    assert mm.apply(0, 2, enter) is None
    assert mm.config(0).voters == 0b1111
    assert mm.conf_changes_applied == 3
    # A voter-less entry is hostile/corrupt: refused.
    assert mm.apply(0, 9, encode_conf_entry(1, 0, 0, 0)) is None


def test_manager_remove_keeps_a_voter_and_counts():
    mm = MembershipManager(3, 2)
    assert mm.counts() == (6, 0)
    mm.apply(0, 1, encode_conf_entry(CONF_KIND_LEAVE_JOINT, 0b001,
                                     0b001, 0b110))
    assert mm.counts() == (4, 2)
    with pytest.raises(MembershipError):
        mm.make_change(0, "remove", 0)          # last voter of g0
    # Group 1 untouched: per-group configs are independent.
    assert mm.config(1).voters == 0b111


def test_manager_restore_baseline_entries_and_pending():
    """WAL-replay restore: REC_CONF baseline, committed entries above
    it re-applied, appended-but-uncommitted ones back in the pending
    list (applied later when their commit passes)."""
    mm = MembershipManager(4, 1, initial_voters=(0, 1, 2))
    e_committed = encode_conf_entry(CONF_KIND_LEARNER, 0b0111, 0b0111,
                                    0b1000)
    e_pending = encode_conf_entry(CONF_KIND_ENTER_JOINT, 0b1111, 0b0111,
                                  0b0000)
    entries = [(1, b"SET k v"), (1, e_committed), (1, e_pending)]
    changed = mm.restore(0, (3, 0, 0b0111, 0b0111, 0b0000), entries,
                         start=4, commit=6)
    assert changed
    c = mm.config(0)
    assert c.index == 6 and c.learners == 0b1000
    assert mm.appended_list(0) == [(7, e_pending)]
    # The pending entry commits later: the live publish path applies it.
    got = mm.take_committed(0, 6, 7)
    assert got == [(7, e_pending)]
    assert mm.apply(0, 7, e_pending).is_joint


def test_manager_note_truncated_discards_clobbered_suffix():
    mm = MembershipManager(3, 1)
    e = encode_conf_entry(CONF_KIND_LEARNER, 0b111, 0b111, 0)
    mm.note_appended(0, 5, e)
    mm.note_appended(0, 8, e)
    mm.note_truncated(0, 6)
    assert mm.appended_list(0) == [(5, e)]
    assert mm.take_committed(0, 0, 4) == []


# -- WAL durability (REC_CONF) -----------------------------------------

def test_wal_conf_baseline_replays(tmp_path):
    with fsio.installed(fsio.StorageFaultInjector()):
        w = WAL(str(tmp_path / "w"))
        w.append_entry(0, 1, 1, b"x")
        assert w.set_conf(0, 5, 0, 0b011, 0b011, 0b100)
        w.set_conf(0, 7, 0, 0b111, 0b111, 0b000)   # last wins
        w.sync()
        w.close()
    logs = WAL.replay(str(tmp_path / "w"))
    assert logs[0].conf == (7, 0, 0b111, 0b111, 0b000)


def test_wal_conf_baseline_survives_compaction(tmp_path):
    """Segment compaction may unlink the segment holding both the conf
    ENTRY and its REC_CONF baseline: compact() must re-assert the
    latest baseline into the active segment (the hard-state survival
    contract) so a restart cannot boot on a stale voter set."""
    with fsio.installed(fsio.StorageFaultInjector()):
        w = WAL(str(tmp_path / "w"), segment_bytes=512)
        for i in range(1, 11):
            w.append_entry(0, i, 1, b"x" * 24)
        w.set_conf(0, 4, 0, 0b011, 0b011, 0b100)
        w.sync()
        for i in range(11, 41):
            w.append_entry(0, i, 1, b"x" * 24)
        w.sync()
        w.compact({0: (30, 1)}, {0: (1, -1, 35)})
        w.close()
    logs = WAL.replay(str(tmp_path / "w"))
    assert logs[0].start == 30
    assert logs[0].conf == (4, 0, 0b011, 0b011, 0b100)


# -- config validation -------------------------------------------------

def test_config_initial_voters_validation():
    RaftConfig(num_peers=4, initial_voters=(0, 2))
    with pytest.raises(ValueError):
        RaftConfig(num_peers=4, initial_voters=())
    with pytest.raises(ValueError):
        RaftConfig(num_peers=4, initial_voters=(0, 4))
    with pytest.raises(ValueError):
        RaftConfig(num_peers=4, initial_voters=(1, 1))


# The PR-4 "mesh ticks lockstep only" regression test
# (MeshLockstepOnlyError) is gone with the error itself: the mesh
# runtime now takes the per-peer timer vector through the sharded step
# (parallel/sharded.py timer_spec).  Skew-on-mesh coverage lives in
# tests/test_mesh.py (lockstep vs skewed elections diverge; mesh-skew
# chaos family digests reproduce) and `make chaos-mesh`.


# -- fused runtime lifecycle -------------------------------------------

def _tick_until(node, pred, limit=600, drain=None):
    for _ in range(limit):
        if pred():
            return True
        node.tick()
        node.publish_flush()
        if drain is not None:
            drain()
    return pred()


def test_fused_membership_lifecycle_and_restart(tmp_path):
    """The fused plane end to end: a 4-slot cluster booted on voters
    {0,1,2} (slot 3 a live spare) adds slot 3 as a learner, promotes
    it through joint consensus (auto LEAVE_JOINT), then removes slot 0
    — group 1 stays on the boot config throughout (per-group device
    configs inside one dispatch) — and a restart recovers the active
    config from the WAL REC_CONF baselines."""
    from raftsql_tpu.chaos.scenarios import _drain_fused_q
    from raftsql_tpu.runtime.fused import FusedClusterNode

    cfg = RaftConfig(num_groups=2, num_peers=4, log_window=32,
                     max_entries_per_msg=4, election_ticks=10,
                     heartbeat_ticks=1, tick_interval_s=0.0,
                     initial_voters=(0, 1, 2))
    node = FusedClusterNode(cfg, str(tmp_path), seed=7)
    node.publish_peers = {0}
    node.enable_membership()
    drain = lambda: _drain_fused_q(node.commit_q(0))
    try:
        assert _tick_until(node, lambda: node.leader_of(0) >= 0
                           and node.leader_of(1) >= 0, drain=drain)
        mm = node.membership
        assert mm.config(0).voters == 0b0111

        node.member_change(0, "add_learner", 3)
        assert _tick_until(node, lambda: mm.config(0).learners == 0b1000,
                           drain=drain)
        # The learner receives AppendEntries: its payload log follows
        # the leader's.
        node.propose_many(0, [b"SET a 1", b"SET b 2"])
        lead = node.leader_of(0)
        assert _tick_until(
            node, lambda: node.plogs[3].length(0)
            == node.plogs[lead].length(0) > 0, drain=drain)

        node.member_change(0, "promote", 3)
        # ENTER_JOINT applies, then the leader auto-proposes the
        # LEAVE_JOINT (rate-limited): the group must come out stable
        # on voters {0,1,2,3} without any further admin op.
        assert _tick_until(node, lambda: mm.config(0).voters == 0b1111
                           and not mm.config(0).is_joint, drain=drain)

        node.member_change(0, "remove", 0)
        assert _tick_until(node, lambda: mm.config(0).voters == 0b1110
                           and not mm.config(0).is_joint, drain=drain)

        # Group 1 never left the boot config: per-group independence.
        assert mm.config(1).voters == 0b0111 and mm.config(1).index == 0
        # The new configuration still commits (quorum of {1,2,3}).
        c0 = int(node._hard[node.leader_of(0), 0, 2])
        node.propose_many(0, [b"SET c 3"])
        assert _tick_until(
            node, lambda: int(node._hard[
                max(node.leader_of(0), 0), 0, 2]) > c0, drain=drain)
        doc = node.members_doc()
        assert doc["groups"]["0"]["voters"] == [1, 2, 3]
        assert doc["groups"]["1"]["voters"] == [0, 1, 2]
        assert node.metrics.conf_changes_applied >= 5
    finally:
        node.stop()

    # Restart: the active per-group configs come back from the WAL.
    node2 = FusedClusterNode(cfg, str(tmp_path), seed=7)
    node2.publish_peers = {0}
    node2.enable_membership()
    try:
        mm2 = node2.membership
        assert mm2.config(0).voters == 0b1110
        assert not mm2.config(0).is_joint
        assert mm2.config(1).voters == 0b0111
    finally:
        node2.stop()


# -- the node-replacement acceptance story -----------------------------

def _replacement_plan(seed=1):
    from raftsql_tpu.chaos import (DropWindow, MemberEvent,
                                   MembershipChaosPlan, NodeBoot,
                                   NodeCrash)
    return MembershipChaosPlan(
        seed=seed, ticks=120, peers=4,
        initial_voters=(0, 1, 2), initial_down=(3,),
        boots=(NodeBoot(30, 3),),
        events=(MemberEvent(34, "add_learner", 3),
                MemberEvent(60, "promote", 3),
                MemberEvent(85, "remove", 1)),
        crashes=(NodeCrash(26, 1, down=10 * 120),),   # permanent SIGKILL
        drops=(DropWindow(45, 60, 0.08),),
        heal_ticks=50, final_voters=(0, 2, 3))


def test_node_replacement_survives_and_reproduces(tmp_path):
    """The acceptance scenario as a tier-1 test: SIGKILL one voter of a
    3-voter cluster, boot a fresh machine into the spare slot, add it
    as a learner, promote it once caught up (joint consensus), remove
    the dead member — under a drop window — with ZERO lost acked
    writes (the runner's durability + log-matching invariants check
    every tick, and the final check proves the post-churn voter set
    still commits).  Two runs of the same plan produce identical
    result digests."""
    from raftsql_tpu.chaos import MembershipChaosRunner

    plan = _replacement_plan()
    r1 = MembershipChaosRunner(plan, str(tmp_path / "a")).run()
    assert r1["crashes"] == 1 and r1["restarts"] == 0   # kill is final
    assert r1["boots"] == 1
    # add_learner + promote + remove, applied on BOTH groups.
    assert r1["member_ops_applied"] == 6
    assert r1["commits"] > 20
    r2 = MembershipChaosRunner(plan, str(tmp_path / "b")).run()
    assert r1["result_digest"] == r2["result_digest"]
    assert r1 == r2


def test_tcp_rebind_crash_restart_catchup(tmp_path):
    """ROADMAP chaos-frontier closure: stop a node under the REAL TCP
    transport (listener closes, port released), rebind the SAME port
    on restart, and require peer reconnect + log catch-up (post-heal
    commit spread bounded by one append batch)."""
    from raftsql_tpu.chaos import (NodeCrash, TcpRebindChaosRunner,
                                   TcpRebindPlan)

    plan = TcpRebindPlan(seed=2, ticks=100,
                         restarts=(NodeCrash(40, -2, down=20),),
                         heal_ticks=60)
    r = TcpRebindChaosRunner(plan, str(tmp_path)).run()
    assert r["stops"] == 1 and r["rebinds"] == 1
    assert r["commits"] > 10


# -- admin HTTP API (both serving planes) ------------------------------

TIMEOUT = 30.0


@pytest.fixture(params=["threaded", "aio"])
def member_server(request, tmp_path):
    """Single live node owning voter slot 0 of a 2-slot cluster (slot 1
    is provisioned spare capacity): self-elects with quorum {0} and can
    legally add/remove slot 1 as a learner."""
    from raftsql_tpu.api.aio import AioSQLServer
    from raftsql_tpu.api.http import SQLServer
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import LoopbackHub, \
        LoopbackTransport

    cfg = RaftConfig(num_groups=2, num_peers=2, tick_interval_s=0.005,
                     log_window=64, max_entries_per_msg=4,
                     initial_voters=(0,))
    pipe = RaftPipe.create(1, 2, cfg, LoopbackTransport(LoopbackHub()),
                           data_dir=str(tmp_path / "raftsql-1"))
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        str(tmp_path / f"m-g{g}.db")), pipe, num_groups=2)
    srv_cls = SQLServer if request.param == "threaded" else AioSQLServer
    srv = srv_cls(0, rdb, host="127.0.0.1", timeout_s=TIMEOUT)
    srv.start()
    yield srv
    srv.stop()
    rdb.close()


def _req(srv, method, path, body=b""):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request(method, path, body=body)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _members(srv):
    status, data = _req(srv, "GET", "/members")
    assert status == 200
    return json.loads(data)


def test_members_api_read_change_and_validation(member_server):
    srv = member_server
    doc = _members(srv)
    assert doc["num_peers"] == 2
    assert doc["groups"]["0"]["voters"] == [0]
    assert doc["groups"]["0"]["learners"] == []

    # Admin write: add slot 1 as a learner of group 0; the change is a
    # log entry applied at commit — poll the read side.  Changes are
    # leader-only (421 + retry hint until the node self-elects).
    deadline = time.monotonic() + TIMEOUT
    while True:
        status, data = _req(srv, "POST", "/members", json.dumps(
            {"group": 0, "op": "add_learner", "peer": 1}).encode())
        if status != 421 or time.monotonic() >= deadline:
            break
        time.sleep(0.02)
    assert status == 200, data
    deadline = time.monotonic() + TIMEOUT
    while time.monotonic() < deadline:
        if _members(srv)["groups"]["0"]["learners"] == [1]:
            break
        time.sleep(0.02)
    doc = _members(srv)
    assert doc["groups"]["0"]["learners"] == [1]
    assert doc["groups"]["1"]["learners"] == []     # per-group config

    # Validation errors surface as 400s.
    for bad in ({"group": 0, "op": "remove", "peer": 0},   # last voter
                {"group": 0, "op": "promote", "peer": 0},  # not learner
                {"group": 0, "op": "bogus", "peer": 1},
                {"group": 9, "op": "add_learner", "peer": 1}):
        status, _ = _req(srv, "POST", "/members",
                         json.dumps(bad).encode())
        assert status == 400, bad

    # And back out: remove the learner.
    status, _ = _req(srv, "POST", "/members", json.dumps(
        {"group": 0, "op": "remove_learner", "peer": 1}).encode())
    assert status == 200
    deadline = time.monotonic() + TIMEOUT
    while time.monotonic() < deadline:
        if _members(srv)["groups"]["0"]["learners"] == []:
            break
        time.sleep(0.02)
    assert _members(srv)["groups"]["0"]["learners"] == []


# -- slow sweeps -------------------------------------------------------

@pytest.mark.slow
def test_membership_seed_sweep(tmp_path):
    """Acceptance-scale sweep: seeded generator plans (permanent kill,
    fresh boot, add/promote/remove under drops + a transient crash),
    each seed run twice and digest-compared."""
    from raftsql_tpu.chaos import (MembershipChaosRunner,
                                   generate_membership_plan)
    for seed in range(3):
        plan = generate_membership_plan(seed)
        r1 = MembershipChaosRunner(plan,
                                   str(tmp_path / f"s{seed}a")).run()
        r2 = MembershipChaosRunner(plan,
                                   str(tmp_path / f"s{seed}b")).run()
        assert r1["result_digest"] == r2["result_digest"], seed
        assert r1["member_ops_applied"] == 6, seed


@pytest.mark.slow
def test_tcp_rebind_seed_sweep(tmp_path):
    from raftsql_tpu.chaos import (TcpRebindChaosRunner,
                                   generate_tcp_rebind_plan)
    for seed in range(3):
        plan = generate_tcp_rebind_plan(seed)
        r = TcpRebindChaosRunner(plan, str(tmp_path / f"s{seed}")).run()
        assert r["rebinds"] == 2, seed
        assert r["commits"] > 20, seed
