"""Concurrency stress: compact / InstallSnapshot / catch-up / publish
hammered concurrently on a live cluster (SURVEY.md §5.2; the reference
relies on Go's race detector being *available* but never enables it,
reference Makefile:14-15 — here the interleavings are driven on purpose).

Shape: a 3-node loopback cluster in snapshot-resume mode with a tiny log
window and aggressive WAL compaction, three concurrent proposer threads,
and a chaos thread that repeatedly partitions node 3 long enough for the
survivors to commit + compact PAST its position — so every heal forces
either host-mediated catch-up or a full InstallSnapshot — while publish
and the per-tick WAL phase run on the node threads throughout.
"""
import os
import threading
import time

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.loopback import (FaultPlan, LoopbackHub,
                                            LoopbackTransport)

TICK = 0.002
TIMEOUT = 60.0
N = 3
G = 4


def test_compact_install_catchup_publish_stress(tmp_path):
    faults = FaultPlan()
    hub = LoopbackHub(faults=faults)
    cfg = RaftConfig(num_groups=G, num_peers=N, tick_interval_s=TICK,
                     election_ticks=10, log_window=16,
                     max_entries_per_msg=4)
    dbs = []
    for i in range(N):
        pipe = RaftPipe.create(
            i + 1, N, cfg, LoopbackTransport(hub),
            data_dir=os.path.join(str(tmp_path), f"raftsql-{i + 1}"))
        dbs.append(RaftDB(
            lambda g, i=i: SQLiteStateMachine(
                os.path.join(str(tmp_path), f"db-{i}-{g}.db"), resume=True),
            pipe, num_groups=G, resume=True,
            compact_every=20, compact_keep=16))
    try:
        for g in range(G):
            assert dbs[0].propose("CREATE TABLE t (v text)",
                                  group=g).wait(TIMEOUT) is None

        stop = threading.Event()
        acked = [0] * N
        failed = []

        def proposer(i):
            k = 0
            while not stop.is_set():
                g = k % G
                fut = dbs[i].propose(
                    f"INSERT INTO t (v) VALUES ('n{i}k{k}')", group=g)
                try:
                    err = fut.wait(TIMEOUT)
                except TimeoutError as e:
                    # A hung ack is exactly what this test hunts — it
                    # must FAIL the test, not die in a daemon thread.
                    failed.append((i, k, e))
                    return
                if err is None:
                    acked[i] += 1
                elif "snapshot" not in str(err):
                    # "superseded by snapshot install" is the documented
                    # retriable outcome for proposals whose commit rode a
                    # state transfer; anything else is a real failure.
                    failed.append((i, k, err))
                k += 1

        threads = [threading.Thread(target=proposer, args=(i,), daemon=True)
                   for i in range(N)]
        for t in threads:
            t.start()

        # Chaos: partition node 3, let the survivors commit + compact far
        # past it, heal, repeat.  Each heal exercises catch-up and (once
        # the WAL floor passes node 3's log) InstallSnapshot, racing the
        # proposers' publish/WAL traffic the whole time.  The hold is
        # PROGRESS-based (survivors must out-run node 3 past the ring +
        # compaction keep), not wall-clock — a CPU-starved run otherwise
        # under-delivers the lag the hard paths need.
        def min_gap() -> int:
            a0 = dbs[0].pipe.node._applied
            a2 = dbs[2].pipe.node._applied
            return int((a0 - a2).min())

        for _ in range(3):
            faults.isolate(3, range(1, N + 1))
            t0 = time.monotonic()
            while min_gap() < 48 and time.monotonic() - t0 < 10.0:
                time.sleep(0.1)
            faults.heal()
            t0 = time.monotonic()
            while min_gap() > 4 and time.monotonic() - t0 < 6.0:
                time.sleep(0.1)

        stop.set()
        for t in threads:
            t.join(TIMEOUT)
        assert not failed, failed[:3]
        assert sum(acked) > 30, f"too few acks for a stress run: {acked}"

        # Quiesce, then require convergence: every node's replica of every
        # group reports the same row count (stale reads poll-retried, as
        # in reference raftsql_test.go:159-170).
        deadline = time.monotonic() + TIMEOUT
        for g in range(G):
            want = None
            while True:
                counts = [db.query("SELECT count(*) FROM t", group=g)
                          for db in dbs]
                if len(set(counts)) == 1:
                    want = counts[0]
                    break
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"group {g} diverged after stress: {counts}")
                time.sleep(0.05)
            assert want.startswith("|") and int(want.strip("|\n")) >= 1
        installs = sum(db.pipe.node.metrics.snapshots_installed
                       for db in dbs if db.pipe is not None)
        catchups = sum(db.pipe.node.metrics.catchup_appends
                       for db in dbs if db.pipe is not None)
        compactions = sum(db.pipe.node.metrics.compactions
                          for db in dbs if db.pipe is not None)
        # The point of the chaos schedule: the hard paths actually ran.
        assert compactions > 0, "stress never compacted"
        assert installs + catchups > 0, \
            "stress never exercised catch-up or InstallSnapshot"
    finally:
        for db in dbs:
            try:
                db.close()
            except Exception:
                pass
