"""Property tests: raft safety invariants under adversarial schedules.

SURVEY.md §4 notes the reference has no property/fuzz testing of its
consensus core (it trusts vendored etcd/raft).  The batched JAX core makes
this cheap: deterministic simulated time, seeded message loss and
partitions, invariants checked over the full [P, G] state every tick.

Invariants (raft paper §5.4):
  * Election Safety   — at most one leader per term per group.
  * Log Matching      — if two logs hold an entry with the same index and
                        term, the logs are identical up through that index.
  * Leader Completeness / State Machine Safety — committed (index, term)
                        pairs are never contradicted later on any peer.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.core.cluster import (cluster_step_jit, empty_cluster_inbox,
                                      init_cluster_state)
from raftsql_tpu.core.state import term_at
from raftsql_tpu.transport.faults import partition_peer, random_drop


def window_terms(states, cfg):
    """[P, G, L] materialized log terms (L = max log_len), 0 beyond len.

    Reads the ring when present; with keep_ring=False (the benchmark
    configuration, [G, 1] stub) reads the O(K) transition table instead —
    the engine's own read path."""
    from raftsql_tpu.core.state import term_at_tbl

    ringless = not cfg.keep_ring
    L = int(np.asarray(states.log_len).max())
    if L == 0:
        return np.zeros((cfg.num_peers, cfg.num_groups, 0), np.int64)
    idx = jnp.arange(1, L + 1, dtype=jnp.int32)[None, :]
    out = []
    for p in range(cfg.num_peers):
        idxb = jnp.broadcast_to(idx, (cfg.num_groups, L))
        if ringless:
            t = term_at_tbl(states.tbl_pos[p], states.tbl_term[p],
                            states.log_len[p], idxb)
        else:
            t = term_at(states.log_term[p], states.log_len[p], idxb,
                        cfg.log_window)
        out.append(np.asarray(t))
    return np.stack(out)


class InvariantChecker:
    def __init__(self, cfg):
        self.cfg = cfg
        # Per (group): history of leaders per term, and the highest
        # committed prefix observed with its terms.
        self.leader_of_term = {}             # (g, term) -> peer
        self.committed = {}                  # g -> list of terms, 1-based

    def check(self, states, t):
        cfg = self.cfg
        role = np.asarray(states.role)
        term = np.asarray(states.term)
        commit = np.asarray(states.commit)
        log_len = np.asarray(states.log_len)
        terms = window_terms(states, cfg)    # [P, G, L]
        ringless = not cfg.keep_ring
        if ringless:
            # The table forgets positions below its floor (the ring
            # path computes its own floor from log_len - W).
            from raftsql_tpu.core.state import tbl_floor
            tblf = np.asarray(tbl_floor(states.tbl_pos, states.log_len))
        else:
            self.check_table_matches_ring(states, t)

        for g in range(cfg.num_groups):
            # Election safety.
            for p in range(cfg.num_peers):
                if role[p, g] == LEADER:
                    prev = self.leader_of_term.setdefault((g, term[p, g]), p)
                    assert prev == p, (
                        f"t={t} g={g}: two leaders ({prev},{p}) "
                        f"in term {term[p, g]}")
            # Log matching over committed prefixes + leader completeness.
            hist = self.committed.setdefault(g, [])
            for p in range(cfg.num_peers):
                c = int(commit[p, g])
                assert c <= log_len[p, g]
                pterms = terms[p, g, :c].tolist()
                # The device ring only holds the last W entries: position
                # i's slot is recycled by position i+W once log_len passes
                # it, so terms read for positions <= log_len - W are
                # aliased garbage, not engine state.  The ringless config
                # reads the table, which forgets positions below its
                # floor instead.  Check (and extend history) only over
                # observable positions.
                if ringless:
                    floor = max(0, int(tblf[p, g]) - 1)
                else:
                    floor = max(0, int(log_len[p, g]) - cfg.log_window)
                overlap = min(len(hist), c)
                assert hist[floor:overlap] == pterms[floor:overlap], (
                    f"t={t} g={g} p={p}: committed prefix diverged: "
                    f"{hist[floor:overlap]} vs {pterms[floor:overlap]}")
                if c > len(hist) and len(hist) >= floor:
                    self.committed[g] = hist + pterms[len(hist):c]

    def check_table_matches_ring(self, states, t):
        """The O(K) term-transition table (the step's read path) must agree
        with the O(W) ring (its write path) on every position BOTH can
        still observe: above the table floor and inside the ring window."""
        from raftsql_tpu.core.state import tbl_floor, term_at_tbl

        cfg = self.cfg
        L = int(np.asarray(states.log_len).max())
        if L == 0:
            return
        idx = jnp.arange(1, L + 1, dtype=jnp.int32)[None, :]
        idxb = jnp.broadcast_to(idx, (cfg.num_groups, L))
        log_len = np.asarray(states.log_len)
        floor = np.asarray(tbl_floor(states.tbl_pos, states.log_len))
        for p in range(cfg.num_peers):
            ring = np.asarray(term_at(states.log_term[p], states.log_len[p],
                                      idxb, cfg.log_window))
            tbl = np.asarray(term_at_tbl(states.tbl_pos[p],
                                         states.tbl_term[p],
                                         states.log_len[p], idxb))
            for g in range(cfg.num_groups):
                lo = max(int(floor[p, g]),
                         int(log_len[p, g]) - cfg.log_window + 1, 1)
                hi = int(log_len[p, g])
                a, b = tbl[g, lo - 1:hi], ring[g, lo - 1:hi]
                assert (a == b).all(), (
                    f"t={t} g={g} p={p}: table/ring term divergence in "
                    f"[{lo},{hi}]: {a.tolist()} vs {b.tolist()}")


def run_chaos(cfg, ticks, p_drop=0.0, partition_schedule=(), prop_rate=0.3,
              seed=0):
    """Run a cluster under chaos, checking invariants every tick."""
    states = init_cluster_state(cfg)
    inboxes = empty_cluster_inbox(cfg)
    checker = InvariantChecker(cfg)
    rng = np.random.default_rng(seed)
    key = jax.random.PRNGKey(seed + 1)
    for t in range(ticks):
        if p_drop > 0:
            key, sub = jax.random.split(key)
            inboxes = random_drop(inboxes, sub, p_drop)
        for (t0, t1, peer) in partition_schedule:
            if t0 <= t < t1:
                inboxes = partition_peer(inboxes, peer)
        props = jnp.asarray(
            (rng.random((cfg.num_peers, cfg.num_groups)) < prop_rate)
            .astype(np.int32))
        states, inboxes, _ = cluster_step_jit(cfg, states, inboxes, props)
        checker.check(states, t)
    return states, checker


CFG = dict(num_groups=4, num_peers=3, log_window=64, max_entries_per_msg=4,
           election_ticks=10, heartbeat_ticks=1)


class TestSafetyUnderChaos:
    def test_invariants_no_faults(self):
        cfg = RaftConfig(seed=1, **CFG)
        states, _ = run_chaos(cfg, 120, seed=1)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()

    @pytest.mark.parametrize("p_drop,seed", [(0.1, 2), (0.3, 3), (0.5, 4)])
    def test_invariants_under_message_loss(self, p_drop, seed):
        cfg = RaftConfig(seed=seed, **CFG)
        states, _ = run_chaos(cfg, 150, p_drop=p_drop, seed=seed)
        if p_drop <= 0.3:   # liveness only asserted under moderate loss
            assert (np.asarray(states.commit).max(axis=0) > 0).all()

    def test_invariants_under_rolling_partitions(self):
        cfg = RaftConfig(seed=5, **CFG)
        sched = [(30, 60, 0), (70, 100, 1), (110, 140, 2)]
        states, _ = run_chaos(cfg, 160, partition_schedule=sched, seed=5)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()

    def test_invariants_five_peers_loss_and_partition(self):
        cfg = RaftConfig(seed=6, num_groups=2, num_peers=5, log_window=64,
                         max_entries_per_msg=4)
        states, _ = run_chaos(cfg, 150, p_drop=0.15,
                              partition_schedule=[(40, 80, 2)], seed=6)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()

    def test_invariants_long_horizon_mixed_faults(self):
        """250 ticks of drops + a flapping partition: long enough for
        multiple prevote probe cycles, pipelined backlogs, and reject
        walkbacks to interleave (the round-3 additions)."""
        cfg = RaftConfig(seed=8, **CFG)
        sched = [(40, 70, 1), (90, 120, 0), (140, 170, 1), (190, 220, 2)]
        states, _ = run_chaos(cfg, 250, p_drop=0.2,
                              partition_schedule=sched, seed=8)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()

    def test_invariants_asymmetric_loss(self):
        """One peer's outbound messages drop per-message at 60% while
        inbound flow stays clean — the shape that provokes stale-leader/
        stale-term traffic and the inflight-cap resend path."""
        from raftsql_tpu.transport.faults import drop_messages

        cfg = RaftConfig(seed=9, **CFG)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        checker = InvariantChecker(cfg)
        rng = np.random.default_rng(9)
        key = jax.random.PRNGKey(10)
        shape = inboxes.v_type.shape        # [P_dst, G, P_src]
        for t in range(200):
            if 40 <= t < 160:
                key, sub = jax.random.split(key)
                drop = jnp.zeros(shape, bool).at[:, :, 1].set(
                    jax.random.bernoulli(sub, 0.6, shape[:-1]))
                inboxes = drop_messages(inboxes, drop)
            props = jnp.asarray(
                (rng.random((cfg.num_peers, cfg.num_groups)) < 0.3)
                .astype(np.int32))
            states, inboxes, _ = cluster_step_jit(cfg, states, inboxes,
                                                  props)
            checker.check(states, t)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()

    def test_committed_entries_survive_leader_churn(self):
        # Partition whoever leads group 0, twice; committed data must persist.
        cfg = RaftConfig(seed=7, **CFG)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        checker = InvariantChecker(cfg)
        zero = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)
        t = 0

        def tick(props, fault_peer=None):
            nonlocal states, inboxes, t
            if fault_peer is not None:
                inboxes = partition_peer(inboxes, fault_peer)
            states, inboxes, _ = cluster_step_jit(cfg, states, inboxes, props)
            checker.check(states, t)
            t += 1

        for _ in range(60):
            tick(zero)
        for round_ in range(2):
            role = np.asarray(states.role)
            leader = int(role[:, 0].argmax())
            props = jnp.asarray((role == LEADER).astype(np.int32) * 2)
            tick(props)
            commit_before = int(np.asarray(states.commit)[:, 0].max())
            for _ in range(40):
                tick(zero, fault_peer=leader)
            for _ in range(40):
                tick(zero)
            commit_after = int(np.asarray(states.commit)[:, 0].max())
            assert commit_after >= commit_before, "committed data lost"


class TestRinglessChaos:
    def test_invariants_ringless_config(self):
        """The benchmark's keep_ring=False configuration must satisfy the
        same safety invariants under drops + partitions — the checker
        reads terms through the engine's own transition table."""
        cfg = RaftConfig(seed=17, keep_ring=False, **CFG)
        sched = [(30, 60, 2), (80, 110, 1)]
        states, _ = run_chaos(cfg, 180, p_drop=0.15,
                              partition_schedule=sched, seed=17)
        assert states.log_term.shape[-1] == 1
        assert (np.asarray(states.commit).max(axis=0) > 0).all()


class TestLargeGChaos:
    """Chaos at the BENCH regime's shape — G=2048, ringless + point
    commit rule — which previously executed only inside bench.py with
    zero invariant coverage (VERDICT r3 weak #5).  Full-width vectorized
    same-tick election safety every tick; cross-tick election safety and
    committed-prefix (Log Matching / Leader Completeness) on a random
    16-group sample per tick so runtime stays bounded."""

    def test_invariants_large_g_sampled(self):
        from raftsql_tpu.core.state import tbl_floor, term_at_tbl

        G, P, SAMPLE = 2048, 3, 16
        cfg = RaftConfig(seed=31, num_groups=G, num_peers=P,
                         log_window=64, max_entries_per_msg=8,
                         election_ticks=10, heartbeat_ticks=1,
                         keep_ring=False, commit_rule="point")
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        rng = np.random.default_rng(31)
        key = jax.random.PRNGKey(32)
        leader_of_term = {}                   # (g, term) -> peer
        committed = {}                        # g -> committed term history
        for t in range(110):
            if 30 <= t < 60:
                inboxes = partition_peer(inboxes, 1)
            elif t >= 60:
                key, sub = jax.random.split(key)
                inboxes = random_drop(inboxes, sub, 0.1)
            props = jnp.asarray(rng.integers(0, 3, (P, G)).astype(np.int32))
            states, inboxes, _ = cluster_step_jit(cfg, states, inboxes,
                                                  props)
            role = np.asarray(states.role)
            term = np.asarray(states.term)
            lead = role == LEADER
            # Same-tick election safety over ALL 2048 groups, vectorized.
            for p1 in range(P):
                for p2 in range(p1 + 1, P):
                    both = lead[p1] & lead[p2] & (term[p1] == term[p2])
                    assert not both.any(), (
                        f"t={t}: two live leaders at one term, groups "
                        f"{np.nonzero(both)[0][:5].tolist()}")
            # Sampled deep checks.
            gs = rng.choice(G, SAMPLE, replace=False)
            gs_j = jnp.asarray(np.sort(gs))
            gs_n = np.sort(gs).tolist()
            commit = np.asarray(states.commit)
            log_len = np.asarray(states.log_len)
            floor = np.asarray(tbl_floor(states.tbl_pos, states.log_len))
            L = int(log_len[:, gs_n].max())
            terms_s = None
            if L:
                idxb = jnp.broadcast_to(
                    jnp.arange(1, L + 1, dtype=jnp.int32)[None],
                    (SAMPLE, L))
                terms_s = np.stack([np.asarray(term_at_tbl(
                    states.tbl_pos[p, gs_j], states.tbl_term[p, gs_j],
                    states.log_len[p, gs_j], idxb)) for p in range(P)])
            for si, g in enumerate(gs_n):
                for p in range(P):
                    if lead[p, g]:
                        prev = leader_of_term.setdefault(
                            (g, int(term[p, g])), p)
                        assert prev == p, (
                            f"t={t} g={g}: leaders {prev} and {p} at "
                            f"term {term[p, g]}")
                hist = committed.setdefault(g, [])
                for p in range(P):
                    c = int(commit[p, g])
                    assert c <= log_len[p, g]
                    pterms = terms_s[p, si, :c].tolist() if c else []
                    flo = max(0, int(floor[p, g]) - 1)
                    overlap = min(len(hist), c)
                    assert hist[flo:overlap] == pterms[flo:overlap], (
                        f"t={t} g={g} p={p}: committed prefix diverged")
                    if c > len(hist) and len(hist) >= flo:
                        committed[g] = hist + pterms[len(hist):c]
                        hist = committed[g]
        assert (np.asarray(states.commit).max(axis=0) > 0).all()


class TestJittedScheduleChaos:
    """The fault masks composed INSIDE one jitted program: a lax.scan
    over simulated time applies random_drop + partition_peer per tick
    from a precomputed multi-tick schedule, the whole adversarial run
    is ONE dispatch, and the stacked per-tick outputs are checked for
    election safety and commit monotonicity on the host.  Previously
    the masks were only unit-tested host-side (applied between
    dispatches); this pins down that they compose under jit/scan — the
    DrJAX-style batched-schedule shape the chaos harness leans on."""

    def test_jitted_schedule_election_safety_and_commit_monotonic(self):
        import functools

        from raftsql_tpu.core.cluster import cluster_step

        cfg = RaftConfig(seed=41, **CFG)
        T = 160
        tt = np.arange(T)
        part = np.full(T, -1, np.int32)       # -1 = no peer partitioned
        part[40:70] = 1
        part[100:130] = 0
        p_drop = np.where((tt >= 60) & (tt < 140), 0.15, 0.0) \
            .astype(np.float32)
        rng = np.random.default_rng(43)
        props = rng.integers(
            0, 2, (T, cfg.num_peers, cfg.num_groups)).astype(np.int32)
        keys = jax.random.split(jax.random.PRNGKey(44), T)

        @functools.partial(jax.jit, static_argnums=0)
        def run(cfg, states, inboxes, keys, part, p_drop, props):
            def body(carry, xs):
                st, ib = carry
                key, pp, pd, pr = xs
                ib = random_drop(ib, key, pd)
                ib = partition_peer(ib, pp)
                st, ib, info = cluster_step(cfg, st, ib, pr)
                return (st, ib), (info.role, info.term, info.commit)

            _, out = jax.lax.scan(
                body, (states, inboxes),
                (keys, jnp.asarray(part), jnp.asarray(p_drop),
                 jnp.asarray(props)))
            return out

        roles, terms, commits = jax.device_get(run(
            cfg, init_cluster_state(cfg), empty_cluster_inbox(cfg),
            keys, part, p_drop, props))
        # Election safety across the whole schedule (cross-tick).
        leader_of_term = {}
        lead = roles == LEADER
        for t in range(T):
            for p, g in zip(*np.nonzero(lead[t])):
                key = (int(g), int(terms[t, p, g]))
                prev = leader_of_term.setdefault(key, int(p))
                assert prev == int(p), (
                    f"t={t} g={g}: leaders {prev} and {p} at term "
                    f"{key[1]}")
        # Commit monotonicity per (peer, group) along simulated time.
        assert (np.diff(commits.astype(np.int64), axis=0) >= 0).all()
        # Liveness: the partitions healed and commits flowed.
        assert (commits[-1].max(axis=0) > 0).all()


class TestFivePeerChaos:
    def test_invariants_five_peers(self):
        """P=5 (quorum 3) under drops and a rolling partition: the quorum
        math, vote tallies, and message slots must hold invariants at the
        wider peer axis too (the reference's canonical cluster is 3-node,
        Procfile:2-4; 5-node is the raft paper's other standard size)."""
        cfg = RaftConfig(num_groups=3, num_peers=5, log_window=64,
                         max_entries_per_msg=4, election_ticks=10,
                         heartbeat_ticks=1, seed=23)
        sched = [(40, 70, 0), (100, 130, 4)]
        states, _ = run_chaos(cfg, 180, p_drop=0.15,
                              partition_schedule=sched, seed=23)
        assert (np.asarray(states.commit).max(axis=0) > 0).all()
