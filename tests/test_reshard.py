"""Elastic keyspace (raftsql_tpu/reshard/): router, journal, fork,
coordinator, and the live serving plane.

The reshard plane's whole safety story reduces to three claims, and
this file pins each at the layer where it is decided:

  1. The router never holds truth the logs don't — `fold_records`
     rebuilds (keymap, active-verb) from journal entries in any order
     with duplicates, and a coordinator rebuilt mid-verb either
     resumes forward (copy fence journaled) or aborts cleanly (fence
     missing), never half-applies a flip.
  2. A snapshot fork is a partition — `fork_by_slots` yields two
     standalone SQLite files whose keyed-row union is exactly the
     source and whose intersection is empty, with the meta tables
     (applied floor, journal) carried on BOTH sides.
  3. Consumers fail closed on the mapping epoch — a /kv request
     pinned to a stale epoch is refused with the current mapping
     attached (409), frozen-slot intake is refused up front (503),
     the api client adopts only strictly newer mappings, and an shm
     worker whose cached epoch lags the publisher's falls back to the
     ring path until it revalidates.

The end-to-end test drives a real split and merge through POST
/reshard on a live single-node cluster (both serving planes) and then
re-folds the replicated journal into a FRESH plane to prove the
router state is fully log-derived.
"""
import http.client
import json
import sqlite3
import tempfile
import time

import pytest

from raftsql_tpu.reshard.coordinator import (ReshardCoordinator,
                                             ReshardRefused)
from raftsql_tpu.reshard.fork import fork_state_machine
from raftsql_tpu.reshard.journal import (decode_rdel, decode_record,
                                         encode_rdel, encode_record,
                                         fold_records)
from raftsql_tpu.reshard.keymap import KeyMap, slot_of

TIMEOUT = 30.0


# -- keymap -----------------------------------------------------------------


def test_slot_of_stable_and_bounded():
    assert all(0 <= slot_of(f"k{i}", 16) < 16 for i in range(200))
    assert slot_of("alpha", 16) == slot_of("alpha", 16)
    # The ring spreads keys: no single slot swallows the keyspace.
    slots = {slot_of(f"k{i}", 16) for i in range(200)}
    assert len(slots) > 8


def test_keymap_move_retire_epoch():
    km = KeyMap.initial(2, 8)
    assert km.epoch == 0 and km.slots == [0, 1] * 4
    assert km.live_groups() == [0, 1]
    assert km.move([0, 2], 1) == 1
    assert km.slots_of(0) == [4, 6]
    assert km.slots_of(1) == [0, 1, 2, 3, 5, 7]
    # Retiring a group that still owns slots is refused.
    with pytest.raises(ValueError):
        km.retire(0)
    km.move([4, 6], 1)
    assert km.retire(0) == 3
    assert km.live_groups() == [1] and 0 in km.retired
    # A later move back ONTO the retired group revives it.
    km.move([0], 0)
    assert 0 not in km.retired and km.live_groups() == [0, 1]


def test_keymap_freeze_is_not_a_routing_change():
    km = KeyMap.initial(2, 8)
    km.freeze([3, 5])
    assert km.epoch == 0          # hygiene, not a routing change
    assert km.frozen == {3, 5}
    frozen_key = next(k for k in (f"k{i}" for i in range(100))
                      if km.slot_of(k) == 3)
    assert km.is_frozen(frozen_key)
    km.unfreeze([3])
    assert not km.is_frozen(frozen_key) and km.frozen == {5}


def test_keymap_doc_roundtrip():
    km = KeyMap.initial(3, 16)
    km.move([1, 4, 7], 2)
    km.freeze([9])
    doc = km.to_doc()
    back = KeyMap.from_doc(json.loads(json.dumps(doc)))
    assert back.to_doc() == doc
    assert back.epoch == 1 and back.frozen == {9}


# -- journal ----------------------------------------------------------------


def _rec(vid, step, verb="split", src=0, dst=1, slots=(0, 2), nslots=8):
    return {"id": vid, "verb": verb, "step": step, "src": src,
            "dst": dst, "slots": sorted(slots), "nslots": nslots}


def test_record_encode_decode():
    rec = _rec(3, "copied")
    assert decode_record(encode_record(rec)) == rec
    assert decode_record(encode_record(rec).encode()) == rec
    for junk in ("", "RJ!not json", "INSERT INTO kv", b"\xff\xfe", None):
        assert decode_record(junk) is None
    rd = decode_rdel(encode_rdel([2, 0], 8, 5))
    assert rd == {"id": 5, "slots": [0, 2], "nslots": 8}
    assert decode_rdel("RD!{bad") is None


def test_fold_any_order_with_duplicates():
    """The journal fold must collapse re-proposed duplicates and sort
    by verb id: the coordinator re-journals idempotently whenever a
    proposal may have been lost at a deposed leader."""
    v1 = [_rec(1, s) for s in ("begin", "copied", "flip", "done")]
    # After v1, group 1 owns {0,1,2,3,5,7} — a merge moves ALL of it.
    v2 = [_rec(2, s, verb="merge", src=1, dst=0,
               slots=[0, 1, 2, 3, 5, 7])
          for s in ("begin", "copied", "flip", "done")]
    records = list(reversed(v1)) + v2 + v1 + [v2[0]]   # shuffled + dups
    km, active = fold_records(records, num_groups=2, nslots=8)
    assert active is None
    # v1 moved slots {0,2} to g1, then v2 merged g1's keyspace into g0
    # and retired g1: everything lands on g0.
    assert set(km.slots) == {0}
    assert km.retired == {1}
    assert km.epoch == 3          # move, move, retire
    assert km.frozen == set()


def test_fold_active_verb_freezes_until_flipped():
    km, active = fold_records([_rec(1, "begin")], num_groups=2, nslots=8)
    assert active is not None and active["id"] == 1
    assert "flip" not in active["steps"]
    assert km.frozen == {0, 2} and km.epoch == 0
    # Once the flip record is in the log the slots belong to dst and
    # are NOT frozen — only the cleanup half remains.
    km, active = fold_records(
        [_rec(1, "begin"), _rec(1, "copied"), _rec(1, "flip")],
        num_groups=2, nslots=8)
    assert active is not None
    assert km.slots[0] == 1 and km.slots[2] == 1
    assert km.frozen == set() and km.epoch == 1
    # A migrate in flight never freezes slots (keyspace doesn't move).
    km, active = fold_records(
        [_rec(2, "begin", verb="migrate", slots=[])],
        num_groups=2, nslots=8)
    assert active is not None and km.frozen == set()


# -- snapshot fork ----------------------------------------------------------


def _rows_of_image(image: bytes, sql: str):
    with tempfile.NamedTemporaryFile(suffix=".db") as f:
        f.write(image)
        f.flush()
        conn = sqlite3.connect(f.name)
        try:
            return conn.execute(sql).fetchall()
        finally:
            conn.close()


def test_fork_disjoint_union(tmp_path):
    """Key-range fork: two standalone DBs, keyed rows disjoint by
    slot, union exactly the source; meta tables on BOTH sides,
    non-keyed tables stay with the source shard.  Runs through
    `SQLiteStateMachine.serialize`, so it exercises the py3.10
    `VACUUM INTO` fallback on interpreters without
    Connection.serialize.  resume=True so the `_raft_meta` applied
    floor exists — the meta table both forks must carry."""
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    sm = SQLiteStateMachine(str(tmp_path / "src.db"), resume=True)
    try:
        sm.apply("CREATE TABLE kv (k TEXT PRIMARY KEY, v TEXT)", 1)
        src_rows = {}
        for i in range(40):
            k, v = f"key-{i}", f"val|{i}"     # '|' probes value safety
            src_rows[k] = v
            sm.apply("INSERT INTO kv VALUES "
                     f"('{k}', '{v}')", i + 2)
        sm.apply("CREATE TABLE sidecar (n INTEGER)", 42)
        sm.apply("INSERT INTO sidecar VALUES (7)", 43)
        nslots = 8
        moving_slots = [0, 3, 5]
        index, moving, staying = fork_state_machine(
            sm, moving_slots, nslots)
        assert index == 43
    finally:
        sm.close()
    got_m = dict(_rows_of_image(moving, "SELECT k, v FROM kv"))
    got_s = dict(_rows_of_image(staying, "SELECT k, v FROM kv"))
    # Disjoint...
    assert not set(got_m) & set(got_s)
    # ...partitioned exactly by slot...
    assert all(slot_of(k, nslots) in set(moving_slots) for k in got_m)
    assert all(slot_of(k, nslots) not in set(moving_slots)
               for k in got_s)
    # ...and the union IS the source, values intact.
    union = dict(got_m)
    union.update(got_s)
    assert union == src_rows
    assert got_m            # the slot choice actually moved something
    # Meta tables ride on both forks; non-keyed tables stay.
    for img in (moving, staying):
        names = {r[0] for r in _rows_of_image(
            img, "SELECT name FROM sqlite_master WHERE type='table'")}
        assert "_raft_meta" in names
    assert _rows_of_image(staying,
                          "SELECT n FROM sidecar") == [(7,)]
    assert not _rows_of_image(
        moving, "SELECT name FROM sqlite_master "
                "WHERE type='table' AND name='sidecar'") \
        or _rows_of_image(moving, "SELECT n FROM sidecar") == []


# -- coordinator ------------------------------------------------------------


class MemBackend:
    """In-memory coordinator backend: journal/copy/rdel apply
    instantly (the 'cluster' never starves), which makes each step()
    advance exactly one state — crash points are then just step
    counts.  `records` doubles as the durable journal a rebuilt
    coordinator folds."""

    def __init__(self, keymap: KeyMap):
        self.nslots = keymap.nslots
        self.kv = {g: {} for g in
                   range(len(set(keymap.slots) | keymap.retired))}
        self.records = []
        self.applied = set()
        self.published = []
        self.shipped = []
        self.cutover_outcome = "completed"

    def seed(self, keymap: KeyMap, n: int = 32):
        for i in range(n):
            k = f"k{i}"
            self.kv[keymap.group_of(k)][k] = f"v{i}"

    def journal(self, group, rec, want=True):
        self.records.append(dict(rec))
        self.applied.add((int(rec["id"]), rec["step"]))

    def journal_applied(self, vid, step):
        return (int(vid), step) in self.applied

    def drained(self, group, slots):
        return True

    def rows_of(self, group, slots):
        ss = set(int(s) for s in slots)
        return {k: v for k, v in self.kv[int(group)].items()
                if slot_of(k, self.nslots) in ss}

    def copy(self, dst, rows):
        self.kv[int(dst)].update(rows)

    def copy_settled(self, dst, rows):
        return all(self.kv[int(dst)].get(k) == v
                   for k, v in rows.items())

    def rdel(self, group, slots, vid):
        for k in list(self.rows_of(group, slots)):
            del self.kv[int(group)][k]

    def rdel_settled(self, group, slots, vid):
        return not self.rows_of(group, slots)

    def publish(self, km):
        self.published.append(km.epoch)

    def ship(self, src, dst):
        self.shipped.append((int(src), int(dst)))

    def cutover(self, src, dst, retry=False):
        return self.cutover_outcome


def _coord(num_groups=2, nslots=8):
    km = KeyMap.initial(num_groups, nslots)
    be = MemBackend(km)
    be.seed(km)
    return ReshardCoordinator(be, km, num_groups=num_groups), be, km


def _run(coord, max_steps=50):
    for _ in range(max_steps):
        if not coord.busy:
            return
        coord.step()
    raise AssertionError(f"verb did not finish: {coord.doc()}")


def test_split_moves_rows_and_bumps_epoch():
    coord, be, km = _coord()
    before = dict(be.kv[0])
    moving = [0, 2]
    moved = {k: v for k, v in before.items()
             if slot_of(k, 8) in set(moving)}
    assert moved                  # seed covered the moving slots
    coord.enqueue("split", 0, 1, moving)
    assert km.frozen == {0, 2}    # intake refused while in flight
    _run(coord)
    assert km.epoch == 1 and km.slots[0] == 1 and km.slots[2] == 1
    assert km.frozen == set()
    for k, v in moved.items():
        assert be.kv[1][k] == v           # arrived at dst...
        assert k not in be.kv[0]          # ...and cleaned off src
    assert coord.counters["splits"] == 1
    assert be.published and be.published[-1] == 1


def test_merge_retires_source_and_migrate_ships():
    coord, be, km = _coord()
    src_rows = dict(be.kv[1])
    coord.enqueue("merge", 1, 0)
    _run(coord)
    assert km.retired == {1} and set(km.slots) == {0}
    assert all(be.kv[0][k] == v for k, v in src_rows.items())
    assert not be.kv[1]
    assert coord.counters["merges"] == 1
    # A full-slot split IS a merge (enqueue normalizes the verb).
    coord2, be2, km2 = _coord()
    coord2.enqueue("split", 1, 0, km2.slots_of(1))
    _run(coord2)
    assert coord2.counters["merges"] == 1 and km2.retired == {1}
    # Migrate never touches the keyspace; it ships + cuts over.
    coord.enqueue("migrate", 0, 2)
    _run(coord)
    assert be.shipped == [(0, 2)]
    assert coord.counters["migrations"] == 1
    assert km.epoch == 2          # unchanged by the migrate


def test_enqueue_refusals():
    coord, be, km = _coord()
    with pytest.raises(ReshardRefused):
        coord.enqueue("rotate", 0, 1)              # unknown verb
    with pytest.raises(ReshardRefused):
        coord.enqueue("split", 0, 1, [1])          # slot owned by g1
    with pytest.raises(ReshardRefused):
        coord.enqueue("split", 0, 0, [0])          # src == dst
    coord.enqueue("split", 0, 1, [0])
    with pytest.raises(ReshardRefused):
        coord.enqueue("split", 0, 1, [2])          # one verb at a time


def _rebuilt(be, num_groups=2, nslots=8):
    """A coordinator restarted after SIGKILL: fresh object, fresh
    boot-time keymap, state rebuilt ONLY from the journal fold."""
    km = KeyMap.initial(num_groups, nslots)
    coord = ReshardCoordinator(be, km, num_groups=num_groups)
    coord.recover(be.records)
    return coord, km


def test_sigkill_before_copy_fence_aborts():
    """Crash after `begin` but before the `copied` fence reached the
    log: rows may be half-copied into dst.  Recovery must UNDO the
    partial copies, release the freeze, and leave the router exactly
    where it was — never guess forward past an unfenced copy."""
    coord, be, km = _coord()
    src_before = dict(be.kv[0])
    coord.enqueue("split", 0, 1, [0, 2])
    coord.step()                  # j:begin -> drain
    coord.step()                  # drain: rows copied into dst
    assert any(slot_of(k, 8) in (0, 2) for k in be.kv[1])
    del coord                     # SIGKILL: fence never journaled

    coord2, km2 = _rebuilt(be)
    assert coord2.busy
    _run(coord2)
    assert coord2.counters["aborted"] == 1
    assert coord2.counters["resumed"] == 1
    assert coord2.counters["splits"] == 0
    assert km2.epoch == 0 and km2.slots == KeyMap.initial(2, 8).slots
    assert km2.frozen == set()
    assert be.kv[0] == src_before              # src untouched
    assert not any(slot_of(k, 8) in (0, 2) for k in be.kv[1])


def test_sigkill_after_copy_fence_resumes_forward():
    """Crash once `copied` is journaled: dst durably holds the rows,
    so recovery must finish the verb FORWARD (flip + cleanup), not
    abort — an abort here would orphan the copies."""
    coord, be, km = _coord()
    moved = {k: v for k, v in be.kv[0].items()
             if slot_of(k, 8) in (0, 2)}
    coord.enqueue("split", 0, 1, [0, 2])
    coord.step()                  # j:begin -> drain
    coord.step()                  # drain -> copy
    coord.step()                  # copy settled -> journal 'copied'
    assert ("copied" in {r["step"] for r in be.records})
    del coord                     # SIGKILL mid-verb

    coord2, km2 = _rebuilt(be)
    _run(coord2)
    assert coord2.counters["splits"] == 1
    assert coord2.counters["resumed"] == 1
    assert coord2.counters["aborted"] == 0
    assert km2.epoch == 1 and km2.slots[0] == 1 and km2.slots[2] == 1
    for k, v in moved.items():
        assert be.kv[1][k] == v and k not in be.kv[0]


def test_sigkill_after_flip_finishes_cleanup():
    coord, be, km = _coord()
    coord.enqueue("split", 0, 1, [0, 2])
    for _ in range(5):            # through j:flip (router flipped)
        coord.step()
    assert "flip" in {r["step"] for r in be.records}
    del coord

    coord2, km2 = _rebuilt(be)
    assert km2.epoch == 1         # fold already applied the flip
    _run(coord2)
    assert coord2.counters["splits"] == 1
    assert not any(slot_of(k, 8) in (0, 2) for k in be.kv[0])
    assert "done" in {r["step"] for r in be.records}


def test_migrate_disk_fault_aborts_cleanly():
    coord, be, km = _coord()

    def bad_ship(src, dst):
        raise OSError("injected fork fault")
    be.ship = bad_ship
    coord.enqueue("migrate", 0, 2)
    _run(coord)
    assert coord.counters["aborted"] == 1
    assert coord.counters["fork_faults"] == 1
    assert km.epoch == 0          # keyspace untouched


def test_metrics_doc_always_carries_all_verbs():
    coord, be, km = _coord()
    doc = coord.metrics_doc()
    assert doc["active"] == 0 and doc["epoch"] == 0
    assert set(doc["duration"]) == {"split", "merge", "migrate"}
    for verb in doc["duration"]:
        h = doc["duration"][verb]
        assert h["count"] == 0 and "inf" in h["bucket"]
    coord.enqueue("split", 0, 1, [0])
    _run(coord)
    h = coord.metrics_doc()["duration"]["split"]
    assert h["count"] == 1 and h["bucket"]["inf"] == 1


# -- shm plane: mapping-epoch fail-closed -----------------------------------


def test_shm_reader_fails_closed_on_keymap_epoch(tmp_path):
    """A router flip publishes the new mapping epoch into the shm
    header; a worker whose cached epoch lags MUST fall back to the
    ring path (None) — recoverably, unlike an engine-epoch mismatch —
    until it refreshes and revalidates."""
    from raftsql_tpu.runtime.shm import (ShmSnapshotPublisher,
                                         ShmSnapshotReader)
    pub = ShmSnapshotPublisher(str(tmp_path), num_groups=1)
    pub.start(lambda g: None, lambda g: 0)
    rdr = ShmSnapshotReader(str(tmp_path))
    try:
        pub.publish_deltas({0: [("CREATE TABLE t (v TEXT)", 1),
                                ("INSERT INTO t VALUES ('x')", 2)]})
        got = rdr.try_read("local", 0, "SELECT count(*) FROM t")
        assert got is not None and got[0].strip() == "|1|"
        pub.set_keymap_epoch(1)   # reshard flip behind the worker
        assert rdr.try_read("local", 0,
                            "SELECT count(*) FROM t") is None
        assert rdr.keymap_epoch() == 1
        rdr.note_keymap_epoch(1)  # worker refreshed its mapping
        got = rdr.try_read("local", 0, "SELECT count(*) FROM t")
        assert got is not None and got[0].strip() == "|1|"
    finally:
        rdr.close()
        pub.close()


# -- api client: mapping-epoch adoption (satellite: unknown-group refresh) --


def _client():
    from raftsql_tpu.api.client import RaftSQLClient
    return RaftSQLClient([10001, 10002], timeout_s=0.2,
                         backoff_s=0.001, backoff_cap_s=0.002)


def _km_doc(epoch, nslots=8, groups=2):
    return KeyMap(nslots, [s % groups for s in range(nslots)],
                  epoch=epoch).to_doc()


def test_client_adopts_only_newer_keymaps():
    c = _client()
    assert c.keymap_epoch() is None
    assert c._note_keymap(_km_doc(2)) is True
    assert c.keymap_epoch() == 2
    # Stale and equal sweeps must NOT roll the router back.
    assert c._note_keymap(_km_doc(1)) is False
    assert c._note_keymap(_km_doc(2)) is False
    assert c.keymap_epoch() == 2
    assert c._note_keymap(_km_doc(3)) is True
    assert c._note_keymap("junk") is False
    assert c.keymap_epoch() == 3
    # The cached epoch is pinned onto every /kv request.
    assert c._kv_headers()["X-Raft-Keymap-Epoch"] == "3"


def test_client_put_kv_refreshes_on_409_and_retries():
    """The mapping-epoch bump path: a split moved the keyspace under
    this client, the server refuses the pinned epoch with 409 + the
    CURRENT mapping, and the client must adopt it and retry the same
    write immediately (breaking the node rotation, not backing off)."""
    c = _client()
    c._note_keymap(_km_doc(1))
    attempts = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        attempts.append((node, (headers or {}).get(
            "X-Raft-Keymap-Epoch")))
        if (headers or {}).get("X-Raft-Keymap-Epoch") != "4":
            return 409, {}, json.dumps(
                {"error": "keymap epoch mismatch",
                 "keymap": _km_doc(4)})
        return 204, {"X-Raft-Session": "9",
                     "X-Raft-Keymap-Epoch": "4"}, ""

    c.raw = fake_raw
    assert c.put_kv("alpha", "1", deadline_s=5) == 9
    assert c.keymap_epoch() == 4
    # One refused probe at the stale epoch, then the retry pins the
    # adopted epoch — no second trip around the ring in between.
    assert attempts[0][1] == "1" and attempts[1][1] == "4"
    assert len(attempts) == 2


def test_client_epoch_echo_triggers_healthz_sweep():
    """A SUCCESSFUL /kv response that echoes a newer epoch than the
    cache means the keyspace moved without refusing us (the slot
    landed on the same group): the client must sweep /healthz so its
    NEXT request pins the current epoch."""
    c = _client()
    swept = []

    def fake_health(node, timeout_s=2.0):
        swept.append(node)
        return {"keymap": _km_doc(2)}

    c.health = fake_health
    c._note_kv_epoch({"X-Raft-Keymap-Epoch": "2"})   # cache empty
    assert swept and c.keymap_epoch() == 2
    swept.clear()
    # Echo of the SAME epoch: no sweep.  Stale echo: no sweep either
    # (epochs only move forward; an old server answer is not news).
    c._note_kv_epoch({"X-Raft-Keymap-Epoch": "2"})
    c._note_kv_epoch({"X-Raft-Keymap-Epoch": "1"})
    c._note_kv_epoch({"X-Raft-Keymap-Epoch": "junk"})
    assert not swept
    c._note_kv_epoch({"X-Raft-Keymap-Epoch": "5"})
    assert swept


def test_client_get_kv_404_is_none_not_error():
    c = _client()

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        if path.endswith("/missing"):
            return 404, {"X-Raft-Keymap-Epoch": "0"}, "no such key"
        return 200, {"X-Raft-Keymap-Epoch": "0"}, "value"

    c.raw = fake_raw
    assert c.get_kv("missing", deadline_s=5) is None
    assert c.get_kv("present", deadline_s=5) == "value"


# -- the live serving plane (both HTTP planes) ------------------------------


@pytest.fixture(params=["threaded", "aio"])
def elastic(request, tmp_path):
    """Single-node 4-group cluster with the reshard plane attached and
    its coordinator thread running — the `--reshard` server wiring."""
    from raftsql_tpu.api.aio import AioSQLServer
    from raftsql_tpu.api.http import SQLServer
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.reshard.plane import ReshardPlane
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import (LoopbackHub,
                                                LoopbackTransport)
    cfg = RaftConfig(num_groups=4, num_peers=1, tick_interval_s=0.005,
                     log_window=64, max_entries_per_msg=4)
    pipe = RaftPipe.create(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                           data_dir=str(tmp_path / "raftsql-1"))
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        str(tmp_path / f"kv-g{g}.db")), pipe, num_groups=4)
    plane = ReshardPlane(rdb, nslots=16,
                         ship_dir=str(tmp_path / "ship"))
    plane.start()
    srv_cls = SQLServer if request.param == "threaded" else AioSQLServer
    srv = srv_cls(0, rdb, host="127.0.0.1", timeout_s=TIMEOUT)
    srv.start()
    yield srv, rdb, plane
    srv.stop()
    plane.stop()
    rdb.close()


def _raw_kv(srv, method, key, body=b"", headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                      timeout=10)
    try:
        conn.request(method, f"/kv/{key}", body=body,
                     headers=headers or {})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _await_idle(plane, deadline_s=20.0):
    deadline = time.monotonic() + deadline_s
    while plane.coord.busy:
        if time.monotonic() > deadline:
            raise AssertionError(f"verb stuck: {plane.doc()}")
        time.sleep(0.02)


@pytest.mark.slow
def test_elastic_keyspace_end_to_end(elastic):
    """The full serving-plane story on a live node: keyed writes over
    the hash ring, a split and a merge through POST /reshard, epoch
    fail-closed refusals, client-side mapping adoption, reshard
    metrics, and finally a journal re-fold into a FRESH plane proving
    the router state is entirely log-derived."""
    from raftsql_tpu.api.client import RaftSQLClient, SQLError
    from raftsql_tpu.reshard.plane import ReshardPlane
    srv, rdb, plane = elastic
    cli = RaftSQLClient([srv.port], timeout_s=5.0, backoff_s=0.01)

    kv = {f"k{i}": f"v{i}|{i}" for i in range(24)}   # '|' in values
    for k, v in kv.items():
        assert cli.put_kv(k, v, deadline_s=TIMEOUT) is not None
    for k, v in kv.items():
        assert cli.get_kv(k, deadline_s=TIMEOUT) == v
    assert cli.get_kv("never-written", deadline_s=TIMEOUT) is None

    # /healthz carries the mapping; the client swept it while probing.
    assert cli.refresh_keymap() == 0

    # SPLIT: move half of group 0's slots to group 2.
    owned = plane.keymap.slots_of(0)
    moving = owned[:len(owned) // 2]
    doc = cli.reshard("split", 0, 2, moving, deadline_s=TIMEOUT)
    assert doc["verb"] == "split" and doc["id"] >= 1
    _await_idle(plane)
    assert plane.keymap.epoch == 1
    assert all(plane.keymap.slots[s] == 2 for s in moving)

    # Every acked write survives the move, read back THROUGH the
    # client, which adopts the bumped epoch along the way (the 409
    # fail-closed path: its cached epoch 0 is now stale).
    for k, v in kv.items():
        assert cli.get_kv(k, deadline_s=TIMEOUT) == v, k
    assert cli.keymap_epoch() == plane.keymap.epoch
    # Writes route to the NEW owner after the flip.
    moved_key = next((k for k in kv
                      if plane.keymap.slot_of(k) in set(moving)), None)
    if moved_key is not None:
        assert cli.put_kv(moved_key, "rewritten",
                          deadline_s=TIMEOUT) is not None
        assert cli.get_kv(moved_key, deadline_s=TIMEOUT) == "rewritten"
        kv[moved_key] = "rewritten"

    # A request pinned to a stale epoch is refused with the CURRENT
    # mapping attached — the raw-HTTP view of what the client handled.
    status, hdrs, body = _raw_kv(srv, "GET", "k0",
                                 headers={"X-Raft-Keymap-Epoch": "0"})
    assert status == 409
    refused = json.loads(body)
    assert refused["keymap"]["epoch"] == plane.keymap.epoch
    assert int(hdrs.get("X-Raft-Keymap-Epoch")) == plane.keymap.epoch

    # Frozen-slot intake is refused up front with a retry hint.
    s0 = plane.keymap.slot_of("k0")
    plane.keymap.freeze([s0])
    try:
        status, hdrs, _ = _raw_kv(srv, "PUT", "k0", body=b"nope")
        assert status == 503 and hdrs.get("Retry-After")
    finally:
        plane.keymap.unfreeze([s0])

    # MERGE group 3 into group 1; group 3 retires from the router.
    cli.reshard("merge", 3, 1, deadline_s=TIMEOUT)
    _await_idle(plane)
    assert 3 in plane.keymap.retired
    assert 3 not in plane.keymap.live_groups()
    for k, v in kv.items():
        assert cli.get_kv(k, deadline_s=TIMEOUT) == v, k

    # Verb hygiene over the wire: unknown verb and busy-coordinator
    # are 409s, surfaced as SQLError by the client.
    with pytest.raises(SQLError):
        cli.reshard("rotate", 0, 1, deadline_s=TIMEOUT)

    # /metrics carries the reshard counters + per-verb histograms.
    m = rdb.metrics()
    assert m["reshard"]["splits"] == 1
    assert m["reshard"]["merges"] == 1
    assert m["reshard"]["epoch"] == plane.keymap.epoch
    assert m["reshard"]["duration"]["split"]["count"] == 1

    # The router never holds truth the logs don't: folding the
    # replicated journal tables into a FRESH plane rebuilds the exact
    # same mapping.
    want = plane.keymap.to_doc()
    rebuilt = ReshardPlane(rdb, nslots=plane.keymap.nslots,
                           ship_dir=plane.ship_dir)
    try:
        rebuilt.recover_from_db()
        got = rebuilt.keymap.to_doc()
        assert got == want
        assert not rebuilt.coord.busy     # no verb left in flight
    finally:
        rdb.reshard = plane               # restore the live plane
