"""End-to-end in-process cluster tests — the reference test scenarios.

Ports of the reference's raftsql_test.go onto the TPU-native stack: a real
3-node cluster in one process (loopback transport instead of localhost
HTTP, reference raftsql_test.go:19), real WAL dirs, real SQLite files,
concurrent per-node proposals, node stop/restart with WAL replay counted
through the commit-listener nil-sentinel protocol (db.go:26, 48-50).
"""
import os
import queue
import sqlite3
import threading
import time

import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport

TICK = 0.005
TIMEOUT = 30.0


class Cluster:
    """The reference's test harness struct (raftsql_test.go:11-28)."""

    def __init__(self, n: int, tmpdir: str, groups: int = 1):
        self.n = n
        self.tmpdir = tmpdir
        self.groups = groups
        self.hub = LoopbackHub()
        self.cfg = RaftConfig(num_groups=groups, num_peers=n,
                              tick_interval_s=TICK, election_ticks=10,
                              log_window=64, max_entries_per_msg=4)
        self.dbs = [None] * n
        self.apply(self.new_node)

    def new_node(self, i: int, listener=None) -> None:
        if self.dbs[i] is not None:
            return
        pipe = RaftPipe.create(
            i + 1, self.n, self.cfg, LoopbackTransport(self.hub),
            data_dir=os.path.join(self.tmpdir, f"raftsql-{i + 1}"))
        dbpath = os.path.join(self.tmpdir, f"testcase-{i}.db")
        self.dbs[i] = RaftDB(lambda g: SQLiteStateMachine(dbpath),
                             pipe, num_groups=self.groups,
                             listener=listener)

    def stop_node(self, i: int) -> None:
        if self.dbs[i] is not None:
            self.dbs[i].close()
            self.dbs[i] = None

    def apply(self, f) -> None:
        """Concurrent per-node ops under a waitgroup
        (reference raftsql_test.go:79-90)."""
        errs = []

        def wrap(i):
            try:
                f(i)
            except Exception as e:          # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=wrap, args=(i,))
                   for i in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(TIMEOUT)
        if errs:
            raise errs[0]

    def create_entries(self) -> int:
        """Schema + one insert per node, proposed concurrently from
        different nodes (reference raftsql_test.go:54-77)."""
        err = self.dbs[0].propose(
            "CREATE TABLE main.t (id int primary key asc, nodeid text)"
        ).wait(TIMEOUT)
        assert err is None, err

        def insert(i):
            q = f'INSERT INTO main.t (nodeid) VALUES ("{i}")'
            e = self.dbs[i].propose(q).wait(TIMEOUT)
            assert e is None, e

        self.apply(insert)
        return 1 + self.n

    def wait_rows(self, i: int, needles, timeout=TIMEOUT,
                  q="SELECT * from main.t") -> str:
        """Poll node i's local replica until all needles appear (local
        reads are stale by design, reference raftsql_test.go:150-158)."""
        deadline = time.monotonic() + timeout
        while True:
            v = self.dbs[i].query(q)
            if all(nd in v for nd in needles):
                return v
            if time.monotonic() > deadline:
                raise AssertionError(f"node {i}: {needles} not in {v!r}")
            time.sleep(0.01)

    def close(self) -> None:
        self.apply(lambda i: self.dbs[i].close() if self.dbs[i] else None)


@pytest.fixture
def tmp_cluster(tmp_path):
    clus = Cluster(3, str(tmp_path))
    yield clus
    clus.close()


def test_new_db(tmp_cluster):
    """Reference TestNewDB (raftsql_test.go:92-115)."""
    clus = tmp_cluster
    clus.create_entries()

    def check(i):
        db = clus.dbs[i]
        with pytest.raises(Exception):
            db.query("SELECT * from main.x")     # no such table
        v = clus.wait_rows(i, ["||0|", "||1|", "||2|"])
        assert v.count("\n") == 3, v

    clus.apply(check)


def test_restart_db(tmp_cluster):
    """Reference TestRestartDB (raftsql_test.go:117-171)."""
    clus = tmp_cluster
    expected = clus.create_entries()

    # Node 1 must have everything applied (hence WAL-durable) before the
    # crash, or the replay count below is racy.
    clus.wait_rows(1, ["||0|", "||1|", "||2|"])
    clus.stop_node(1)

    # Add an entry while node 1 is down.
    err = clus.dbs[2].propose(
        'INSERT INTO main.t (nodeid) VALUES ("foo")').wait(TIMEOUT)
    assert err is None, err

    # Restart node 1 behind a partition: WAL replay is local, so the
    # replay count is exact, and the stale-read check below is
    # deterministic instead of racing leader catch-up.  (The reference
    # wins the same race only because its ticks are 100ms,
    # raftsql_test.go:134-158.)
    clus.hub.faults.isolate(2, range(1, 4))       # node index 1 == id 2
    db1cc: "queue.Queue" = queue.Queue()
    done = threading.Event()
    threading.Thread(
        target=lambda: (clus.new_node(1, listener=db1cc), done.set()),
        daemon=True).start()
    n = 0
    while True:
        item = db1cc.get(timeout=TIMEOUT)
        if item is None:
            break
        n += 1
    assert n == expected, f"expected {expected}, got {n} replay entries"
    assert done.wait(TIMEOUT)

    # 'foo' must NOT be in node 1's replica yet — still out of sync
    # (raftsql_test.go:150-158 documents the stale-read model).
    v = clus.dbs[1].query("SELECT * from main.t")
    assert "||foo|" not in v, f'"foo" already in db! {v}'

    # Heal: the missed write streams in from the leader; await it on the
    # listener (raftsql_test.go:159).
    clus.hub.faults.heal()
    while True:
        item = db1cc.get(timeout=TIMEOUT)
        if item is not None and "foo" in item[1]:
            break

    def check(i):
        clus.wait_rows(i, ["||foo|"])

    clus.apply(check)


def test_duplicate_identical_queries_fifo(tmp_cluster):
    """The q2cb FIFO path for duplicate in-flight identical queries —
    untested in the reference (SURVEY.md §4 gap, db.go:70-75)."""
    clus = tmp_cluster
    err = clus.dbs[0].propose(
        "CREATE TABLE main.d (x text)").wait(TIMEOUT)
    assert err is None, err
    q = 'INSERT INTO main.d (x) VALUES ("same")'
    futs = [clus.dbs[0].propose(q) for _ in range(4)]
    for f in futs:
        assert f.wait(TIMEOUT) is None
    clus.wait_rows(0, ["|same|"], q="SELECT * from main.d")
    v = clus.dbs[0].query("SELECT count(*) from main.d")
    assert v == "|4|\n", v


def test_propose_select_rejected(tmp_cluster):
    err = tmp_cluster.dbs[0].propose("SELECT 1").wait(TIMEOUT)
    assert err is not None and "non-SELECT" in str(err)


def test_query_non_select_rejected(tmp_cluster):
    with pytest.raises(ValueError, match="expected SELECT"):
        tmp_cluster.dbs[0].query("INSERT INTO t VALUES (1)")


def test_bad_sql_propagates_apply_error(tmp_cluster):
    err = tmp_cluster.dbs[0].propose(
        "INSERT INTO main.nosuch VALUES (1)").wait(TIMEOUT)
    assert err is not None


def test_transport_error_fans_out_to_pending_acks(tmp_cluster):
    """Transport failure → every pending ack receives the error and the
    node tears down (reference raft.go:136-142, db.go:83-95).

    The proposing node is partitioned first so its proposals can never
    commit, then the transport's on_error callback fires — the exact path
    a fatal listener failure takes (transport/tcp.py _accept_loop)."""
    clus = tmp_cluster
    err = clus.dbs[0].propose("CREATE TABLE main.e (x text)").wait(TIMEOUT)
    assert err is None, err

    clus.hub.faults.isolate(1, range(1, 4))       # node index 0 == id 1
    futs = [clus.dbs[0].propose(
        f'INSERT INTO main.e (x) VALUES ("{k}")') for k in range(3)]
    time.sleep(0.1)                               # let them enter flight
    for f in futs:
        assert not f._evt.is_set()                # stuck without quorum

    boom = RuntimeError("transport exploded")
    clus.dbs[0].pipe.node._on_error(boom)

    for f in futs:
        assert f.wait(TIMEOUT) is boom            # fan-out, not a hang
    # The node is down: new proposals fail fast with the same error.
    assert clus.dbs[0].propose(
        'INSERT INTO main.e (x) VALUES ("late")').wait(TIMEOUT) is boom
    clus.hub.faults.heal()
    clus.stop_node(0)
    # Survivors keep running (they hold quorum without the dead node).
    err = clus.dbs[1].propose(
        'INSERT INTO main.e (x) VALUES ("alive")').wait(TIMEOUT)
    assert err is None, err


def test_multi_group_isolation(tmp_path):
    """Groups are independent logs applied to independent DB files — the
    batched engine's reason to exist (BASELINE.json north star)."""
    hub = LoopbackHub()
    cfg = RaftConfig(num_groups=3, num_peers=3, tick_interval_s=TICK,
                     log_window=64, max_entries_per_msg=4)
    dbs = []
    for i in range(3):
        pipe = RaftPipe.create(
            i + 1, 3, cfg, LoopbackTransport(hub),
            data_dir=str(tmp_path / f"raftsql-{i + 1}"))
        dbs.append(RaftDB(
            lambda g, i=i: SQLiteStateMachine(
                str(tmp_path / f"multi-{i}-g{g}.db")),
            pipe, num_groups=3))
    try:
        for g in range(3):
            err = dbs[0].propose(
                f"CREATE TABLE main.t (v text)", group=g).wait(TIMEOUT)
            assert err is None, err
            err = dbs[g].propose(
                f'INSERT INTO main.t (v) VALUES ("g{g}")',
                group=g).wait(TIMEOUT)
            assert err is None, err
        deadline = time.monotonic() + TIMEOUT
        for i in range(3):
            for g in range(3):
                while True:
                    v = dbs[i].query("SELECT * from main.t", group=g)
                    if f"|g{g}|" in v:
                        assert v == f"|g{g}|\n", v   # no cross-group leak
                        break
                    assert time.monotonic() < deadline
                    time.sleep(0.01)
    finally:
        for db in dbs:
            db.close()


def test_follower_catchup_beyond_ring_window(tmp_path):
    """A restarted follower whose lag exceeds the on-device term ring (W)
    can no longer be served by device-built appends (core/step.py window
    guard sends it empty heartbeats).  The leader HOST must feed it
    catch-up appends from the payload log (runtime/node.py
    _build_catchups) until it re-enters the window."""
    hub = LoopbackHub()
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                     log_window=16, max_entries_per_msg=4)
    dirs = [str(tmp_path / f"raftsql-{i + 1}") for i in range(3)]
    paths = [str(tmp_path / f"cu-{i}.db") for i in range(3)]

    def boot(i):
        pipe = RaftPipe.create(i + 1, 3, cfg, LoopbackTransport(hub),
                               data_dir=dirs[i])
        return RaftDB(lambda g, i=i: SQLiteStateMachine(paths[i]), pipe)

    dbs = [boot(i) for i in range(3)]
    try:
        err = dbs[0].propose("CREATE TABLE main.t (v int)").wait(TIMEOUT)
        assert err is None, err
        dbs[1].close()
        dbs[1] = None
        # Push the live pair far past the dead node's position + W.
        for k in range(3 * cfg.log_window):
            err = dbs[0].propose(
                f"INSERT INTO main.t (v) VALUES ({k})").wait(TIMEOUT)
            assert err is None, err
        dbs[1] = boot(1)
        deadline = time.monotonic() + TIMEOUT
        while True:
            # The restarted replica may not have replayed/caught up the
            # CREATE yet: local reads are stale by design, so "no such
            # table" is a legitimate transient — keep polling.
            try:
                v = dbs[1].query("SELECT count(*) from main.t")
            except sqlite3.OperationalError as e:
                v = repr(e)
            if v == f"|{3 * cfg.log_window}|\n":
                break
            assert time.monotonic() < deadline, \
                f"follower stalled at {v!r}"
            time.sleep(0.02)
        # The leader really used the host path.
        assert any(db is not None
                   and db.metrics()["catchup_appends"] > 0
                   for db in dbs)
    finally:
        for db in dbs:
            if db is not None:
                db.close()


def test_follower_catchup_below_table_floor(tmp_path):
    """A follower whose next_idx falls below the leader's term-transition
    table floor (more than K transitions behind — here K=2 with repeated
    re-elections) is unservable by device appends even INSIDE the ring
    window: the send guard (core/step.py in_window) suppresses real
    batches, so without the floor clause in _build_catchups' lag test
    the follower would see empty heartbeats forever.  The leader host
    must feed it catch-up appends from the payload log."""
    hub = LoopbackHub()
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                     log_window=32, max_entries_per_msg=4,
                     term_table_slots=2)
    dirs = [str(tmp_path / f"raftsql-{i + 1}") for i in range(3)]
    paths = [str(tmp_path / f"fl-{i}.db") for i in range(3)]

    def boot(i):
        pipe = RaftPipe.create(i + 1, 3, cfg, LoopbackTransport(hub),
                               data_dir=dirs[i])
        return RaftDB(lambda g, i=i: SQLiteStateMachine(paths[i]), pipe)

    dbs = [boot(i) for i in range(3)]
    inserted = 0
    try:
        err = dbs[0].propose("CREATE TABLE main.t (v int)").wait(TIMEOUT)
        assert err is None, err
        dbs[2].close()
        dbs[2] = None

        def put(n):
            nonlocal inserted
            for _ in range(n):
                err = None
                for src in (0, 1) * 5:      # whichever is up forwards to
                    if dbs[src] is None:    # the current leader
                        continue
                    err = dbs[src].propose(
                        f"INSERT INTO main.t (v) VALUES ({inserted})"
                    ).wait(TIMEOUT)
                    if err is None:
                        break
                assert err is None, err
                inserted += 1

        # K+1 = 3 term transitions while node 2 is down, with a few
        # entries each so every transition stays inside the ring window
        # — the floor (oldest of the last K=2 transitions) then sits
        # ABOVE node 2's position while the ring still covers it.
        # Alternate WHICH of the live pair restarts: the survivor wins
        # the next election, so every cycle really bumps the term.
        put(2)
        for cyc in range(3):
            i = cyc % 2
            dbs[i].close()
            dbs[i] = None
            time.sleep(40 * TICK)
            dbs[i] = boot(i)
            put(2)
        dbs[2] = boot(2)
        deadline = time.monotonic() + TIMEOUT
        while True:
            # "no such table" is a legitimate transient on the freshly
            # restarted replica (stale local reads by design): its
            # parity-mode SQLite was rebuilt from a replayed prefix
            # that may predate the CREATE — poll until catch-up
            # delivers it.
            try:
                v = dbs[2].query("SELECT count(*) from main.t")
            except sqlite3.OperationalError as e:
                v = repr(e)
            if v == f"|{inserted}|\n":
                break
            assert time.monotonic() < deadline, \
                f"follower stalled below the table floor at {v!r}"
            time.sleep(0.02)
        assert any(db is not None
                   and db.metrics()["catchup_appends"] > 0
                   for db in dbs)
    finally:
        for db in dbs:
            if db is not None:
                db.close()
