"""Overload plane units (raftsql_tpu/overload/) + client backoff.

Two halves, matching the PR-20 contract:

* the controller itself — budgets refuse BEFORE the enqueue, deadline
  sheds attribute a phase, the brownout ladder never downgrades
  silently, and the advisory Retry-After stays inside its clamp; and

* the client side of the refusal (satellite c) — a 429's Retry-After
  holds exactly THAT node out of the rotation (no global stall, no
  retry storm), junk header values are ignored, and a request whose
  deadline already passed fails fast without a network round trip.

No sockets anywhere: the client's `raw` is monkeypatched, the
controller is driven directly.
"""
import time

import pytest

from raftsql_tpu.api.client import RaftSQLClient, Unavailable
from raftsql_tpu.overload import (
    BROWNOUT_LEASE_ONLY,
    BrownoutGovernor,
    DeadlineExceeded,
    Overloaded,
    OverloadController,
    deadline_steps,
    retry_after_header,
    retryable_refusal,
    zero_metrics_doc,
)


# -- admission budgets -------------------------------------------------


def _ctl(**kw):
    kw.setdefault("groups", 4)
    kw.setdefault("seed", 0)
    return OverloadController(**kw)


def test_admit_refuses_before_enqueue_per_group():
    c = _ctl(group_cap=4)
    assert c.admit(0, 3) == 3
    # The 4th entry still fits; a batch of 2 would overflow and must
    # be refused WHOLE (budgets are checked before the enqueue, so the
    # real queue can never exceed the cap mid-batch).
    with pytest.raises(Overloaded) as ei:
        c.admit(0, 2)
    assert ei.value.scope == "group:0"
    assert c.rejected == 2 and c._depth[0] == 3
    # Other groups have their own budget.
    assert c.admit(1, 4) == 4
    assert c.depth_total == 7 and c.peak_depth == 7


def test_admit_engine_budget_spans_groups():
    c = _ctl(group_cap=0, total_cap=5)
    c.admit(0, 3)
    c.admit(1, 2)
    with pytest.raises(Overloaded) as ei:
        c.admit(2, 1)
    assert ei.value.scope == "engine"
    assert c.admitted == 5 and c.rejected == 1


def test_zero_caps_track_depth_but_never_refuse():
    c = _ctl()                              # both budgets disabled
    c.admit(0, 10_000)
    assert c.depth_total == 10_000 and c.rejected == 0


def test_drained_and_stage_shed_release_budget():
    c = _ctl(group_cap=4)
    c.admit(0, 4)
    with pytest.raises(Overloaded):
        c.admit(0, 1)
    c.drained(0, 3)
    c.stage_shed(0, 1)
    assert c.depth_total == 0 and c.shed_stage == 1
    assert c.admit(0, 4) == 4               # budget fully returned
    assert c.peak_depth == 4


def test_reset_depth_survives_counters():
    """Crash/restart: the queues died with the node, the cumulative
    counters must not (they feed the chaos report)."""
    c = _ctl(group_cap=4)
    c.admit(0, 4)
    with pytest.raises(Overloaded):
        c.admit(0, 1)
    c.reset_depth()
    assert c.depth_total == 0 and c._depth[0] == 0
    assert c.admitted == 4 and c.rejected == 1
    assert c.admit(0, 2) == 2


# -- deadline clocks ---------------------------------------------------


def test_deadline_steps_conversion_and_floor():
    # 10 ms at 1 ms/step = 10 steps from now.
    assert deadline_steps(100, 10.0, 0.001) == 110
    # Untimed engine (tick_interval_s=0): the 0.1 ms/step floor, the
    # same floor the lease clock uses.
    assert deadline_steps(0, 1.0, 0.0) == 10
    # A zero/negative budget never moves the deadline into the past.
    assert deadline_steps(7, 0.0, 0.001) == 7


def test_check_deadline_attributes_the_phase():
    c = _ctl()
    assert c.check_deadline(5, None, "stage") is True
    assert c.check_deadline(5, 5, "stage") is True   # inclusive
    with pytest.raises(DeadlineExceeded) as ei:
        c.check_deadline(6, 5, "stage")
    assert ei.value.phase == "stage" and c.shed_stage == 1
    with pytest.raises(DeadlineExceeded):
        c.check_deadline(6, 5, "ring")
    assert c.shed_ring == 1
    c.note_shed("edge")
    c.note_shed("commit_wait")
    assert c.shed_edge == 1 and c.shed_commit_wait == 1


# -- brownout ladder ---------------------------------------------------


def test_brownout_governor_hysteresis():
    g = BrownoutGovernor(hi=10.0, lo=3.0, alpha=1.0)  # alpha=1: no lag
    assert g.note_depth(9) == 0
    assert g.note_depth(11) == BROWNOUT_LEASE_ONLY
    # Between lo and hi: stays browned out (the hysteresis gap).
    assert g.note_depth(5) == BROWNOUT_LEASE_ONLY
    assert g.note_depth(2) == 0
    assert g.transitions == 2
    with pytest.raises(ValueError):
        BrownoutGovernor(hi=5.0, lo=5.0)


def test_brownout_read_path_never_silently_downgrades():
    c = _ctl(total_cap=100, brownout_hi=4.0, brownout_lo=1.0)
    assert c.brownout_read_path(opt_in=False) == "read_index"
    # Sustained depth pushes the EWMA over hi.
    c.admit(0, 50)
    for _ in range(8):
        c.note_tick()
    assert c.brownout_active()
    # Opted in: degraded to a session read, counted.
    assert c.brownout_read_path(opt_in=True) == "session"
    # Not opted in: typed refusal, never a silent stale answer.
    with pytest.raises(Overloaded) as ei:
        c.brownout_read_path(opt_in=False)
    assert ei.value.scope == "brownout"
    assert c.brownouts == 2


def test_no_total_cap_means_no_governor_by_default():
    assert _ctl().governor is None
    assert _ctl(total_cap=48).governor is not None
    # Explicit thresholds work without a total cap.
    assert _ctl(brownout_hi=8.0).governor is not None


# -- advisory Retry-After ----------------------------------------------


def test_retry_after_pessimistic_then_drain_tracking():
    c = _ctl(total_cap=100)
    # No drain observed yet: the pessimistic 5 s base, jittered into
    # [2.5, 7.5).
    for _ in range(32):
        assert 2.5 <= c.retry_after_s() < 7.5
    # Steady drain of 10 entries/tick at 1 ms/tick, backlog 20:
    # base = 20/10 * 0.001 = 2 ms -> clamped up to the 10 ms floor.
    c.admit(0, 20)
    for _ in range(64):
        c.drained(0, 10)
        c._depth[0] += 10          # hold the backlog constant
        c.depth_total += 10
        c.note_tick()
    for _ in range(32):
        assert 0.005 <= c.retry_after_s() < 0.015


def test_retry_after_header_floor_and_format():
    assert retry_after_header(0.0) == "0.010"
    assert retry_after_header(-3.0) == "0.010"
    assert retry_after_header(1.2345) == "1.234"
    assert float(retry_after_header(5.0)) == 5.0


def test_retryable_refusal_unified_mapping():
    st, ra = retryable_refusal(Overloaded("engine", 0.25))
    assert (st, ra) == (429, 0.25)
    st, ra = retryable_refusal(TimeoutError("apply"), default_retry_s=2.0)
    assert (st, ra) == (503, 2.0)


def test_metrics_doc_matches_zero_doc_shape():
    """Both HTTP planes flatten m["overload"] into raftsql_overload_*
    series; attached and detached engines must export the SAME keys or
    check_prom's required-series list breaks on one of them."""
    assert set(_ctl().metrics_doc()) == set(zero_metrics_doc())


# -- client: per-node Retry-After holdoff (satellite c) -----------------


def _client(**kw):
    kw.setdefault("timeout_s", 0.2)
    kw.setdefault("backoff_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.002)
    return RaftSQLClient([10001, 10002, 10003], **kw)


def test_retry_after_parsing_and_clamp():
    c = _client()
    c._note_retry_after(0, {"Retry-After": "1.5"})
    assert c._holdoff[0] > time.monotonic() + 1.0
    # Clamped: a hostile/buggy server cannot park a node for an hour.
    c._note_retry_after(1, {"Retry-After": "3600"})
    assert c._holdoff[1] <= time.monotonic() + 30.0
    # Junk, absent, zero and negative values are all ignored.
    c._note_retry_after(2, {"Retry-After": "soon"})
    c._note_retry_after(2, {})
    c._note_retry_after(2, {"Retry-After": "0"})
    c._note_retry_after(2, {"Retry-After": "-2"})
    assert 2 not in c._holdoff
    # A shorter estimate never truncates a live longer holdoff.
    before = c._holdoff[1]
    c._note_retry_after(1, {"Retry-After": "0.01"})
    assert c._holdoff[1] == before


def test_holdoff_skips_node_but_never_empties_rotation():
    c = _client()
    c._holdoff[1] = time.monotonic() + 60.0
    for _ in range(8):
        assert 1 not in c._order(0, None)
    # Expired holdoffs rejoin.
    c._holdoff[1] = time.monotonic() - 0.001
    assert 1 in c._order(0, None)
    # All nodes held off: desperation wins over an empty rotation.
    now = time.monotonic()
    for i in range(3):
        c._holdoff[i] = now + 60.0
    assert len(c._order(0, None)) == 3


def test_put_429_holds_that_node_out():
    """One saturated engine answers 429+Retry-After; the write lands
    on a peer and the NEXT request never dials the saturated node."""
    c = _client()
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if node == 0:
            return 429, {"Retry-After": "9.000"}, "overloaded (engine)"
        return 204, {}, ""

    c.raw = fake_raw
    c._rr = 0                               # rotation starts at node 0
    c._hints_at = time.monotonic()          # suppress the hint sweep
    assert c.put("insert into kv values ('a','1')",
                 deadline_s=5) is None
    assert calls == [0, 1]
    calls.clear()
    c.put("insert into kv values ('b','2')", deadline_s=5)
    assert 0 not in calls and len(calls) == 1


def test_cluster_wide_429_is_bounded_no_retry_storm():
    """Every node refusing must produce Unavailable after a BOUNDED
    number of attempts — backoff between rotations, not a tight loop
    hammering the cluster it just learned is saturated."""
    c = _client(backoff_s=0.01, backoff_cap_s=0.02)
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        return 429, {"Retry-After": "0.050"}, "overloaded (engine)"

    c.raw = fake_raw
    c._hints_at = time.monotonic()          # suppress the hint sweep
    with pytest.raises(Unavailable) as ei:
        c.put("insert into kv values ('c','3')", deadline_s=0.05)
    assert "429" in str(ei.value)
    # 50 ms of deadline with backoff between rotations: a handful of
    # rounds over 3 nodes, nowhere near a storm.
    assert len(calls) <= 30


def test_expired_deadline_fails_fast_without_round_trip():
    c = _client()

    def fake_raw(*a, **k):
        raise AssertionError("network dialled past the deadline")

    c.raw = fake_raw
    c.raw_replica = fake_raw
    c._hints_at = time.monotonic()          # suppress the hint sweep
    with pytest.raises(Unavailable):
        c.put("insert into kv values ('d','4')", deadline_s=0)
    with pytest.raises(Unavailable):
        c.get("select v from kv", deadline_s=0)


def test_requests_carry_remaining_deadline_header():
    """End-to-end propagation starts at the client: every attempt
    advertises its REMAINING budget so the server can shed before
    paying WAL cost."""
    c = _client()
    seen = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        seen.append(dict(headers or {}))
        return (204, {}, "") if method == "PUT" else (200, {}, "|1|")

    c.raw = fake_raw
    c._hints_at = time.monotonic()          # suppress the hint sweep
    c.put("insert into kv values ('e','5')", deadline_s=2.0)
    c.get("select v from kv", linear=True, deadline_s=2.0)
    for h in seen:
        ms = int(h["X-Raft-Deadline-Ms"])
        assert 1 <= ms <= 2000
