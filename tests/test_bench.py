"""Benchmark-harness smoke tests (SURVEY.md §4 lists "no benchmark
tests" among the reference's gaps to close): a micro-scale bench child
must produce a well-formed result with nonzero commits, and the parent's
JSON contract must hold even when everything fails.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=timeout, cwd=REPO)
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no stdout; stderr tail: {r.stderr[-800:]}"
    return r, json.loads(lines[-1])


def test_headline_child_micro():
    r, out = run_bench({
        "BENCH_CHILD": "1", "BENCH_PLATFORM": "cpu", "BENCH_GROUPS": "64",
        "BENCH_TICKS": "20", "BENCH_REPEATS": "1", "BENCH_SKIP_SWEEP": "1",
        "BENCH_E": "8"})
    assert r.returncode == 0, r.stderr[-800:]
    assert out["metric"] == "raft_commits_per_sec"
    assert out["unit"] == "commits/s"
    assert out["value"] > 0
    assert out["platform"] == "cpu"
    # Pipelined replication: the marked batch commits in ~3 ticks.
    assert out.get("p50_sat_ms") is not None


def test_durable_child_micro():
    r, out = run_bench({
        "BENCH_CHILD": "1", "BENCH_PLATFORM": "cpu",
        "BENCH_CONFIG": "durable", "BENCH_GROUPS": "32",
        "BENCH_TICKS": "8", "BENCH_REPEATS": "1"})
    assert r.returncode == 0, r.stderr[-800:]
    assert out["value"] > 0
    phases = out["durable_phase_ms"]
    assert set(phases) == {"stage", "device", "wal", "send", "publish"}


def test_parent_emits_json_when_all_attempts_fail():
    """The driver contract: ONE parseable JSON line and exit 0, no
    matter what.  BENCH_GROUPS=-1 makes every measurement child die in
    RaftConfig validation (and short timeouts kill wedged probes), so
    the parent must reach its emergency platform="none" emit."""
    r, out = run_bench({
        "BENCH_PROBE_TIMEOUT_S": "3", "BENCH_ATTEMPT_TIMEOUT_S": "30",
        "BENCH_TOTAL_BUDGET_S": "90", "BENCH_SKIP_DURABLE": "1",
        "BENCH_SKIP_SWEEP": "1", "BENCH_GROUPS": "-1",
        "BENCH_TICKS": "20", "BENCH_REPEATS": "1", "BENCH_E": "8"},
        timeout=480)
    assert r.returncode == 0
    assert out["metric"] == "raft_commits_per_sec"
    assert out["platform"] == "none"
    assert out["value"] == 0.0
