"""Benchmark-harness smoke tests (SURVEY.md §4 lists "no benchmark
tests" among the reference's gaps to close): a micro-scale bench child
must produce a well-formed result with nonzero commits, and the parent's
JSON contract must hold even when everything fails.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=timeout, cwd=REPO)
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no stdout; stderr tail: {r.stderr[-800:]}"
    return r, json.loads(lines[-1])


def test_headline_child_micro():
    r, out = run_bench({
        "BENCH_CHILD": "1", "BENCH_PLATFORM": "cpu", "BENCH_GROUPS": "64",
        "BENCH_TICKS": "20", "BENCH_REPEATS": "1", "BENCH_SKIP_SWEEP": "1",
        "BENCH_E": "8"})
    assert r.returncode == 0, r.stderr[-800:]
    assert out["metric"] == "raft_commits_per_sec"
    assert out["unit"] == "commits/s"
    assert out["value"] > 0
    assert out["platform"] == "cpu"
    # Pipelined replication: the marked batch commits in ~3 ticks.
    assert out.get("p50_sat_ms") is not None


def test_durable_child_micro():
    r, out = run_bench({
        "BENCH_CHILD": "1", "BENCH_PLATFORM": "cpu",
        "BENCH_CONFIG": "durable", "BENCH_GROUPS": "32",
        "BENCH_TICKS": "8", "BENCH_REPEATS": "1"})
    assert r.returncode == 0, r.stderr[-800:]
    assert out["value"] > 0
    phases = out["durable_phase_ms"]
    assert set(phases) == {"stage", "device", "wal", "send", "publish"}


def test_durable_fused_child_records_phase_profile():
    """The durable fused rung's extras must carry the tick-phase
    profile summary (fsync/dispatch/publish shares + histograms) so
    the BENCH_*.json trajectory shows WHY a rung moved."""
    r, out = run_bench({
        "BENCH_CHILD": "1", "BENCH_PLATFORM": "cpu",
        "BENCH_CONFIG": "durable", "BENCH_DURABLE_MODE": "fused",
        "BENCH_GROUPS": "32", "BENCH_TICKS": "8",
        "BENCH_REPEATS": "1", "BENCH_E": "8"})
    assert r.returncode == 0, r.stderr[-800:]
    assert out["value"] > 0
    pp = out["phase_profile"]
    assert {"fsync_share", "dispatch_share", "publish_share"} <= set(pp)
    shares = sum(v for k, v in pp.items() if k.endswith("_share"))
    assert 0.99 <= shares <= 1.01, pp
    assert "fsync" in pp["phases"], pp["phases"]
    assert "p99_ms" in pp["phases"]["fsync"]


def test_parent_recovers_tunnel_on_late_reprobe(tmp_path):
    """VERDICT r3 task 8 (the round-3 failure mode): both early probes
    hang, but the tunnel recovers mid-budget — the late re-probe must
    notice and the parent must still produce a ladder headline instead
    of the CPU fallback."""
    state = str(tmp_path / "probe_state")
    r, out = run_bench({
        "BENCH_FAKE_PROBE_PLAN": "timeout,timeout,tpu:cpu",
        "BENCH_FAKE_PROBE_STATE": state,
        # Probe timeout must comfortably cover interpreter startup (~5 s
        # under load) so the fake-plan branch is reached; the scripted
        # "timeout" steps sleep 3600 s and still trip it.
        "BENCH_PROBE_TIMEOUT_S": "30", "BENCH_ATTEMPT_TIMEOUT_S": "120",
        "BENCH_TOTAL_BUDGET_S": "400", "BENCH_SKIP_DURABLE": "1",
        "BENCH_SKIP_SWEEP": "1", "BENCH_SKIP_RULES": "1",
        "BENCH_LADDER": "64", "BENCH_TICKS": "20", "BENCH_REPEATS": "1",
        "BENCH_E": "8"}, timeout=480)
    assert r.returncode == 0, r.stderr[-800:]
    # Ladder headline, not the no-TPU fallback: the late probe reported
    # a live device, so the rung children ran (on this host's real CPU
    # backend — only the probe outcome is scripted).
    assert out["value"] > 0
    assert out.get("ladder") == {"64": out["value"]}, out
    assert "tpu_probe" not in out
    assert "probe-late" in r.stderr
    # All three probes consumed: two early (timed out) + one late.
    with open(state) as f:
        assert f.read().strip() == "3"


def test_ledger_append_and_last_good(tmp_path, monkeypatch):
    """Every successful TPU child appends to TPU_RUNS.jsonl; the
    CPU-fallback parent surfaces the newest entry as last_good_tpu."""
    import bench

    path = str(tmp_path / "TPU_RUNS.jsonl")
    monkeypatch.setattr(bench, "TPU_RUNS_PATH", path)
    assert bench._ledger_last_good() is None          # missing file
    bench._ledger_append({"platform": "cpu", "value": 1.0})
    assert bench._ledger_last_good() is None          # no TPU entries
    bench._ledger_append({"platform": "tpu", "value": 2.0, "ts": "t1"})
    bench._ledger_append({"platform": "tpu", "value": 3.0, "ts": "t2"})
    with open(path, "a") as f:
        f.write("not json\n")                         # corruption tolerated
    got = bench._ledger_last_good()
    assert got == {"platform": "tpu", "value": 3.0, "ts": "t2"}


def test_committed_ledger_has_tpu_evidence():
    """On-device evidence must stay committed and parseable (VERDICT r3
    missing #1: the only TPU proof used to be a gitignored stray log).
    The newest entry may be any config (latency/durable children append
    too); the headline proof just has to exist somewhere in the ledger."""
    import bench

    got = bench._ledger_last_good()
    assert got is not None and got["platform"] == "tpu"
    headline = []
    with open(bench.TPU_RUNS_PATH) as f:
        for line in f:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("platform") == "tpu" and d.get("config") == "headline":
                headline.append(d)
    assert any(d.get("value", 0) > 1e8 for d in headline)


def test_parent_emits_json_when_all_attempts_fail():
    """The driver contract: ONE parseable JSON line and exit 0, no
    matter what.  BENCH_GROUPS=-1 makes every measurement child die in
    RaftConfig validation (and short timeouts kill wedged probes), so
    the parent must reach its emergency platform="none" emit."""
    r, out = run_bench({
        "BENCH_PROBE_TIMEOUT_S": "3", "BENCH_ATTEMPT_TIMEOUT_S": "30",
        "BENCH_TOTAL_BUDGET_S": "90", "BENCH_SKIP_DURABLE": "1",
        "BENCH_SKIP_SWEEP": "1", "BENCH_GROUPS": "-1",
        "BENCH_TICKS": "20", "BENCH_REPEATS": "1", "BENCH_E": "8"},
        timeout=480)
    assert r.returncode == 0
    assert out["metric"] == "raft_commits_per_sec"
    assert out["platform"] == "none"
    assert out["value"] == 0.0


def test_ledger_regression_tripwire(tmp_path, monkeypatch):
    """_ledger_last_matching finds the newest same-shape TPU entry so a
    >20% drop vs the committed record can be flagged (VERDICT r4 task
    6: round-4's numbers regressed silently)."""
    import bench

    path = str(tmp_path / "TPU_RUNS.jsonl")
    monkeypatch.setattr(bench, "TPU_RUNS_PATH", path)
    shape = {"config": "headline", "groups": "32768", "e": "32"}
    assert bench._ledger_last_matching(shape) is None
    bench._ledger_append(dict(shape, platform="tpu", value=100.0,
                              ts="t1"))
    bench._ledger_append({"config": "headline", "groups": "1000",
                          "e": "32", "platform": "tpu", "value": 5.0,
                          "ts": "t2"})                 # other shape
    bench._ledger_append(dict(shape, platform="cpu", value=1.0,
                              ts="t3"))                # wrong platform
    got = bench._ledger_last_matching(shape)
    assert got is not None and got["value"] == 100.0
    bench._ledger_append(dict(shape, platform="tpu", value=250.0,
                              ts="t4"))
    assert bench._ledger_last_matching(shape)["value"] == 250.0
