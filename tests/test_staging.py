"""Inbox staging arbitration across the two delivery forms.

A peer may speak columnar (ColRecs) and record (AppendRec) forms in any
mix; the staging contract is "newest message per (group, src, slot)
wins" regardless of form, and the ReadIndex seq echo must be bound to
the request the device actually processes (never to a response's seq,
which lives in the SENDER's tick numberspace).  These are regression
tests for a leadership-churn hazard: a columnar RESP landing in the
same staging window as the same peer's record REQ must not leave the
inbox answering the REQ while echoing the RESP's (much larger) seq —
that inflates the peer's _resp_echo past rounds it ever sent and lets
read_ready() confirm a ReadIndex with no real quorum round.
"""
import numpy as np
import pytest

from raftsql_tpu.config import MSG_REQ, MSG_RESP, RaftConfig
from raftsql_tpu.core.step import unpack_inbox
from raftsql_tpu.runtime.node import RaftNode
from raftsql_tpu.transport.base import AppendRec, ColRecs, TickBatch
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport


def build_inbox(node):
    """node._build_inbox(), unpacked to the named Inbox view (the build
    returns the packed [G, P, IB_NCOLS+E] array — core/step.py)."""
    packed, apps = node._build_inbox()
    return unpack_inbox(packed), apps


@pytest.fixture
def node(tmp_path):
    cfg = RaftConfig(num_groups=2, num_peers=3, tick_interval_s=1.0,
                     election_ticks=10, log_window=32,
                     max_entries_per_msg=4)
    n = RaftNode(1, 3, cfg, LoopbackTransport(LoopbackHub()),
                 data_dir=str(tmp_path / "raftsql-1"))
    yield n
    n.stop()


def col_resp(group: int, seq: int, term: int = 7) -> ColRecs:
    c = ColRecs()
    c.a_group = np.array([group], np.int32)
    c.a_type = np.array([MSG_RESP], np.int32)
    c.a_term = np.array([term], np.int32)
    c.a_prev_idx = np.zeros(1, np.int32)
    c.a_prev_term = np.zeros(1, np.int32)
    c.a_commit = np.zeros(1, np.int32)
    c.a_success = np.ones(1, np.int32)
    c.a_match = np.array([3], np.int32)
    c.a_seq = np.array([seq], np.int64)
    return c


def col_req(group: int, seq: int, term: int = 7) -> ColRecs:
    c = col_resp(group, seq, term)
    c.a_type = np.array([MSG_REQ], np.int32)
    c.a_success = np.zeros(1, np.int32)
    c.a_match = np.zeros(1, np.int32)
    return c


def rec_req(group: int, seq: int, term: int = 7) -> AppendRec:
    return AppendRec(group=group, type=MSG_REQ, term=term, prev_idx=2,
                     prev_term=term, ent_terms=[term],
                     payloads=[b"x"], seq=seq)


def test_record_req_then_columnar_resp_resp_wins(node):
    """Record REQ staged first, columnar RESP arrives later for the same
    slot: the RESP (newer) must win the inbox, and its seq must NOT leak
    into the echo array (the old code answered the REQ with the RESP's
    seq — the stale-linearizable-read hazard)."""
    src = 2  # node_id 2 -> slot 1
    node._deliver(src, TickBatch(appends=[rec_req(0, seq=5)]))
    node._deliver(src, TickBatch(cols=col_resp(0, seq=999)))
    inbox, apps = build_inbox(node)
    assert int(np.asarray(inbox.a_type)[0, 1]) == MSG_RESP
    # The displaced record is gone from the WAL-phase dict too.
    assert (0, 1) not in apps
    # No REQ in the slot => nothing to echo.
    assert int(node._tick_seq[0, 1]) == 0


def test_columnar_resp_then_record_req_req_and_its_seq_win(node):
    """Columnar RESP first, record REQ later: the REQ wins, and the echo
    seq must be the REQ's own (5), not the response's 999."""
    src = 2
    node._deliver(src, TickBatch(cols=col_resp(0, seq=999)))
    node._deliver(src, TickBatch(appends=[rec_req(0, seq=5)]))
    inbox, apps = build_inbox(node)
    assert int(np.asarray(inbox.a_type)[0, 1]) == MSG_REQ
    assert (0, 1) in apps
    assert int(node._tick_seq[0, 1]) == 5
    # The RESP's ReadIndex bookkeeping still registered (independent of
    # slot arbitration).
    assert int(node._resp_echo[0, 1]) == 999


def test_columnar_resp_seq_never_enters_echo_array(node):
    """A columnar RESP alone must leave the seq-echo array untouched:
    only REQ rows may set the echo binding."""
    node._deliver(2, TickBatch(cols=col_resp(1, seq=4242)))
    build_inbox(node)
    assert int(node._tick_seq[1, 1]) == 0


def test_columnar_req_seq_binds(node):
    node._deliver(2, TickBatch(cols=col_req(1, seq=17)))
    inbox, _ = build_inbox(node)
    assert int(np.asarray(inbox.a_type)[1, 1]) == MSG_REQ
    assert int(node._tick_seq[1, 1]) == 17


def test_record_req_then_newer_columnar_heartbeat_wins(node):
    """Same-form semantics preserved across forms: a newer columnar
    heartbeat REQ displaces an older record REQ (and its entries)."""
    src = 3  # slot 2
    node._deliver(src, TickBatch(appends=[rec_req(0, seq=5)]))
    node._deliver(src, TickBatch(cols=col_req(0, seq=6)))
    inbox, apps = build_inbox(node)
    assert int(np.asarray(inbox.a_type)[0, 2]) == MSG_REQ
    assert int(np.asarray(inbox.a_n)[0, 2]) == 0      # heartbeat, no ents
    assert (0, 2) not in apps
    assert int(node._tick_seq[0, 2]) == 6


def test_windows_reset_between_ticks(node):
    node._deliver(2, TickBatch(cols=col_req(0, seq=17)))
    build_inbox(node)
    inbox, apps = build_inbox(node)
    assert int(np.asarray(inbox.a_type)[0, 1]) == 0
    assert not apps
    assert int(node._tick_seq[0, 1]) == 0
