"""HTTP API tests: reference semantics (PUT/GET/405, httpapi.go:36-66)
plus the multi-group and robustness extensions.  Every test runs
against BOTH serving planes — the threaded stdlib port (api/http.py)
and the event-loop redesign (api/aio.py) — the parametrized fixture is
the parity contract between them."""
import http.client

import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.api.aio import AioSQLServer
from raftsql_tpu.api.http import SQLServer
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport

TIMEOUT = 30.0


@pytest.fixture(params=["threaded", "aio"])
def server(request, tmp_path):
    """Single-node cluster (self-elects) behind a real HTTP server."""
    cfg = RaftConfig(num_groups=2, num_peers=1, tick_interval_s=0.005,
                     log_window=64, max_entries_per_msg=4)
    pipe = RaftPipe.create(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                           data_dir=str(tmp_path / "raftsql-1"))
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        str(tmp_path / f"api-g{g}.db")), pipe, num_groups=2)
    srv_cls = SQLServer if request.param == "threaded" else AioSQLServer
    srv = srv_cls(0, rdb, host="127.0.0.1", timeout_s=TIMEOUT)
    srv.start()
    yield srv
    srv.stop()
    rdb.close()


def req(srv, method, body=b"", headers=None, conn=None):
    own = conn is None
    if own:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    conn.request(method, "/", body=body, headers=headers or {})
    r = conn.getresponse()
    data = r.read()
    if own:
        conn.close()
    return r, data


def test_put_get_roundtrip(server):
    r, _ = req(server, "PUT", b"CREATE TABLE main.t (v text)")
    assert r.status == 204
    r, _ = req(server, "PUT", b'INSERT INTO main.t (v) VALUES ("x")')
    assert r.status == 204
    r, data = req(server, "GET", b"SELECT * FROM main.t")
    assert r.status == 200 and data == b"|x|\n"


def test_group_header_out_of_range_is_400(server):
    for g in ("-1", "5", "junk"):
        r, data = req(server, "PUT", b"CREATE TABLE main.bad (v text)",
                      headers={"X-Raft-Group": g})
        assert r.status == 400, (g, r.status, data)
    r, data = req(server, "GET", b"SELECT 1",
                  headers={"X-Raft-Group": "7"})
    assert r.status == 400


def test_method_not_allowed_keeps_connection_usable(server):
    """405 must drain the request body and emit one `Allow: PUT, GET`
    header, or the keep-alive stream parses body bytes as the next
    request (reference semantics: httpapi.go:63-66)."""
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    try:
        r, _ = req(server, "POST", b"some body that must be drained",
                   conn=conn)
        assert r.status == 405
        assert r.getheader("Allow") == "PUT, GET"
        # Same connection must still serve a clean request.
        r, data = req(server, "GET", b"SELECT 42", conn=conn)
        assert r.status == 200 and data == b"|42|\n"
    finally:
        conn.close()


def test_metrics_endpoint(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        data = r.read()
    finally:
        conn.close()
    assert r.status == 200
    import json
    m = json.loads(data)
    assert {"ticks", "proposals", "commits", "msgs_sent"} <= set(m)


def test_healthz_endpoint(server):
    """GET /healthz (both planes): id, per-group role / leader hint /
    term / applied — the readiness probe the process-plane nemesis
    uses to detect restart completion without a write."""
    import json
    import time
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    try:
        deadline = time.monotonic() + 15.0
        while True:
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            doc = json.loads(r.read())
            assert r.status == 200
            assert doc["id"] == 1 and doc["ready"] is True
            assert set(doc["groups"]) == {"0", "1"}
            row = doc["groups"]["0"]
            assert {"role", "leader", "term", "commit",
                    "applied"} <= set(row)
            if row["role"] == "leader":     # single node self-elects
                assert row["leader"] == 1 and row["term"] >= 1
                break
            assert time.monotonic() < deadline, doc
            time.sleep(0.05)
    finally:
        conn.close()


def test_put_retry_token_applies_exactly_once(server):
    """X-Raft-Retry-Token (both planes): re-sending a PUT with the same
    token must ACK normally but apply once — the envelope dedup rides
    the token across client retries, so retry-after-accept is safe
    (api/client.py's whole premise)."""
    r, _ = req(server, "PUT", b"CREATE TABLE main.rt (v text)")
    assert r.status == 204
    hdr = {"X-Raft-Retry-Token": "00c0ffee00c0ffee"}
    for _ in range(3):
        r, data = req(server, "PUT",
                      b"INSERT INTO main.rt (v) VALUES ('once')",
                      headers=hdr)
        assert r.status == 204, (r.status, data)
    # A DIFFERENT token is a different logical request: applies again.
    r, _ = req(server, "PUT",
               b"INSERT INTO main.rt (v) VALUES ('once')",
               headers={"X-Raft-Retry-Token": "00000000deadbeef"})
    assert r.status == 204
    r, data = req(server, "GET", b"SELECT count(*) FROM main.rt")
    assert r.status == 200 and data == b"|2|\n", data


def test_concurrent_puts_all_ack(server):
    """Many keep-alive connections proposing at once: every PUT must
    block until ITS commit+apply and ack 204 (httpapi.go:38-49 under
    raftsql_test.go:79-90-style concurrency); the applied row count
    equals the acked request count."""
    import threading

    r, _ = req(server, "PUT", b"CREATE TABLE main.c (v text)")
    assert r.status == 204
    n_threads, per = 12, 8
    errs: list = []

    def worker(i):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=30)
            try:
                for k in range(per):
                    r, data = req(server, "PUT",
                                  f"INSERT INTO main.c (v) VALUES"
                                  f" ('t{i}_{k}')".encode(), conn=conn)
                    if r.status != 204:
                        errs.append((i, k, r.status, data))
            finally:
                conn.close()
        except Exception as e:          # noqa: BLE001 - must surface
            errs.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:3]
    r, data = req(server, "GET", b"SELECT count(*) FROM main.c")
    assert r.status == 200
    assert data == f"|{n_threads * per}|\n".encode()


def test_pipelined_requests_answer_in_order(server):
    """Two requests written back-to-back before any response is read:
    both planes must answer in order on the same connection (the aio
    state machine buffers the second while the first is in flight)."""
    import socket

    body1 = b"CREATE TABLE main.p (v text)"
    body2 = b"INSERT INTO main.p (v) VALUES ('x')"
    raw = b"".join(
        b"PUT / HTTP/1.1\r\nHost: t\r\nContent-Length: "
        + str(len(b)).encode() + b"\r\n\r\n" + b
        for b in (body1, body2))
    s = socket.create_connection(("127.0.0.1", server.port), timeout=30)
    try:
        s.sendall(raw)
        buf = b""
        deadline = 30
        import time
        t0 = time.monotonic()
        while buf.count(b"HTTP/1.1 ") < 2:    # any two responses
            assert time.monotonic() - t0 < deadline, buf
            chunk = s.recv(4096)
            assert chunk, buf
            buf += chunk
        assert buf.count(b"HTTP/1.1 204") == 2, buf
    finally:
        s.close()
    r, data = req(server, "GET", b"SELECT count(*) FROM main.p")
    assert r.status == 200 and data == b"|1|\n"


def test_group_header_routes_to_second_group(server):
    r, _ = req(server, "PUT", b"CREATE TABLE main.g1 (v text)",
               headers={"X-Raft-Group": "1"})
    assert r.status == 204
    # group 0 must not see group 1's table.
    r, data = req(server, "GET", b"SELECT * FROM main.g1")
    assert r.status == 400
    r, data = req(server, "GET", b"SELECT * FROM main.g1",
                  headers={"X-Raft-Group": "1"})
    assert r.status == 200


def test_put_propose_failure_answers_400(server, monkeypatch):
    """An unexpected exception from rdb.propose (e.g. pipe/queue closed
    during shutdown) must answer 400, not kill the handler and leave
    the connection hanging with busy=True (ADVICE r5 low — the aio
    plane's _do_put previously called propose outside any try)."""
    def boom(self, query, group=0, token=None):
        raise RuntimeError("injected propose failure")

    # Class-level: the threaded plane closes over the RaftDB instance
    # rather than exposing it.
    monkeypatch.setattr(RaftDB, "propose", boom)
    r, data = req(server, "PUT", b"INSERT INTO main.t VALUES (1)")
    assert r.status == 400
    assert b"injected propose failure" in data
    # The server keeps serving once the fault clears.
    monkeypatch.undo()
    r, _ = req(server, "PUT", b"CREATE TABLE main.after_fault (v text)")
    assert r.status == 204
