"""The lease-based read plane (config.lease_ticks) and its chaos
falsification harness.

Covers the PR's acceptance spine:
  - leader leases serve linearizable reads without a quorum round, and
    metrics attribute every read to its mode;
  - a partitioned leader's lease EXPIRES (never a silent stale read),
    and the degraded path surfaces typed, retryable errors within the
    request timeout;
  - session (X-Raft-Session) and follower watermark reads give
    read-your-writes at any replica;
  - ReadIndex/lease quorum confirmation under JOINT consensus needs
    both halves of the config;
  - the read nemesis (chaos/scenarios.py ReadNemesisRunner) and the
    lease FALSIFICATION pair: a deliberately mis-sized lease bound
    under 4x clock skew must be CAUGHT by the read-linearizability
    invariant, and the same schedule with a correct bound must pass.
"""
import os
import time

import numpy as np
import pytest

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import NotLeaderError, RaftDB, ReadTimeout
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.loopback import (FaultPlan, LoopbackHub,
                                            LoopbackTransport)

TICK = 0.005
TIMEOUT = 30.0


@pytest.fixture
def lease_cluster(tmp_path):
    """3-node loopback cluster with leases ON, sized safely for the
    lockstep (rate-1) clock: lease 6 + skew 1 < election 10."""
    faults = FaultPlan()
    hub = LoopbackHub(faults=faults)
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                     election_ticks=10, log_window=64,
                     max_entries_per_msg=4,
                     lease_ticks=6, max_clock_skew=1)
    dbs = []
    for i in range(3):
        pipe = RaftPipe.create(
            i + 1, 3, cfg, LoopbackTransport(hub),
            data_dir=os.path.join(str(tmp_path), f"raftsql-{i + 1}"))
        dbs.append(RaftDB(
            lambda g, i=i: SQLiteStateMachine(
                os.path.join(str(tmp_path), f"db-{i}.db")),
            pipe, num_groups=1))
    yield dbs, faults
    for db in dbs:
        try:
            db.close()
        except Exception:
            pass


def _leader(dbs, timeout=TIMEOUT) -> int:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for i, db in enumerate(dbs):
            if db.pipe.node._last_role[0] == LEADER:
                return i
        time.sleep(0.02)
    raise AssertionError("no leader elected")


def test_lease_serves_linear_reads(lease_cluster):
    """At a healthy leader, linearizable reads ride the lease (no
    quorum round), read-your-writes holds, and the /metrics read
    counters attribute the path."""
    dbs, _ = lease_cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = _leader(dbs)
    node = dbs[lead].pipe.node
    for k in range(4):
        assert dbs[lead].propose(
            f"INSERT INTO t (v) VALUES ('k{k}')").wait(TIMEOUT) is None
        got = dbs[lead].query("SELECT count(*) FROM t", mode="linear",
                              timeout=TIMEOUT)
        assert got == f"|{k + 1}|\n", got
    m = node.metrics
    # At a healthy heartbeat-confirmed leader the lease covers most of
    # these reads; any degrade must have gone through ReadIndex, never
    # served stale.
    assert m.reads_lease + m.reads_read_index == 4
    assert m.reads_lease >= 1
    assert m.lease_grants >= 1
    # The metrics doc nests them under "reads" (prom round-trip).
    doc = dbs[lead].metrics()
    assert doc["reads"]["lease"] == m.reads_lease


def test_lease_expires_under_partition_typed_timeout(lease_cluster):
    """A leader cut from its quorum must LOSE its lease within the
    bound (no silent stale read), and the degraded ReadIndex round
    must surface a TYPED retryable error within the request timeout —
    the bounded-poll-loop satellite."""
    dbs, faults = lease_cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = _leader(dbs)
    node = dbs[lead].pipe.node
    # Healthy: the lease is live.
    dbs[lead].query("SELECT count(*) FROM t", mode="linear",
                    timeout=TIMEOUT)
    faults.isolate(lead + 1, range(1, 4))
    # Wait out the lease bound (lease_ticks + skew, in ticks) plus the
    # in-flight echo window.
    time.sleep(30 * TICK)
    assert node.lease_read(0) is None, \
        "partitioned leader still claims a lease past its bound"
    t0 = time.monotonic()
    with pytest.raises((TimeoutError, NotLeaderError)) as ei:
        dbs[lead].query("SELECT count(*) FROM t", mode="linear",
                        timeout=1.5)
    took = time.monotonic() - t0
    assert took < 5.0, f"read poll did not respect its timeout ({took})"
    if isinstance(ei.value, TimeoutError):
        # The typed class names the stalled phase for client logs.
        assert isinstance(ei.value, ReadTimeout)
        assert ei.value.phase in ("confirm", "read_index")
    assert node.metrics.lease_expiries >= 1
    faults.heal()


def test_session_read_your_writes_any_replica(lease_cluster):
    """A session read presenting the write's watermark must see it at
    ANY replica — the X-Raft-Session contract."""
    dbs, _ = lease_cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = _leader(dbs)
    assert dbs[lead].propose(
        "INSERT INTO t (v) VALUES ('mine')").wait(TIMEOUT) is None
    wm = dbs[lead].watermark(0)
    assert wm >= 2
    for i in range(3):
        got = dbs[i].query("SELECT count(*) FROM t", mode="session",
                           watermark=wm, timeout=TIMEOUT)
        assert got == "|1|\n", (i, got)
    m = dbs[(lead + 1) % 3].pipe.node.metrics
    assert m.reads_session >= 1


def test_follower_mode_reads_at_commit_watermark(lease_cluster):
    """mode="follower": the replica serves once its apply reaches its
    OWN commit watermark — fresher than a stale local read, no leader
    round.  A follower that has replicated the write must return it."""
    dbs, _ = lease_cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = _leader(dbs)
    assert dbs[lead].propose(
        "INSERT INTO t (v) VALUES ('x')").wait(TIMEOUT) is None
    follower = (lead + 1) % 3
    deadline = time.monotonic() + TIMEOUT
    while True:
        got = dbs[follower].query("SELECT count(*) FROM t",
                                  mode="follower", timeout=TIMEOUT)
        if got == "|1|\n":
            break
        if time.monotonic() > deadline:
            raise AssertionError(f"follower never caught up: {got!r}")
        time.sleep(0.05)
    assert dbs[follower].pipe.node.metrics.reads_follower >= 1


def test_unknown_read_mode_rejected(lease_cluster):
    dbs, _ = lease_cluster
    with pytest.raises(ValueError, match="unknown read mode"):
        dbs[0].query("SELECT 1", mode="strong")


def test_joint_consensus_confirmation_needs_both_halves():
    """ReadIndex confirmation AND the lease quorum clock under a joint
    C_old,new config must have a majority of BOTH masks — a read
    served on one half alone could miss a leader the other half
    elected mid-membership-change."""
    from raftsql_tpu.membership import MembershipManager
    mm = MembershipManager(4, 1, initial_voters=(0, 1, 2))
    entry = mm.make_change(0, "add_learner", 3)
    assert mm.apply(0, 5, entry) is not None
    entry = mm.make_change(0, "promote", 3)  # -> joint {0,1,2,3}/{0,1,2}
    assert mm.apply(0, 6, entry) is not None
    assert mm.config(0).is_joint

    # quorum_confirmed: self=0.  {0,1} confirms old (2 of {0,1,2}) but
    # not new (2 of 4 needs 3) -> must NOT confirm.
    ok = np.array([False, True, False, False])
    assert not mm.quorum_confirmed(0, ok, 0)
    # {0,1,3} confirms both halves.
    ok = np.array([False, True, False, True])
    assert mm.quorum_confirmed(0, ok, 0)

    # quorum_nth (the lease clock): the min of both masks' majorities.
    vals = np.array([100, 90, 0, 95])        # peer 2 never confirmed
    # old {0,1,2}: 2nd largest of (100,90,0) = 90; new {0,1,2,3}: 3rd
    # largest of (100,90,0,95) = 90.
    assert mm.quorum_nth(0, vals) == 90
    vals = np.array([100, 0, 0, 95])
    # old majority falls to 0 -> the stale half gates the lease.
    assert mm.quorum_nth(0, vals) == 0


def test_masked_lease_kernel_joint_min():
    """Device-side: the joint lease clock is the min of both masks'
    quorum values (core/step.py Phase 8b uses exactly this pair)."""
    import jax.numpy as jnp
    from raftsql_tpu.ops.quorum import masked_quorum_match_index
    resp = jnp.asarray([[50, 40, 0, 45]])
    new = jnp.asarray([[True, True, True, True]])
    old = jnp.asarray([[True, True, True, False]])
    q = jnp.minimum(masked_quorum_match_index(resp, new),
                    masked_quorum_match_index(resp, old))
    # new: 3rd largest of (50,40,0,45)=40; old: 2nd of (50,40,0)=40.
    assert int(q[0]) == 40


def test_fused_device_lease_lifecycle(tmp_path):
    """The fused runtime's [G] lease column: a healthy leader's device
    lease stays ahead of the step clock; with leases disabled the
    column is all zero (the compiled-in-but-disabled contract)."""
    from raftsql_tpu.runtime.fused import FusedClusterNode
    for lease_ticks in (4, 0):
        cfg = RaftConfig(num_groups=2, num_peers=3, log_window=32,
                         max_entries_per_msg=4, election_ticks=10,
                         heartbeat_ticks=1, tick_interval_s=0.0,
                         lease_ticks=lease_ticks, max_clock_skew=0)
        node = FusedClusterNode(
            cfg, os.path.join(str(tmp_path), f"lease{lease_ticks}"))
        try:
            for _ in range(60):
                node.tick()
            node.publish_flush()
            lc = node._lease_col
            assert lc is not None
            if lease_ticks == 0:
                assert (lc == 0).all()
                assert node.lease_read(0) is None
            else:
                hints = node._hints
                assert (hints >= 0).all()
                for g in range(2):
                    p = int(hints[g])
                    assert int(lc[p, g]) > node._device_steps, \
                        (g, lc[:, g], node._device_steps)
                    assert node.lease_read(g) is not None
                assert node.metrics.lease_grants >= 2
        finally:
            node.stop()


def test_lease_falsification_broken_bound_is_caught(tmp_path):
    """THE sensitivity proof: a lease sized for zero skew, run under
    4x clock skew behind a leader partition, must produce a stale
    lease read that the read-linearizability invariant CATCHES."""
    from raftsql_tpu.chaos.invariants import InvariantViolation
    from raftsql_tpu.chaos.scenarios import ReadNemesisRunner
    from raftsql_tpu.chaos.schedule import falsification_plan
    os.environ["RAFTSQL_FLIGHT_DIR"] = str(tmp_path)
    try:
        plan = falsification_plan(0, broken=True)
        with pytest.raises(InvariantViolation, match="STALE"):
            ReadNemesisRunner(plan,
                              os.path.join(str(tmp_path), "bad")).run()
    finally:
        os.environ.pop("RAFTSQL_FLIGHT_DIR", None)


@pytest.mark.slow
def test_lease_falsification_correct_bound_passes(tmp_path):
    """The control arm: the SAME schedule with a correctly sized bound
    passes, with leases actually granted — the invariant keys on the
    bound, not on chaos in general."""
    from raftsql_tpu.chaos.scenarios import ReadNemesisRunner
    from raftsql_tpu.chaos.schedule import falsification_plan
    plan = falsification_plan(0, broken=False)
    r = ReadNemesisRunner(plan, os.path.join(str(tmp_path), "ok")).run()
    assert r["lease_reads"] > 0
    assert r["reads_checked"] > 0


@pytest.mark.slow
def test_read_nemesis_family_deterministic(tmp_path):
    """The seeded read nemesis: every read family fires, invariants
    hold, and two runs of one seed digest-match (the `make
    chaos-reads` gate in miniature)."""
    from raftsql_tpu.chaos.scenarios import ReadNemesisRunner
    from raftsql_tpu.chaos.schedule import generate_reads
    plan = generate_reads(0, ticks=160)
    r1 = ReadNemesisRunner(plan,
                           os.path.join(str(tmp_path), "r1")).run()
    r2 = ReadNemesisRunner(plan,
                           os.path.join(str(tmp_path), "r2")).run()
    assert r1["result_digest"] == r2["result_digest"]
    assert r1["lease_reads"] > 0
    assert r1["session_reads"] > 0
    assert r1["follower_reads"] > 0
    assert r1["reads_by_mode"].get("linear", 0) > 0


@pytest.mark.slow
def test_proc_read_nemesis(tmp_path):
    """Process-plane read nemesis: linear/session/follower HTTP reads
    race real SIGKILLs/stalls/storms; no stale session read, no
    unscripted death."""
    from raftsql_tpu.chaos.proc import ProcReadChaosRunner
    from raftsql_tpu.chaos.schedule import generate_procs
    plan = generate_procs(3, ticks=40)
    r = ProcReadChaosRunner(plan, str(tmp_path)).run()
    assert r["linear_reads"] > 0
    assert r["session_reads"] > 0
    assert r["follower_reads"] > 0
    assert r["stale_session"] == 0
    assert r["unexpected_exits"] == 0
