"""Double-buffered dispatch + WAL group commit (PR 7).

Covers the two durable-plane levers of the serving-stack PR:

  * overlap pipeline (runtime/hostplane.py): crash mid-overlap loses
    exactly the un-externalized pipeline tail — everything published
    survives replay, the stashed tick vanishes atomically, and with
    multi-step dispatch the epoch-erase semantics still hold (an
    uncommitted dispatch whose records ARE durable is dropped on every
    peer);
  * chaos digest stability: the same seeded schedule produces
    bit-identical schedule+result digests with the overlap pipeline on
    and off, and with group commit layered on top;
  * GroupCommitWAL (storage/wal.py): one fsync per barrier round for
    all P peers, per-peer replay split, and bit-identical cluster
    behavior vs the per-peer-file layout.
"""
import queue
import tempfile

import numpy as np
import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.storage import fsio
from raftsql_tpu.storage.wal import GroupCommitWAL


def mkcfg(groups=2):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, tick_interval_s=0.0)


def elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


def _published(node):
    """Everything delivered to peer 0's commit stream so far, WITHOUT
    draining the double-buffer stash (only the async publish queues are
    joined) — the crash tests depend on the stash staying pending."""
    from raftsql_tpu.runtime.db import _expand_commit_item
    for q in node._pub_qs:
        q.join()
    out = []
    q = node.commit_q(0)
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            break
        if item is None or not isinstance(item, tuple):
            continue
        out.extend(_expand_commit_item(item))
    return out


# -- crash mid-overlap -------------------------------------------------------


def test_crash_mid_overlap_keeps_published_drops_stash(tmp_path):
    """Crash with a stashed (never fsynced) tick in the pipeline: the
    stash vanishes atomically; every entry ever PUBLISHED before the
    crash replays."""
    from raftsql_tpu.chaos.scenarios import hard_crash_fused

    inj = fsio.StorageFaultInjector()     # forces the Python backend:
    with fsio.installed(inj):             # buffered bytes die on crash
        cfg = mkcfg()
        node = FusedClusterNode(cfg, str(tmp_path))
        assert node._overlap
        elect(node)
        node.propose_many(0, [b"SET a 1", b"SET b 2"])
        for _ in range(12):
            node.tick()
        published = _published(node)
        keys_a = {(g, i) for (g, i, _q) in published}
        assert any(q == "SET a 1" for (_g, _i, q) in published)
        # Tick once more with a FRESH batch so it sits in the stash,
        # accepted by the device but never written to any WAL.
        node.propose_many(1, [b"SET z 9"])
        node.tick()
        assert node._stash is not None, "pipeline should be hot"
        published += _published(node)
        hard_crash_fused(node)

        node2 = FusedClusterNode(cfg, str(tmp_path))
        replayed = _published(node2)
        rkeys = {(g, i): q for (g, i, q) in replayed}
        # Durability: everything externalized before the crash is in
        # the replay, verbatim.
        for (g, i, q) in published:
            assert rkeys.get((g, i)) == q, (g, i, q)
        # Atomic loss: the stashed tick's write never happened.
        assert not any(q == "SET z 9" for q in rkeys.values())
        # The cluster continues: the lost write can be re-proposed.
        elect(node2, max_ticks=60)
        node2.propose_many(1, [b"SET z 9"])
        for _ in range(12):
            node2.tick()
        node2.publish_flush()
        assert any(q == "SET z 9"
                   for (_g, _i, q) in _published(node2))
        node2.stop()
        assert keys_a <= set(rkeys)


class _SimCrash(RuntimeError):
    pass


def test_crash_before_epoch_commit_erases_dispatch(tmp_path):
    """Multi-step dispatch + overlap: the stashed dispatch's WAL
    records land and FSYNC on every peer, but the crash hits before the
    cluster-atomic epoch commit — replay must ERASE the whole dispatch
    on every peer (repair_epochs), because within a multi-step dispatch
    peers observed each other's un-fsynced messages."""
    from raftsql_tpu.chaos.scenarios import hard_crash_fused

    inj = fsio.StorageFaultInjector()
    with fsio.installed(inj):
        cfg = mkcfg()
        node = FusedClusterNode(cfg, str(tmp_path))
        node._steps = 2
        elect(node)
        node.propose_many(0, [b"SET a 1"])
        for _ in range(12):
            node.tick()
        node.publish_flush()
        _published(node)                  # drain
        lens_before = [node.plogs[0].length(g)
                       for g in range(cfg.num_groups)]

        node.propose_many(1, [b"SET doomed 1"])
        node.tick()                       # stash holds the dispatch
        assert node._stash is not None

        def boom(no):
            raise _SimCrash(f"crash before epoch {no} commit")

        node._commit_epoch = boom
        with pytest.raises(_SimCrash):
            node.tick()                   # retire writes+fsyncs, then dies
        hard_crash_fused(node)

        node2 = FusedClusterNode(cfg, str(tmp_path))
        # The doomed dispatch's records were DURABLE — only the epoch
        # machinery can (and must) drop them.
        replayed = _published(node2)
        assert not any(q == "SET doomed 1"
                       for (_g, _i, q) in replayed)
        for g in range(cfg.num_groups):
            assert node2.plogs[0].length(g) <= lens_before[g]
        node2.stop()


# -- chaos digests under the new pipeline ------------------------------------


def _chaos_digest(monkeypatch, overlap: str, gc: str, sched):
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner
    monkeypatch.setenv("RAFTSQL_OVERLAP_DISPATCH", overlap)
    monkeypatch.setenv("RAFTSQL_WAL_GROUP_COMMIT", gc)
    with tempfile.TemporaryDirectory(prefix="chaos-ovl-") as d:
        r = FusedChaosRunner(sched, d).run()
    return r["schedule_digest"], r["result_digest"]


def test_chaos_digest_stable_under_overlap(monkeypatch):
    """The same seeded fault schedule — partitions, crashes, storage
    faults, the full invariant suite — produces IDENTICAL digests with
    the double-buffered pipeline off and on: overlap moves work in
    time, never in content."""
    from raftsql_tpu.chaos.schedule import generate
    sched = generate(5, ticks=120)
    base = _chaos_digest(monkeypatch, "0", "0", sched)
    ovl = _chaos_digest(monkeypatch, "1", "0", sched)
    assert base == ovl


def test_chaos_digest_stable_under_group_commit(monkeypatch):
    """Group commit is a WAL LAYOUT change: with the storage-fault
    windows stripped (they key on per-peer paths), the committed
    history digest must match the per-peer layout exactly — under the
    overlap pipeline too."""
    import dataclasses

    from raftsql_tpu.chaos.schedule import generate
    sched = generate(11, ticks=100, min_fsync_faults=0,
                     min_torn_writes=0, min_crashes=0)
    sched = dataclasses.replace(sched, fsync_faults=(), torn_writes=(),
                                enospc_faults=(), fsync_stalls=())
    # Crash/restart events stay: replay must be layout-equivalent.
    base = _chaos_digest(monkeypatch, "1", "0", sched)
    gc = _chaos_digest(monkeypatch, "1", "1", sched)
    assert base == gc


# -- GroupCommitWAL units ----------------------------------------------------


def test_group_commit_one_fsync_per_round(tmp_path):
    gw = GroupCommitWAL(str(tmp_path / "gc"), num_peers=3, num_groups=2)
    views = [gw.view(p) for p in range(3)]
    for p, v in enumerate(views):
        v.append_ranges([0], [1], [1], [1], [f"p{p}".encode()])
        v.set_hardstates([0], [1], [p], [0])
    for v in views:                       # the barrier: P calls...
        v.sync()
    assert gw.group_commits == 1          # ...ONE fsync
    assert gw.batch_hist == {3: 1}
    views[1].append_ranges([1], [1], [1], [1], [b"solo"])
    for v in views:
        v.sync()
    assert gw.group_commits == 2
    assert gw.batch_hist == {3: 1, 1: 1}
    for v in views:
        v.sync()                          # idle round: no fsync
    assert gw.group_commits == 2
    for v in views:
        v.close()


def test_group_commit_replay_splits_per_peer(tmp_path):
    d = str(tmp_path / "gc")
    gw = GroupCommitWAL(d, num_peers=3, num_groups=2)
    views = [gw.view(p) for p in range(3)]
    for p, v in enumerate(views):
        v.append_ranges([0, 1], [1, 1], [2, 1], [1, 1],
                        [f"p{p}e1".encode(), f"p{p}e2".encode(),
                         f"p{p}g1".encode()])
        v.set_hardstates([0, 1], [1, 1], [-1, -1], [2, 1])
        v.sync()
        v.close()
    flat = GroupCommitWAL.replay_flat(d)
    for p in range(3):
        mine = GroupCommitWAL.split_replay(flat, p, 2)
        assert sorted(mine) == [0, 1]
        assert [e[1] for e in mine[0].entries] == [
            f"p{p}e1".encode(), f"p{p}e2".encode()]
        assert [e[1] for e in mine[1].entries] == [f"p{p}g1".encode()]
        assert mine[0].hard.commit == 2
        assert mine[1].hard.commit == 1


def test_group_commit_cluster_equivalent_to_per_peer(tmp_path):
    """The SAME seeded run on both WAL layouts: identical commit
    streams, identical hard states, identical post-restart replay."""
    results = []
    for label, gc in (("pp", False), ("gc", True)):
        d = str(tmp_path / label)
        cfg = mkcfg()
        node = FusedClusterNode(cfg, d, seed=3, group_commit=gc)
        assert (node._gcwal is not None) == gc
        for _ in range(60):
            node.tick()
        for g in range(cfg.num_groups):
            node.propose_many(g, [f"SET k{i} g{g}".encode()
                                  for i in range(6)])
        for _ in range(30):
            node.tick()
        node.publish_flush()
        stream = sorted(_published(node))
        hard = node._hard.copy()
        node.stop()
        node2 = FusedClusterNode(cfg, d, seed=3, group_commit=gc)
        replay = sorted(_published(node2))
        hard2 = node2._hard.copy()
        node2.stop()
        results.append((stream, replay, hard, hard2))
    a, b = results
    assert a[0] == b[0]                   # live commit streams
    assert a[1] == b[1]                   # replayed streams
    assert np.array_equal(a[2], b[2])
    assert np.array_equal(a[3], b[3])
    assert len(a[0]) >= 12
