"""Pod runtime tests: the multi-host break of the single-controller
assumption (raftsql_tpu/pod/).

The equivalence contract mirrors tests/test_mesh.py's fused<->mesh
pins one level up: a pod of N processes driven through a seeded global
workload must land bit-for-bit on the same hard states, publish
cursors, leader hints and applied KV stream as one MeshClusterNode
driven through the SAME workload.  Fast tests run the procs == 1
degenerate pod in-process (every pod code path except the TCP hop);
the `slow`-marked test spawns two real `python -m
raftsql_tpu.pod.dryrun` processes and compares their dumps against an
in-process mesh reference — the dry-run rung of the pod ladder.
"""
import json
import os
import queue
import subprocess
import sys
import threading

import numpy as np
import pytest

from tests.conftest import free_port

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    """Env for pod child processes: sitecustomize pre-imports jax, so
    the platform MUST be pinned before the interpreter starts."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


# -- PodConfig ----------------------------------------------------------


def test_pod_config_validation():
    from raftsql_tpu.pod import PodConfig
    with pytest.raises(ValueError, match="process"):
        PodConfig(procs=0)
    with pytest.raises(ValueError, match="outside"):
        PodConfig(procs=2, proc_id=2, coordinator="h:1")
    with pytest.raises(ValueError, match="coordinator"):
        PodConfig(procs=2, proc_id=0)
    with pytest.raises(ValueError, match="hosts"):
        PodConfig(procs=2, proc_id=0, coordinator="h:1",
                  hosts=("http://a",))
    pod = PodConfig(procs=2, proc_id=1, coordinator="h:1")
    with pytest.raises(ValueError, match="shard"):
        pod.validate(group_shards=1)
    pod.validate(group_shards=4)
    assert pod.owned_shards(4) == [1, 3]
    assert PodConfig(procs=2, proc_id=0,
                     coordinator="h:1").owned_shards(4) == [0, 2]
    assert pod.seq_origin(3) == 1 and pod.seq_origin(4) == 0


def test_pod_meta_refuses_reassignment(tmp_path):
    """The PODMETA check — a host restarted with a shard assignment
    that disagrees with its on-disk layout is refused (the cross-host
    analogue of the mesh re-shard refusal)."""
    from raftsql_tpu.pod import PodConfig
    d = str(tmp_path / "h0")
    PodConfig(procs=2, proc_id=0, coordinator="h:1").check_meta(d, 4)
    # Same assignment reopens fine.
    PodConfig(procs=2, proc_id=0, coordinator="h:1").check_meta(d, 4)
    # A different pod size, proc id, or shard count is refused.
    with pytest.raises(ValueError, match="shard assignment"):
        PodConfig(procs=3, proc_id=0, coordinator="h:1").check_meta(d, 4)
    with pytest.raises(ValueError, match="shard assignment"):
        PodConfig(procs=2, proc_id=1, coordinator="h:1").check_meta(d, 4)
    with pytest.raises(ValueError, match="shard assignment"):
        PodConfig(procs=2, proc_id=0, coordinator="h:1").check_meta(d, 8)
    assert PodConfig.read_meta(d)["owned"] == [0, 2]
    assert PodConfig.read_meta(str(tmp_path / "none")) is None


# -- the collective -----------------------------------------------------


def test_tcp_pod_transport_gather():
    """Three threads form a pod over localhost and run a few
    collectives; every process must see every contribution in proc-id
    order, and a mismatched tag must fail loudly."""
    from raftsql_tpu.pod import PodPeerLost, TcpPodTransport
    procs = 3
    coord = f"127.0.0.1:{free_port()}"
    results = [None] * procs
    errors = []

    def run(pid):
        try:
            t = TcpPodTransport(procs, pid, coord, connect_timeout_s=10.0)
            try:
                out = []
                for tag in ("a", "b"):
                    out.append(t.gather(tag, f"{tag}{pid}".encode()))
                t.barrier("end")
                results[pid] = out
            finally:
                t.close()
        except Exception as e:  # surfaced below
            errors.append((pid, e))

    threads = [threading.Thread(target=run, args=(p,)) for p in range(procs)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    for pid in range(procs):
        assert results[pid] == [[b"a0", b"a1", b"a2"],
                                [b"b0", b"b1", b"b2"]]

    with pytest.raises(ValueError):
        TcpPodTransport(1, 0, "x:1")
    t = __import__("raftsql_tpu.pod.transport",
                   fromlist=["make_transport"]).make_transport(1, 0, "")
    assert t.gather("x", b"p") == [b"p"]
    assert isinstance(PodPeerLost("x"), RuntimeError)


# -- equivalence (procs == 1 pod vs MeshClusterNode, in-process) --------


def _mesh_pair(tmp_path, num_groups=8, num_peers=3, group_shards=4):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.pod import PodClusterNode, PodConfig
    from raftsql_tpu.runtime.mesh import MeshClusterNode, MeshConfig
    cfg = RaftConfig(num_groups=num_groups, num_peers=num_peers,
                     log_window=32, max_entries_per_msg=4,
                     election_ticks=10, heartbeat_ticks=1,
                     tick_interval_s=0.0, seed=7)
    mesh = MeshConfig(peer_shards=1, group_shards=group_shards).build()
    pod = PodClusterNode(PodConfig(), cfg, str(tmp_path / "pod"), mesh,
                         seed=3)
    ref = MeshClusterNode(cfg, str(tmp_path / "ref"), mesh, seed=3)
    return pod, ref, cfg


def _drain(node):
    from raftsql_tpu.runtime.db import _expand_commit_item
    out = []
    q = node.commit_q(0)
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            break
        if item is None or not isinstance(item, tuple):
            continue
        out.extend(_expand_commit_item(item))
    return out


def _assert_equal_state(pod, ref, pod_applied, ref_applied):
    from raftsql_tpu.pod.dryrun import state_doc
    np.testing.assert_array_equal(np.asarray(pod._hard),
                                  np.asarray(ref._hard))
    np.testing.assert_array_equal(np.asarray(pod._applied),
                                  np.asarray(ref._applied))
    pd = state_doc(pod, pod_applied)
    rd = state_doc(ref, ref_applied)
    assert pd["digest"] == rd["digest"]
    assert pd["kv_stream"] == rd["kv_stream"]


def test_pod_single_proc_equivalence(tmp_path):
    """A procs == 1 pod is bit-for-bit the single controller: same
    hard states, same hints, same applied stream — through the full
    pod tick (gather merge, strided seqs, ack plane)."""
    from raftsql_tpu.pod.dryrun import seeded_workload
    pod, ref, cfg = _mesh_pair(tmp_path)
    pod_applied, ref_applied = [], []
    try:
        wl = seeded_workload(0, 60, cfg.num_groups)
        for t in range(60):
            for _i, g, payload in wl[t]:
                seqs = pod.pod_propose(g, [payload])
                assert len(seqs) == 1
                ref.propose_many(g, [payload])
            pod.tick()
            ref.tick()
            ref.publish_flush()
            pod_applied.extend(_drain(pod))
            ref_applied.extend(_drain(ref))
            if t % 20 == 19:
                _assert_equal_state(pod, ref, pod_applied, ref_applied)
        _assert_equal_state(pod, ref, pod_applied, ref_applied)
        assert len(pod_applied) > 0
        # The ack plane: the owner acks a committed seq, and the next
        # collective carries it back to the origin.
        pod.pod_send_ack([5, 9])
        pod.tick()
        assert pod.pod_take_acked() == {5, 9}
        assert pod.pod_take_acked() == set()
        assert pod.metrics.pod_gathers >= 60
    finally:
        pod.stop()
        ref.stop()


def test_pod_restart_replays_from_disk(tmp_path):
    """Stop a pod, reopen over the same dirs: the replay exchange must
    rebuild the identical state (PodShardedWAL replay + PODMETA
    second-open acceptance)."""
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.pod import PodClusterNode, PodConfig
    from raftsql_tpu.pod.dryrun import seeded_workload, state_doc
    from raftsql_tpu.runtime.mesh import MeshConfig
    cfg = RaftConfig(num_groups=8, num_peers=3, log_window=32,
                     max_entries_per_msg=4, election_ticks=10,
                     heartbeat_ticks=1, tick_interval_s=0.0, seed=7)
    mesh = MeshConfig(peer_shards=1, group_shards=4).build()
    d = str(tmp_path / "pod")
    node = PodClusterNode(PodConfig(), cfg, d, mesh, seed=3)
    applied = []
    try:
        wl = seeded_workload(0, 40, cfg.num_groups)
        for t in range(40):
            for _i, g, payload in wl[t]:
                node.pod_propose(g, [payload])
            node.tick()
            applied.extend(_drain(node))
        before = state_doc(node, applied)
    finally:
        node.stop()
    node2 = PodClusterNode(PodConfig(), cfg, d, mesh, seed=3)
    try:
        np.testing.assert_array_equal(
            np.asarray(node2._hard)[:, :, :2],
            np.frombuffer(__import__("base64").b64decode(before["hard"]),
                          dtype=np.asarray(node._hard).dtype).reshape(
                              np.asarray(node._hard).shape)[:, :, :2])
        replayed = []
        for _ in range(3):
            node2.tick()
            replayed.extend(_drain(node2))
        rows = sorted([int(g), int(i),
                       d2.decode() if isinstance(d2, (bytes, bytearray))
                       else str(d2)] for (g, i, d2) in replayed)
        assert rows == before["kv_stream"]
    finally:
        node2.stop()


def test_pod_rejects_bad_shapes(tmp_path):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.pod import PodClusterNode, PodConfig
    from raftsql_tpu.runtime.mesh import MeshConfig
    cfg = RaftConfig(num_groups=8, num_peers=3, log_window=32,
                     max_entries_per_msg=4, tick_interval_s=0.0)
    mesh = MeshConfig(peer_shards=1, group_shards=2).build()
    with pytest.raises(ValueError, match="shard"):
        PodClusterNode(PodConfig(procs=4, proc_id=0, coordinator="h:1"),
                       cfg, str(tmp_path / "x"), mesh)


# -- the dry-run rung: two real processes over TCP ----------------------


@pytest.mark.slow
def test_pod_dryrun_two_process_equivalence(tmp_path):
    """Rungs 1+2 of the pod ladder: two `raftsql_tpu.pod.dryrun`
    processes form a pod over localhost, run the seeded workload, and
    both dumps must match each other AND an in-process procs == 1
    reference bit-for-bit."""
    coord = f"127.0.0.1:{free_port()}"
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.pod.dryrun",
             "--procs", "2", "--proc-id", str(pid),
             "--coord", coord,
             "--data-dir", str(tmp_path / f"h{pid}"),
             "--ticks", "60", "--seed", "0",
             "--out", str(tmp_path / f"h{pid}.json")],
            env=_child_env(), cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    logs = [p.communicate(timeout=280)[0] for p in procs]
    for pid, p in enumerate(procs):
        assert p.returncode == 0, logs[pid].decode(errors="replace")
    docs = [json.loads((tmp_path / f"h{i}.json").read_text())
            for i in range(2)]
    assert docs[0]["digest"] == docs[1]["digest"]
    assert docs[0]["kv_stream"] == docs[1]["kv_stream"]
    assert len(docs[0]["kv_stream"]) > 0

    # The single-controller reference over the same workload.
    from raftsql_tpu.pod.dryrun import (build_pod_node, drain_commits,
                                        seeded_workload, state_doc)

    class _A:
        procs = 1
        proc_id = 0
        coord = ""
        data_dir = str(tmp_path / "ref")
        groups = 8
        peers = 3
        group_shards = 0
        connect_timeout = 30.0

    node, cfg = build_pod_node(_A)
    applied = []
    try:
        wl = seeded_workload(0, 60, cfg.num_groups)
        for t in range(60):
            for _i, g, payload in wl[t]:
                node.pod_propose(g, [payload])
            node.tick()
            applied.extend(drain_commits(node))
        ref = state_doc(node, applied)
    finally:
        node.stop()
    assert docs[0]["digest"] == ref["digest"]
    # Durability is sharded: each host materialized only its own
    # shards' WAL dirs, disjoint and jointly exhaustive.
    owned = [sorted(x.name for x in (tmp_path / f"h{i}" / "p1").iterdir())
             for i in range(2)]
    assert not set(owned[0]) & set(owned[1])


# -- the serving plane: client routing + the --pod server ---------------


def test_client_pod_hint_merge(monkeypatch):
    """refresh_hints over a pod: the sweep adopts the /healthz hosts
    table (a client pointed at ONE host learns them all) and routes
    each group to its OWNER host — engine role is ignored on pod rows
    (every host truthfully reports every group; only owners serve)."""
    from raftsql_tpu.api.client import RaftSQLClient
    hosts = ["127.0.0.1:18000", "127.0.0.1:18001"]
    docs = {
        0: {"id": 0, "ready": True,
            "pod": {"procs": 2, "proc_id": 0, "hosts": hosts},
            "groups": {"0": {"role": "leader", "pod_owned": True},
                       "1": {"role": "leader", "pod_owned": False,
                             "lease_s": 9.0}}},
        1: {"id": 0, "ready": True,
            "pod": {"procs": 2, "proc_id": 1, "hosts": hosts},
            "groups": {"0": {"pod_owned": False},
                       "1": {"pod_owned": True, "lease_s": 5.0}}},
    }
    monkeypatch.setattr(RaftSQLClient, "health",
                        lambda self, idx, timeout_s=1.0: docs.get(idx))
    cli = RaftSQLClient([hosts[0]])
    try:
        assert cli.refresh_hints() == 2
        assert [p for (_h, p) in cli.nodes] == [18000, 18001]
        assert cli._leader == {0: 0, 1: 1}
        # The lease hint comes from the OWNER's row, never the
        # non-owner's (whose identical engine lease is not servable).
        assert cli._lease_target(1) == 1
        # A second sweep is stable (no duplicate adoption).
        assert cli.refresh_hints() == 2
        assert len(cli.nodes) == 2
    finally:
        cli.close()


@pytest.mark.slow
def test_pod_server_two_hosts(tmp_path):
    """The --pod serving rung end to end: two `server.main --pod`
    processes on one box, a client pointed at host 0 only.  The sweep
    adopts host 1 and routes by ownership; a deliberately misdirected
    write 421s with X-Raft-Leader naming the owner host; reads land on
    the owner's durable SQLite shard."""
    from raftsql_tpu.api.client import RaftSQLClient
    from raftsql_tpu.server.main import EXIT_CODE_FATAL
    deadline = 120.0
    p0, p1 = free_port(), free_port()
    coord = f"127.0.0.1:{free_port()}"
    hosts = f"127.0.0.1:{p0},127.0.0.1:{p1}"
    procs = [subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main",
         "--pod", "--pod-id", str(i), "--pod-coord", coord,
         "--pod-hosts", hosts, "--port", str(p), "--groups", "4",
         "--group-shards", "2", "--peers", "3", "--tick", "0.02"],
        env=_child_env(), cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i, p in enumerate((p0, p1))]
    cli = RaftSQLClient([f"127.0.0.1:{p0}"], timeout_s=15.0)
    try:
        cli.wait_healthy(0, deadline_s=deadline)
        doc = cli.health(0)
        assert doc["pod"]["procs"] == 2
        assert doc["pod"]["owned_shards"] == [0]
        # group_shards=2 over 4 groups: host 0 owns groups 0-1 (shard
        # 0), host 1 owns 2-3 — every host reports all four rows.
        assert doc["groups"]["0"]["pod_owned"] is True
        assert doc["groups"]["2"]["pod_owned"] is False
        assert cli.refresh_hints(timeout_s=5.0) == 4
        assert len(cli.nodes) == 2          # host 1 adopted
        assert cli._leader == {0: 0, 1: 0, 2: 1, 3: 1}
        # A write for a host-1 group routes there via the merged hints.
        cli.put("CREATE TABLE t (v text)", group=2, deadline_s=deadline)
        cli.put("INSERT INTO t (v) VALUES ('x')", group=2,
                deadline_s=deadline)
        cli.get_until("SELECT v FROM t", "|x|\n", group=2,
                      deadline_s=deadline)
        # And host 0's own groups serve locally.
        cli.put("CREATE TABLE s (v text)", group=0, deadline_s=deadline)
        # Misdirected write: host 0 refuses a host-1 group up front
        # with 421 + the owner host (1-based hosts-table slot).
        status, hdrs, _ = cli.raw(
            0, "PUT", "/", "INSERT INTO t (v) VALUES ('y')",
            headers={"X-Raft-Group": "2"})
        assert status == 421
        assert hdrs.get("X-Raft-Leader") == "2"
        # Misdirected read: same refusal on the query path.
        status, hdrs, _ = cli.raw(0, "GET", "/", "SELECT v FROM t",
                                  headers={"X-Raft-Group": "2"})
        assert status == 421
    except BaseException:
        for p in procs:
            p.terminate()
        logs = [p.communicate(timeout=30)[0] for p in procs]
        for i, log in enumerate(logs):
            print(f"--- pod host {i} ---\n" + log.decode(errors="replace"))
        raise
    finally:
        cli.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
    # Fail-stop teardown: whichever host's collective dies first may
    # exit EXIT_CODE_FATAL (pod-wide fail-stop), a clean stop exits 0.
    for p in procs:
        p.communicate(timeout=60)
        assert p.returncode in (0, EXIT_CODE_FATAL), p.returncode
