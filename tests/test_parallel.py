"""Multi-chip sharded execution tests (8 virtual CPU devices, conftest.py).

The reference has no multi-node-in-one-binary story beyond loopback TCP
(reference raftsql_test.go:16-28); the TPU-native framework's equivalent of
"the cluster runs across machines" is the mesh-sharded step.  These tests
pin its two key properties:

  * bit-identical to the single-chip fused step (sharding is an execution
    detail, never a semantics change) — for both a groups-only mesh and a
    peers×groups mesh (whose message routing is the ICI all_to_all);
  * liveness at scale: elections + commits proceed under the scan runner.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.core.cluster import (cluster_run, empty_cluster_inbox,
                                      init_cluster_state)
from raftsql_tpu.parallel import (make_mesh, make_sharded_cluster_run,
                                  make_sharded_cluster_step,
                                  shard_cluster_arrays)


def cfg_for(num_peers, num_groups, seed=42):
    return RaftConfig(num_groups=num_groups, num_peers=num_peers,
                      log_window=32, max_entries_per_msg=4,
                      election_ticks=10, heartbeat_ticks=1, seed=seed)


def run_unsharded(cfg, ticks, props):
    states = init_cluster_state(cfg)
    inboxes = empty_cluster_inbox(cfg)
    return cluster_run(cfg, states, inboxes, ticks, props)


def assert_trees_equal(a, b, msg):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=msg)


@pytest.mark.parametrize("pp,gg,P,G", [(1, 8, 3, 16), (2, 4, 4, 8)])
def test_sharded_step_matches_unsharded(pp, gg, P, G):
    cfg = cfg_for(P, G)
    mesh = make_mesh(pp, gg)
    step = make_sharded_cluster_step(cfg, mesh)

    ref_states = init_cluster_state(cfg)
    ref_inboxes = empty_cluster_inbox(cfg)
    states, inboxes = shard_cluster_arrays(mesh, init_cluster_state(cfg),
                                           empty_cluster_inbox(cfg))
    rng = np.random.default_rng(0)
    from raftsql_tpu.core.cluster import cluster_step_jit
    for t in range(60):
        props_np = rng.integers(0, 2, (P, G)).astype(np.int32)
        ref_states, ref_inboxes, ref_info = cluster_step_jit(
            cfg, ref_states, ref_inboxes, jnp.asarray(props_np))
        props = jax.device_put(
            jnp.asarray(props_np),
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("peers", "groups")))
        states, inboxes, info = step(states, inboxes, props)
        if t % 20 == 19:      # compare periodically (device_get is the cost)
            assert_trees_equal(states, ref_states, f"state diverged at {t}")
            assert_trees_equal(inboxes, ref_inboxes, f"inbox diverged at {t}")
    assert_trees_equal(info, ref_info, "final info diverged")


def test_sharded_run_commits_advance():
    P, G = 4, 8
    cfg = cfg_for(P, G, seed=5)
    mesh = make_mesh(2, 4)
    ticks = 150
    run = make_sharded_cluster_run(cfg, mesh, ticks)
    # Propose 1 entry per group per tick at every peer; non-leaders reject,
    # so this exercises the leader gating too.
    props = jnp.ones((ticks, P, G), jnp.int32)
    states, inboxes = shard_cluster_arrays(mesh, init_cluster_state(cfg),
                                           empty_cluster_inbox(cfg))
    props = jax.device_put(
        props, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "peers", "groups")))
    states, inboxes, total = run(states, inboxes, props)
    role = np.asarray(states.role)
    assert (np.sum(role == LEADER, axis=0) >= 1).all()
    # Every group elected and committed at least the no-op plus entries.
    commit = np.asarray(states.commit).max(axis=0)
    assert (commit >= 1).all(), commit
    assert int(total) == int(np.sum(commit)), (int(total), commit)


def test_sharded_soak_faults_matches_unsharded():
    """Multi-chip SOAK (VERDICT r3 task 5): a 160-tick sharded run on the
    peers×groups mesh under a fault plan — 5% random message loss
    throughout plus a 40-tick full isolation of peer 0 — must elect,
    commit, recover after the heal, and stay BIT-IDENTICAL to the
    unsharded engine under the same plan (the reference's analog is its
    full-system tests, raftsql_test.go:92-171, generalized to the mesh).

    Faults are injected at the delivery boundary: the inbox produced by
    tick t-1 is masked (slot type codes zeroed) before tick t consumes
    it — exactly what a dropped rafthttp message is to the reference.
    """
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from raftsql_tpu.core.cluster import cluster_step_jit

    P, G = 4, 8
    cfg = cfg_for(P, G, seed=11)
    mesh = make_mesh(2, 4)
    step = make_sharded_cluster_step(cfg, mesh)
    spec3 = NamedSharding(mesh, PS("peers", "groups", None))
    spec2 = NamedSharding(mesh, PS("peers", "groups"))

    ref_states = init_cluster_state(cfg)
    ref_inboxes = empty_cluster_inbox(cfg)
    states, inboxes = shard_cluster_arrays(mesh, init_cluster_state(cfg),
                                           empty_cluster_inbox(cfg))
    rng = np.random.default_rng(7)
    ticks, part_from, part_to = 160, 60, 100
    commit_at_heal = None
    for t in range(ticks):
        # Fault plan for this tick's deliveries: [dst, g, src] keep-mask.
        drop = rng.random((P, G, P)) < 0.05
        if part_from <= t < part_to:
            drop[0, :, :] = True          # nothing delivered TO peer 0
            drop[:, :, 0] = True          # nothing FROM peer 0
        keep = jnp.asarray(~drop, jnp.int32)

        def masked(ib, keep_arr):
            return ib._replace(v_type=ib.v_type * keep_arr,
                               a_type=ib.a_type * keep_arr)

        props_np = rng.integers(0, 2, (P, G)).astype(np.int32)
        ref_states, ref_inboxes, _ = cluster_step_jit(
            cfg, ref_states, masked(ref_inboxes, keep),
            jnp.asarray(props_np))
        keep_sh = jax.device_put(keep, spec3)
        props_sh = jax.device_put(jnp.asarray(props_np), spec2)
        states, inboxes, _ = step(states, masked(inboxes, keep_sh),
                                  props_sh)
        if t == part_to:
            commit_at_heal = np.asarray(ref_states.commit).max(axis=0)
        if t % 40 == 39:
            np.testing.assert_array_equal(
                np.asarray(states.commit), np.asarray(ref_states.commit),
                err_msg=f"commit diverged at tick {t}")
    assert_trees_equal(states, ref_states, "final state diverged")
    commit = np.asarray(ref_states.commit).max(axis=0)
    # Every group elected + committed, and progress resumed after heal.
    assert (commit >= 1).all(), commit
    assert (commit > commit_at_heal).all(), (commit_at_heal, commit)


def test_mesh_divisibility_validation():
    cfg = cfg_for(3, 8)
    mesh = make_mesh(2, 4)
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_cluster_step(cfg, mesh)
    cfg = cfg_for(4, 6)
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_cluster_step(cfg, mesh)


def test_mesh_cluster_node_durable(tmp_path):
    """MeshClusterNode: the sharded step under the full durable host
    plane (per-peer WAL, mirroring, publish, apply) — commits flow,
    every peer's WAL is written, and a restart replays them over the
    same mesh (VERDICT r4 task 5 / SURVEY §7 phase 4)."""
    from raftsql_tpu.runtime.db import _expand_commit_item
    from raftsql_tpu.runtime.mesh import MeshClusterNode

    cfg = RaftConfig(num_groups=8, num_peers=4, log_window=32,
                     max_entries_per_msg=4, tick_interval_s=0.0)
    mesh = make_mesh(2, 4)

    def drain(node, peer=0):
        out = []
        q = node.commit_q(peer)
        while True:
            try:
                item = q.get_nowait()
            except Exception:
                break
            if item is None or not isinstance(item, tuple):
                continue
            out.extend(_expand_commit_item(item))
        return out

    node = MeshClusterNode(cfg, str(tmp_path), mesh)
    for t in range(200):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            break
    assert (node._hints >= 0).all()
    for g in range(8):
        node.propose_many(g, [f"SET k{i} g{g}".encode() for i in range(5)])
    for _ in range(40):
        node.tick()
    live = drain(node)
    assert len(live) == 8 * 5
    node.stop()
    # Every peer's WAL is sharded per group shard (runtime/mesh.py
    # ShardedWAL: p<i>/s<j>) and every shard dir holds segments —
    # durability actually happened, laid out per local device shard.
    for p in range(4):
        for j in range(4):
            segs = list((tmp_path / f"p{p + 1}" / f"s{j}").glob("wal-*"))
            assert segs, f"peer {p} shard {j} wrote no WAL"

    node2 = MeshClusterNode(cfg, str(tmp_path), mesh)
    rep = drain(node2)
    assert sorted(rep) == sorted(live)
    node2.stop()
