"""api/client.py unit tests: leader-cache transitions on 421 hints.

PR 11 makes leadership MOVE on purpose (graceful transfers), so the
client's 421 handling is now on the hot path: a hint naming a node
other than the cached leader must invalidate the cache and rotate the
request to the new leader IMMEDIATELY — finishing the old rotation
first would spend a full round of timeouts on nodes known not to lead.
No sockets here: `raw` is monkeypatched, the cache logic is the unit.
"""
from raftsql_tpu.api.client import RaftSQLClient


def _client():
    # Ports never dialled — raw() is replaced in every test that sends.
    return RaftSQLClient([10001, 10002, 10003], timeout_s=0.2,
                         backoff_s=0.001, backoff_cap_s=0.002)


def test_note_leader_change_detection():
    c = _client()
    # Empty cache: any valid hint is a change.
    assert c._note_leader(0, {"X-Raft-Leader": "2"}) is True
    assert c._leader[0] == 1
    # Same hint again: cache already right, no rotation needed.
    assert c._note_leader(0, {"X-Raft-Leader": "2"}) is False
    assert c._leader[0] == 1
    # Different hint (leadership transferred): change, cache follows.
    assert c._note_leader(0, {"X-Raft-Leader": "3"}) is True
    assert c._leader[0] == 2
    # Groups are independent.
    assert c._note_leader(5, {"X-Raft-Leader": "1"}) is True
    assert c._leader[0] == 2 and c._leader[5] == 0


def test_note_leader_hintless_421_invalidates():
    c = _client()
    assert c._note_leader(0, {"X-Raft-Leader": "1"}) is True
    # 421 with no (or junk) hint: the cached leader is demonstrably
    # wrong — drop it so the next rotation is unbiased.
    assert c._note_leader(0, {}) is False
    assert 0 not in c._leader
    c._leader[0] = 1
    assert c._note_leader(0, {"X-Raft-Leader": "zap"}) is False
    assert 0 not in c._leader


def test_put_chases_moved_leader_immediately():
    c = _client()
    c._leader[0] = 0                       # stale: node 0 used to lead
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if node == 2:
            return 204, {"X-Raft-Session": "7"}, ""
        # Everyone else redirects to node 3 (idx 2): a transfer moved
        # leadership mid-flight.
        return 421, {"X-Raft-Leader": "3"}, "not leader"

    c.raw = fake_raw
    assert c.put("insert into kv values ('a','1')", deadline_s=5) == 7
    # The changed hint must ABANDON the rotation: exactly one miss at
    # the stale leader, then straight to the new one — the third node
    # is never dialled.
    assert calls == [0, 2]
    assert c._leader[0] == 2


def test_put_same_hint_keeps_rotating():
    c = _client()
    c._leader[0] = 2                       # cache already names idx 2
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if len(calls) >= 4:
            return 204, {}, ""
        # idx 2 (the cached leader) answers 421 naming ITSELF — e.g.
        # it is mid-step-down; no rotation reset, just move on.
        return 421, {"X-Raft-Leader": "3"}, "not yet"

    c.raw = fake_raw
    assert c.put("insert into kv values ('b','2')", deadline_s=5) is None
    # Cached leader first, then the round-robin remainder — the
    # self-naming hint must NOT restart the order (that would hammer
    # one node in a tight loop).
    assert calls[0] == 2
    assert set(calls[:3]) == {0, 1, 2}


def test_get_rotates_on_changed_hint():
    c = _client()
    c._leader[0] = 0
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if node == 1:
            return 200, {}, "42"
        return 421, {"X-Raft-Leader": "2"}, "moved"

    c.raw = fake_raw
    assert c.get("select v from kv", linear=True, deadline_s=5) == "42"
    assert calls == [0, 1]
    assert c._leader[0] == 1
