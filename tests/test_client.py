"""api/client.py unit tests: leader-cache transitions on 421 hints.

PR 11 makes leadership MOVE on purpose (graceful transfers), so the
client's 421 handling is now on the hot path: a hint naming a node
other than the cached leader must invalidate the cache and rotate the
request to the new leader IMMEDIATELY — finishing the old rotation
first would spend a full round of timeouts on nodes known not to lead.
No sockets here: `raw` is monkeypatched, the cache logic is the unit.
"""
from raftsql_tpu.api.client import RaftSQLClient


def _client():
    # Ports never dialled — raw() is replaced in every test that sends.
    return RaftSQLClient([10001, 10002, 10003], timeout_s=0.2,
                         backoff_s=0.001, backoff_cap_s=0.002)


def test_note_leader_change_detection():
    c = _client()
    # Empty cache: any valid hint is a change.
    assert c._note_leader(0, {"X-Raft-Leader": "2"}) is True
    assert c._leader[0] == 1
    # Same hint again: cache already right, no rotation needed.
    assert c._note_leader(0, {"X-Raft-Leader": "2"}) is False
    assert c._leader[0] == 1
    # Different hint (leadership transferred): change, cache follows.
    assert c._note_leader(0, {"X-Raft-Leader": "3"}) is True
    assert c._leader[0] == 2
    # Groups are independent.
    assert c._note_leader(5, {"X-Raft-Leader": "1"}) is True
    assert c._leader[0] == 2 and c._leader[5] == 0


def test_note_leader_hintless_421_invalidates():
    c = _client()
    assert c._note_leader(0, {"X-Raft-Leader": "1"}) is True
    # 421 with no (or junk) hint: the cached leader is demonstrably
    # wrong — drop it so the next rotation is unbiased.
    assert c._note_leader(0, {}) is False
    assert 0 not in c._leader
    c._leader[0] = 1
    assert c._note_leader(0, {"X-Raft-Leader": "zap"}) is False
    assert 0 not in c._leader


def test_put_chases_moved_leader_immediately():
    c = _client()
    c._leader[0] = 0                       # stale: node 0 used to lead
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if node == 2:
            return 204, {"X-Raft-Session": "7"}, ""
        # Everyone else redirects to node 3 (idx 2): a transfer moved
        # leadership mid-flight.
        return 421, {"X-Raft-Leader": "3"}, "not leader"

    c.raw = fake_raw
    assert c.put("insert into kv values ('a','1')", deadline_s=5) == 7
    # The changed hint must ABANDON the rotation: exactly one miss at
    # the stale leader, then straight to the new one — the third node
    # is never dialled.
    assert calls == [0, 2]
    assert c._leader[0] == 2


def test_put_same_hint_keeps_rotating():
    c = _client()
    c._leader[0] = 2                       # cache already names idx 2
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if len(calls) >= 4:
            return 204, {}, ""
        # idx 2 (the cached leader) answers 421 naming ITSELF — e.g.
        # it is mid-step-down; no rotation reset, just move on.
        return 421, {"X-Raft-Leader": "3"}, "not yet"

    c.raw = fake_raw
    assert c.put("insert into kv values ('b','2')", deadline_s=5) is None
    # Cached leader first, then the round-robin remainder — the
    # self-naming hint must NOT restart the order (that would hammer
    # one node in a tight loop).
    assert calls[0] == 2
    assert set(calls[:3]) == {0, 1, 2}


def test_get_rotates_on_changed_hint():
    c = _client()
    c._leader[0] = 0
    calls = []

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        calls.append(node)
        if node == 1:
            return 200, {}, "42"
        return 421, {"X-Raft-Leader": "2"}, "moved"

    c.raw = fake_raw
    assert c.get("select v from kv", linear=True, deadline_s=5) == "42"
    assert calls == [0, 1]
    assert c._leader[0] == 1


# -- the read-replica tier (ISSUE 19): nearest-first routing + fallback -----


def _with_replicas(c, n=2):
    c._adopt_replicas([f"127.0.0.1:{20001 + i}" for i in range(n)])
    return c


def test_adopt_replicas_is_idempotent_append_only():
    c = _client()
    assert c._adopt_replicas(["h:20001", "h:20002"]) == 2
    assert c._adopt_replicas(["h:20002", "h:20003", "junk"]) == 1
    assert c.replica_endpoints() == ["h:20001", "h:20002", "h:20003"]


def test_replica_order_is_rtt_ewma_nearest_first():
    c = _with_replicas(_client(), n=3)
    c._note_rtt(0, 12.0)
    c._note_rtt(1, 3.0)
    # replica 2 unmeasured: goes last until its first probe answers.
    assert c._replica_order() == [1, 0, 2]
    # EWMA: one slow sample must not instantly demote a near replica.
    c._note_rtt(1, 8.0)
    assert c._rtt[1] == 0.7 * 3.0 + 0.3 * 8.0
    assert c._replica_order() == [1, 0, 2]
    with c._mu:
        c._ralive[1] = False                 # dead endpoints drop out
    assert c._replica_order() == [0, 2]


def test_get_session_routes_to_replica_and_carries_watermark():
    """Satellite: the session watermark a PUT returned must reach the
    replica verbatim (X-Raft-Session), and a 200 there never touches
    the write tier."""
    c = _with_replicas(_client())
    seen = {}

    def fake_raw_replica(ridx, method, path="/", body="", headers=None,
                         timeout_s=None):
        seen.update(headers or {}, ridx=ridx, body=body)
        return 200, {"X-Raft-Session": "9"}, "|5|"

    c.raw_replica = fake_raw_replica
    c.raw = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("write tier dialled despite replica 200"))
    c._hints_at = __import__("time").monotonic()   # suppress the sweep
    rows, wm = c.get_session("SELECT count(*) FROM t",
                             consistency="session", session=7)
    assert rows == "|5|" and wm == 9
    assert seen["X-Raft-Session"] == "7"
    assert seen["X-Consistency"] == "session"


def test_get_session_falls_back_to_write_tier_on_421():
    """Satellite: any replica refusal (the fail-closed ladder answers
    421) must fall through to the authoritative tier — and adopt the
    leader hint the refusal carried."""
    c = _with_replicas(_client())
    order = []

    def fake_raw_replica(ridx, method, path="/", body="", headers=None,
                         timeout_s=None):
        order.append(("replica", ridx))
        return 421, {"X-Raft-Leader": "2"}, "replica refuses"

    def fake_raw(node, method, path="/", body="", headers=None,
                 timeout_s=None):
        order.append(("engine", node))
        return 200, {"X-Raft-Session": "4"}, "|1|"

    c.raw_replica = fake_raw_replica
    c.raw = fake_raw
    c._hints_at = __import__("time").monotonic()
    rows, wm = c.get_session("SELECT 1", consistency="session")
    assert rows == "|1|" and wm == 4
    # Both replicas refused, then the write tier answered — and the
    # hint from the refusal warmed the leader cache.
    assert order[:2] == [("replica", 0), ("replica", 1)]
    assert order[2][0] == "engine"
    assert c._leader[0] == 1
    assert c.replica_stats["127.0.0.1:20001"] == [0, 1]


def test_replica_conn_error_marks_dead_and_falls_back():
    c = _with_replicas(_client())

    def fake_raw_replica(ridx, method, path="/", body="", headers=None,
                         timeout_s=None):
        if ridx == 0:
            raise ConnectionRefusedError("down")
        return 200, {}, "|2|"

    c.raw_replica = fake_raw_replica
    c._hints_at = __import__("time").monotonic()
    assert c.get("SELECT 1") == "|2|"
    assert c._ralive[0] is False
    # Dead endpoint skipped on the next pass.
    assert c._replica_order() == [1]


def test_refresh_hints_adopts_replica_endpoints():
    c = _client()
    docs = {0: {"groups": {"0": {"role": "leader"}},
                "replica": {"endpoints": ["127.0.0.1:20007"]}}}
    c.health = lambda idx, timeout_s=1.0: docs.get(idx)
    probed = []
    c.raw_replica = lambda ridx, *a, **k: probed.append(ridx) \
        or (200, {}, "{}")
    assert c.refresh_hints() == 1
    assert c.replica_endpoints() == ["127.0.0.1:20007"]
    assert probed == [0]                    # the sweep seeds the EWMA
