"""Mesh runtime subsystem (runtime/mesh.py) — 8 virtual CPU devices.

The acceptance story for the mesh scale-out:

  * mesh ↔ fused EQUIVALENCE: the MeshClusterNode under forced host
    devices reproduces the single-device FusedClusterNode bit-for-bit —
    hard states, commit indexes, applied KV — on full-voter-mask and
    masked-membership configs, with and without per-peer skew
    (sharding is an execution detail, never a semantics change);
  * acked writes with G sharded over >= 2 devices, through the full
    product stack (RaftDB + FusedPipe over the mesh node);
  * the per-shard durable layout (ShardedWAL): routing, replay merge,
    restart equivalence, re-shard refusal;
  * skew on the mesh (the closed MeshLockstepOnlyError frontier):
    lockstep vs skewed elections diverge, and the mesh-skew chaos
    family reproduces digests.
"""
import queue

import numpy as np
import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.runtime.db import _expand_commit_item
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.runtime.mesh import (MeshClusterNode, MeshConfig,
                                      ShardedWAL)


def cfg_for(num_peers=4, num_groups=8, seed=7, **kw):
    kw.setdefault("log_window", 32)
    kw.setdefault("max_entries_per_msg", 4)
    kw.setdefault("election_ticks", 10)
    kw.setdefault("heartbeat_ticks", 1)
    kw.setdefault("tick_interval_s", 0.0)
    return RaftConfig(num_groups=num_groups, num_peers=num_peers,
                      seed=seed, **kw)


def drain(node, peer=0):
    out = []
    q = node.commit_q(peer)
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            break
        if item is None or not isinstance(item, tuple):
            continue
        out.extend(_expand_commit_item(item))
    return out


# -- MeshConfig ---------------------------------------------------------

def test_mesh_config_validation():
    with pytest.raises(ValueError, match="positive"):
        MeshConfig(peer_shards=0, group_shards=4)
    mc = MeshConfig(peer_shards=2, group_shards=4)
    assert mc.total_devices == 8
    with pytest.raises(ValueError, match="not divisible"):
        mc.validate(cfg_for(num_peers=3, num_groups=8))
    with pytest.raises(ValueError, match="not divisible"):
        mc.validate(cfg_for(num_peers=4, num_groups=6))
    mc.validate(cfg_for(num_peers=4, num_groups=8))
    with pytest.raises(ValueError, match="devices"):
        MeshConfig(peer_shards=4, group_shards=4).build()


def test_mesh_config_for_groups_picks_widest_divisor():
    # 8 devices, 12 groups: the widest divisor of 12 that fits is 6.
    mc = MeshConfig.for_groups(cfg_for(num_groups=12))
    assert mc.group_shards == 6 and mc.peer_shards == 1
    # Reserving 2 peer shards halves the device budget per group shard.
    mc = MeshConfig.for_groups(cfg_for(num_groups=12), peer_shards=2)
    assert mc.group_shards == 4 and mc.peer_shards == 2


# -- ShardedWAL ---------------------------------------------------------

def test_sharded_wal_routes_and_replays(tmp_path):
    d = str(tmp_path / "p1")
    w = ShardedWAL(d, num_shards=4, groups_per_shard=2)
    # Ranges spanning three shards in one call (groups 0, 3, 6).
    w.append_ranges([0, 3, 6], [1, 1, 1], [2, 1, 1], [1, 1, 1],
                    [b"a", b"b", b"c", b"d"])
    w.set_hardstates(np.array([0, 3, 6]), np.array([1, 1, 1]),
                     np.array([0, 1, 2]), np.array([2, 1, 1]))
    w.sync()
    w.close()
    # Each touched shard got exactly its own groups' records.
    per_shard = [ShardedWAL.replay(d, 4, 2)]
    from raftsql_tpu.storage.wal import WAL, wal_exists
    assert wal_exists(str(tmp_path / "p1" / "s0"))
    assert wal_exists(str(tmp_path / "p1" / "s1"))
    assert wal_exists(str(tmp_path / "p1" / "s3"))
    # Untouched shard: its active segment exists but replays empty.
    assert WAL.replay(str(tmp_path / "p1" / "s2")) == {}
    s0 = WAL.replay(str(tmp_path / "p1" / "s0"))
    assert set(s0) == {0}
    assert [dt for (_, dt) in s0[0].entries] == [b"a", b"b"]
    merged = per_shard[0]
    assert set(merged) == {0, 3, 6}
    assert merged[3].hard.vote == 1
    assert [dt for (_, dt) in merged[6].entries] == [b"d"]


def test_sharded_wal_refuses_reshard(tmp_path):
    d = str(tmp_path / "p1")
    w = ShardedWAL(d, num_shards=2, groups_per_shard=4)
    w.append_ranges([5], [1], [1], [1], [b"x"])   # shard 1 under gl=4
    w.sync()
    w.close()
    with pytest.raises(ValueError, match="different group-shard"):
        ShardedWAL.replay(d, 2, 2)   # gl=2 would put group 5 in shard 2


def test_mesh_node_refuses_reshard(tmp_path):
    cfg = cfg_for()
    mesh4 = MeshConfig(group_shards=4).build()
    node = MeshClusterNode(cfg, str(tmp_path), mesh4)
    node.stop()
    mesh2 = MeshConfig(group_shards=2).build()
    with pytest.raises(ValueError, match="re-sharding"):
        MeshClusterNode(cfg, str(tmp_path), mesh2)


# -- mesh <-> fused equivalence (the property test) ---------------------

def _run_pair(tmp_path, ticks, membership=None, skew_windows=(),
              group_shards=4, peer_shards=1, num_peers=4):
    """Drive a FusedClusterNode and a MeshClusterNode through the SAME
    seeded workload (+ optional identical skew schedule) and assert
    bit-for-bit equal hard states, commit indexes, and applied KV
    stream after every check interval."""
    cfg = cfg_for(num_peers=num_peers)
    mesh = MeshConfig(peer_shards=peer_shards,
                      group_shards=group_shards).build()
    fused = FusedClusterNode(cfg, str(tmp_path / "fused"), seed=3)
    meshn = MeshClusterNode(cfg, str(tmp_path / "mesh"), mesh, seed=3)
    if membership is not None:
        fused.enable_membership(initial_voters=membership)
        meshn.enable_membership(initial_voters=membership)
    rng = np.random.default_rng(0)
    seq = 0
    applied_f, applied_m = [], []
    try:
        for t in range(ticks):
            for g in range(cfg.num_groups):
                if rng.random() < 0.4:
                    payload = f"SET k{g} v{seq}".encode()
                    seq += 1
                    # Same routing state on both sides (asserted below),
                    # so the same propose lands at the same peer.
                    fused.propose_many(g, [payload])
                    meshn.propose_many(g, [payload])
            ti = None
            for (s, e, incs) in skew_windows:
                if s <= t < e:
                    ti = np.asarray(incs, np.int32)
            fused.timer_inc = ti
            meshn.timer_inc = ti
            fused.tick()
            meshn.tick()
            if t % 20 == 19 or t == ticks - 1:
                fused.publish_flush()
                meshn.publish_flush()
                np.testing.assert_array_equal(
                    fused._hard, meshn._hard,
                    err_msg=f"hard state diverged at tick {t}")
                np.testing.assert_array_equal(
                    fused._hints, meshn._hints,
                    err_msg=f"leader hints diverged at tick {t}")
                np.testing.assert_array_equal(
                    fused._applied, meshn._applied,
                    err_msg=f"publish cursors diverged at tick {t}")
                applied_f.extend(drain(fused))
                applied_m.extend(drain(meshn))
                assert applied_f == applied_m, f"KV stream at tick {t}"
        assert (fused._hard[:, :, 2] > 0).any(), "nothing ever committed"
        assert applied_f, "no applied KV to compare"
    finally:
        fused.stop()
        meshn.stop()
    return applied_f


def test_mesh_fused_equivalence_full_voters(tmp_path):
    applied = _run_pair(tmp_path, ticks=100)
    assert len(applied) > 20


def test_mesh_fused_equivalence_peer_sharded(tmp_path):
    # The peers x groups mesh: message exchange rides the all_to_all
    # route; the host contract must not notice.
    applied = _run_pair(tmp_path, ticks=80, group_shards=4,
                        peer_shards=2)
    assert applied


def test_mesh_fused_equivalence_masked_membership(tmp_path):
    # Boot a 3-of-4 voter config over provisioned slot capacity: every
    # quorum kernel runs mask-weighted, and the mesh must reproduce the
    # fused runtime's masked elections and commits exactly.
    applied = _run_pair(tmp_path, ticks=100, membership=(0, 1, 2))
    assert applied


def test_mesh_fused_equivalence_under_skew(tmp_path):
    # The SAME per-peer skew schedule on both runtimes: the sharded
    # step's [P] timer vector must be semantically identical to the
    # fused step's — the closed MeshLockstepOnlyError frontier.
    windows = ((20, 50, (2, 0, 1, 1)), (60, 80, (1, 3, 1, 0)))
    applied = _run_pair(tmp_path, ticks=100, skew_windows=windows)
    assert applied


# -- acked writes over the product stack --------------------------------

def test_mesh_acked_writes_sharded_groups(tmp_path):
    """Acceptance: under forced host devices the mesh runtime commits
    ACKED writes with G sharded over >= 2 devices, through the full
    RaftDB product stack (propose -> device step -> per-shard WAL fsync
    -> publish workers -> SQLite apply -> ack)."""
    import jax

    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.fused import FusedPipe

    assert len(jax.devices()) >= 2
    cfg = cfg_for(num_peers=3, num_groups=4)
    mesh = MeshConfig(group_shards=2).build()
    assert mesh.shape["groups"] >= 2
    node = MeshClusterNode(cfg, str(tmp_path / "data"), mesh)
    node.start(interval_s=0.001)
    rdb = RaftDB(lambda g: SQLiteStateMachine(":memory:"),
                 FusedPipe(node), num_groups=4)
    try:
        futs = [rdb.propose("CREATE TABLE t (k TEXT, v TEXT)", group=g)
                for g in range(4)]
        errs = [f.wait(30) for f in futs]
        futs = [rdb.propose(f"INSERT INTO t VALUES ('k', 'g{g}')",
                            group=g) for g in range(4)]
        errs += [f.wait(30) for f in futs]
        assert all(e is None for e in errs), errs
        for g in range(4):
            assert rdb.query("SELECT v FROM t WHERE k='k'",
                             group=g) == f"|g{g}|\n"
    finally:
        rdb.close()


# -- skew on the mesh (replaces the PR-4 lockstep regression) -----------

def test_mesh_skew_changes_elections(tmp_path):
    """Same seed, lockstep vs per-peer skew on the MESH runtime: the
    election outcomes must demonstrably differ — proof the sharded
    timer vector actually reaches every peer block's clocks (and not,
    say, only shard 0's)."""
    import dataclasses as dc

    from raftsql_tpu.chaos.scenarios import MeshChaosRunner
    from raftsql_tpu.chaos.schedule import generate_skew

    sk = generate_skew(0, ticks=120)
    lock = dc.replace(sk, skews=())
    ra = MeshChaosRunner(lock, str(tmp_path / "lock"))
    rep_a = ra.run()
    rb = MeshChaosRunner(sk, str(tmp_path / "skew"))
    rep_b = rb.run()
    assert rep_b["skew_ticks"] > 0 and rep_a["skew_ticks"] == 0
    assert rep_a["result_digest"] != rep_b["result_digest"]
    # Skew fault counters export through NodeMetrics (the /metrics
    # surface), from the mesh runtime too.
    assert rb.final_metrics.faults_skew_ticks == rep_b["skew_ticks"]


def test_mesh_skew_chaos_reproduces(tmp_path):
    from raftsql_tpu.chaos.scenarios import MeshChaosRunner
    from raftsql_tpu.chaos.schedule import generate_skew

    sk = generate_skew(4, ticks=100)
    r1 = MeshChaosRunner(sk, str(tmp_path / "a")).run()
    r2 = MeshChaosRunner(sk, str(tmp_path / "b")).run()
    assert (r1["schedule_digest"], r1["result_digest"]) \
        == (r2["schedule_digest"], r2["result_digest"])
    assert r1["skew_ticks"] > 0 and r1["crashes"] >= 1


def test_mesh_skew_matches_fused_chaos(tmp_path):
    """The SAME skew schedule through the fused and the mesh chaos
    runners must produce the SAME result digest: the chaos harness is
    another witness that sharding never changes semantics — crashes,
    per-shard WAL replay and all."""
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner, MeshChaosRunner
    from raftsql_tpu.chaos.schedule import generate_skew

    sk = generate_skew(2, ticks=100)
    rf = FusedChaosRunner(sk, str(tmp_path / "fused")).run()
    rm = MeshChaosRunner(sk, str(tmp_path / "mesh")).run()
    assert rf["result_digest"] == rm["result_digest"], (rf, rm)
