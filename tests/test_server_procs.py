"""Real-process cluster smoke test — the Procfile topology end to end.

The reference's proof of life is 3 OS processes wired by real sockets
(reference Procfile:2-4, raftsql_test.go:16-41).  The in-process cluster
tests all ride LoopbackTransport; these tests boot 3 actual
`raftsql_tpu.server.main` processes on localhost (TcpTransport + HTTP API
+ WAL + SQLite) via the chaos harness's ProcCluster, drive them with the
hardened HTTP client (api/client.py — per-request timeouts, backoff,
leader caching, retry tokens: the former private `sql`/`put_when_up`/
`get_retry` helpers, done properly once), then crash-restart a node and
require catch-up.
"""
import pytest

from raftsql_tpu.api.client import RaftSQLClient, SQLError
from raftsql_tpu.chaos.proc import ProcCluster

TIMEOUT = 90.0


def _boot3(tmp_path, groups: int = 1):
    c = ProcCluster(str(tmp_path), peers=3, groups=groups, tick=0.02)
    for i in range(3):
        c.spawn(i)
    cli = RaftSQLClient([f"127.0.0.1:{p}" for p in c.http_ports],
                        timeout_s=10.0)
    return c, cli


def _logs(c: ProcCluster) -> str:
    return "\n".join(f"--- node{i + 1} ---\n" + c.log_tail(i, 2000)
                     for i in range(3))


def test_three_process_cluster_put_get_restart(tmp_path):
    c, cli = _boot3(tmp_path)
    try:
        # README curl recipe: PUT on node 1, INSERT via node 2, read on 3.
        cli.put("CREATE TABLE t (name text)", node=0,
                deadline_s=TIMEOUT)
        cli.put("INSERT INTO t (name) VALUES ('abc')", node=1,
                deadline_s=TIMEOUT)
        cli.get_until("SELECT name FROM t", "|abc|\n", node=2,
                      deadline_s=TIMEOUT)
        # Method semantics over the real stack: 405 + Allow header.
        status, _, _ = cli.raw(0, "POST", "/", "x")
        assert status == 405
        # Bad SQL propagates the apply error as 400 (reference
        # httpapi.go:45-49 blocking-PUT contract) — the client must NOT
        # retry a deterministic failure.
        with pytest.raises(SQLError):
            cli.put("INSERT INTO nosuch VALUES (1)", node=0,
                    deadline_s=TIMEOUT)

        # Crash node 2 (SIGKILL), write while it is down, restart it, and
        # require the missed write to stream in from the leader
        # (reference raftsql_test.go:117-170).
        c.sigkill(1)
        cli.put("INSERT INTO t (name) VALUES ('while-down')", node=0,
                deadline_s=TIMEOUT)
        c.spawn(1)
        try:
            cli.get_until("SELECT count(*) FROM t", "|2|\n", node=1,
                          deadline_s=TIMEOUT)
        except BaseException:
            print(_logs(c))
            raise
        # Clean stop is SIGTERM (graceful-shutdown handler): the WAL is
        # flushed and every process exits 0 — SIGKILL above was "crash",
        # this is "stop".
        codes = c.stop_all()
        assert codes == [0, 0, 0], (codes, _logs(c))
    finally:
        c.stop_all()


def test_multi_group_over_real_processes(tmp_path):
    """The flagship axis (N raft groups) over the reference's proof-of-
    life topology (3 OS processes, real sockets): writes routed to
    distinct groups via different nodes, per-group isolation (each group
    is its own SQLite database), and group state surviving a SIGKILL
    crash/restart — VERDICT r2 task 7."""
    c, cli = _boot3(tmp_path, groups=4)
    try:
        # One table per group, created via a different node each time;
        # rows encode the group id.
        for g in range(4):
            node = g % 3
            cli.put("CREATE TABLE t (v text)", group=g, node=node,
                    deadline_s=TIMEOUT)
            cli.put(f"INSERT INTO t (v) VALUES ('g{g}')", group=g,
                    node=node, deadline_s=TIMEOUT)
        # Every node serves every group; each group sees ONLY its row.
        for g in range(4):
            for node in range(3):
                cli.get_until("SELECT v FROM t", f"|g{g}|\n", group=g,
                              node=node, deadline_s=TIMEOUT)
        # Unknown group -> 400, not a crash.
        status, _, _ = cli.raw(0, "GET", "/", "SELECT v FROM t",
                               headers={"X-Raft-Group": "99"})
        assert status == 400

        # Crash node 3; write to two different groups while it is down;
        # restart; both groups' missed writes must stream in, and the
        # untouched groups must stay isolated.
        c.sigkill(2)
        cli.put("INSERT INTO t (v) VALUES ('late1')", group=1, node=0,
                deadline_s=TIMEOUT)
        cli.put("INSERT INTO t (v) VALUES ('late3')", group=3, node=1,
                deadline_s=TIMEOUT)
        c.spawn(2)
        try:
            for g, want in ((1, "|2|\n"), (3, "|2|\n"),
                            (0, "|1|\n"), (2, "|1|\n")):
                cli.get_until("SELECT count(*) FROM t", want, group=g,
                              node=2, deadline_s=TIMEOUT)
        except BaseException:
            print(_logs(c))
            raise
    finally:
        c.stop_all()
