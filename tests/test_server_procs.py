"""Real-process cluster smoke test — the Procfile topology end to end.

The reference's proof of life is 3 OS processes wired by real sockets
(reference Procfile:2-4, raftsql_test.go:16-41).  The in-process cluster
tests all ride LoopbackTransport; this test boots 3 actual
`raftsql_tpu.server.main` processes on localhost (TcpTransport + HTTP API
+ WAL + SQLite), drives them with HTTP like the README's curl recipe, then
crash-restarts one node and requires catch-up.
"""
import http.client
import os
import signal
import subprocess
import sys
import time

import pytest

from conftest import reserve_ports

TIMEOUT = 90.0


def sql(port: int, method: str, body: str, timeout: float = 60.0,
        group: int | None = None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = {} if group is None else {"X-Raft-Group": str(group)}
    try:
        conn.request(method, "/", body=body.encode(), headers=headers)
        r = conn.getresponse()
        return r.status, r.read().decode()
    finally:
        conn.close()


def put_when_up(port: int, body: str, deadline: float,
                group: int | None = None) -> None:
    """PUT once the node is reachable; a PUT is only retried while the
    connection is REFUSED (nothing was enqueued), never after the server
    accepted it — re-sending a slow-but-committed write would duplicate
    it (writes here are not idempotent, matching the reference's
    content-keyed ack model, db.go:112-118)."""
    last = None
    while time.monotonic() < deadline:
        try:
            status, text = sql(port, "PUT", body, group=group)
            assert status == 204, (status, text)
            return
        except ConnectionRefusedError as e:
            last = e
            time.sleep(0.25)
    pytest.fail(f"PUT {body!r} on :{port}: never reachable, last={last}")


def get_retry(port: int, body: str, want_body: str,
              deadline: float, group: int | None = None) -> str:
    """Idempotent read: retry until the answer matches (replication is
    async; the reference polls the same way, raftsql_test.go:159-170)."""
    last = None
    while time.monotonic() < deadline:
        try:
            status, text = sql(port, "GET", body, group=group)
            last = (status, text)
            if status == 200 and text == want_body:
                return text
        except OSError:
            last = ("conn", None)
        time.sleep(0.25)
    pytest.fail(f"GET {body!r} on :{port}: wanted {want_body!r}, "
                f"last={last}")


class Cluster3:
    """3 server/main.py subprocesses on free localhost ports."""

    def __init__(self, tmp_path, groups: int = 1):
        self.tmp = tmp_path
        self.groups = groups
        ports, release = reserve_ports(6)  # held until just before Popen
        self.peer_ports, self.http_ports = ports[:3], ports[3:]
        self.cluster = ",".join(f"http://127.0.0.1:{p}"
                                for p in self.peer_ports)
        self.procs = [None, None, None]
        self._release_ports = release
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        self.env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=repo_root + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""))
        self._release_ports()
        for i in range(3):
            self.start(i)

    def start(self, i: int) -> None:
        logf = open(self.tmp / f"node{i + 1}.log", "ab")
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.main",
             "--id", str(i + 1), "--cluster", self.cluster,
             "--port", str(self.http_ports[i]), "--tick", "0.02",
             "--groups", str(self.groups)],
            cwd=self.tmp, env=self.env, stdout=logf, stderr=logf)

    def kill(self, i: int) -> None:
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGKILL)     # crash, not graceful stop
            p.wait(timeout=10)
        self.procs[i] = None

    def stop_all(self) -> None:
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.procs:
            if p is not None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()

    def logs(self) -> str:
        out = []
        for i in range(3):
            f = self.tmp / f"node{i + 1}.log"
            if f.exists():
                out.append(f"--- node{i + 1} ---\n"
                           + f.read_text()[-2000:])
        return "\n".join(out)


def test_three_process_cluster_put_get_restart(tmp_path):
    c = Cluster3(tmp_path)
    try:
        deadline = time.monotonic() + TIMEOUT
        # README curl recipe: PUT on node 1, INSERT via node 2, read on 3.
        put_when_up(c.http_ports[0], "CREATE TABLE t (name text)",
                    deadline)
        put_when_up(c.http_ports[1], "INSERT INTO t (name) VALUES ('abc')",
                    deadline)
        get_retry(c.http_ports[2], "SELECT name FROM t", "|abc|\n",
                  deadline)
        # Method semantics over the real stack: 405 + Allow header.
        status, _ = sql(c.http_ports[0], "POST", "x")
        assert status == 405
        # Bad SQL propagates the apply error as 400 (reference
        # httpapi.go:45-49 blocking-PUT contract).
        status, _ = sql(c.http_ports[0], "PUT", "INSERT INTO nosuch "
                        "VALUES (1)")
        assert status == 400

        # Crash node 2 (SIGKILL), write while it is down, restart it, and
        # require the missed write to stream in from the leader
        # (reference raftsql_test.go:117-170).
        c.kill(1)
        deadline = time.monotonic() + TIMEOUT
        put_when_up(c.http_ports[0],
                    "INSERT INTO t (name) VALUES ('while-down')", deadline)
        c.start(1)
        deadline = time.monotonic() + TIMEOUT
        try:
            get_retry(c.http_ports[1], "SELECT count(*) FROM t", "|2|\n",
                      deadline)
        except BaseException:
            print(c.logs())
            raise
    finally:
        c.stop_all()


def test_multi_group_over_real_processes(tmp_path):
    """The flagship axis (N raft groups) over the reference's proof-of-
    life topology (3 OS processes, real sockets): writes routed to
    distinct groups via different nodes, per-group isolation (each group
    is its own SQLite database), and group state surviving a SIGKILL
    crash/restart — VERDICT r2 task 7."""
    c = Cluster3(tmp_path, groups=4)
    try:
        deadline = time.monotonic() + TIMEOUT
        # One table per group, created via a different node each time;
        # rows encode the group id.
        for g in range(4):
            node = g % 3
            put_when_up(c.http_ports[node], "CREATE TABLE t (v text)",
                        deadline, group=g)
            put_when_up(c.http_ports[node],
                        f"INSERT INTO t (v) VALUES ('g{g}')",
                        deadline, group=g)
        # Every node serves every group; each group sees ONLY its row.
        for g in range(4):
            for node in range(3):
                get_retry(c.http_ports[node], "SELECT v FROM t",
                          f"|g{g}|\n", deadline, group=g)
        # Unknown group -> 400, not a crash.
        status, _ = sql(c.http_ports[0], "GET", "SELECT v FROM t",
                        group=99)
        assert status == 400

        # Crash node 3; write to two different groups while it is down;
        # restart; both groups' missed writes must stream in, and the
        # untouched groups must stay isolated.
        c.kill(2)
        deadline = time.monotonic() + TIMEOUT
        put_when_up(c.http_ports[0],
                    "INSERT INTO t (v) VALUES ('late1')", deadline, group=1)
        put_when_up(c.http_ports[1],
                    "INSERT INTO t (v) VALUES ('late3')", deadline, group=3)
        c.start(2)
        deadline = time.monotonic() + TIMEOUT
        try:
            get_retry(c.http_ports[2], "SELECT count(*) FROM t", "|2|\n",
                      deadline, group=1)
            get_retry(c.http_ports[2], "SELECT count(*) FROM t", "|2|\n",
                      deadline, group=3)
            get_retry(c.http_ports[2], "SELECT count(*) FROM t", "|1|\n",
                      deadline, group=0)
            get_retry(c.http_ports[2], "SELECT count(*) FROM t", "|1|\n",
                      deadline, group=2)
        except BaseException:
            print(c.logs())
            raise
    finally:
        c.stop_all()
