"""Process-plane chaos tests (chaos/proc.py + the seams it rides).

Fast tier: seeded-plan determinism, the RAFTSQL_FSIO_FAULTS grammar,
the retry-token exactly-once path, one full nemesis run over 3 real
server processes (every fault family: leader SIGKILL, random SIGKILL,
leader SIGSTOP/SIGCONT, rolling-restart storm, env-injected ENOSPC and
exit-at-fsync), and the SIGSTOP satellite: a stalled leader must be
deposed while frozen, rejoin as a follower, and lose nothing acked.

The slow tier sweeps more seeds and proves the verdict-digest
reproducibility claim by running one seed twice (the `make chaos-procs`
contract, which CI also runs).
"""
import dataclasses
import time

import pytest

from raftsql_tpu.api.client import RaftSQLClient
from raftsql_tpu.chaos.proc import ProcChaosRunner, ProcCluster
from raftsql_tpu.chaos.schedule import generate_procs
from raftsql_tpu.storage import fsio


# ---------------------------------------------------------------------------
# seeded plans + env grammar (no processes)

def test_proc_plan_is_deterministic_per_seed():
    for seed in (0, 1, 17):
        a, b = generate_procs(seed), generate_procs(seed)
        assert a == b and a.digest() == b.digest()
    assert generate_procs(0).digest() != generate_procs(1).digest()


def test_proc_plan_has_every_fault_family():
    plan = generate_procs(3)
    assert len(plan.kills) >= 2
    assert any(k.peer == -2 for k in plan.kills)   # leader-targeted
    assert len(plan.stalls) >= 1 and len(plan.storms) >= 1
    specs = " ".join(f.spec for f in plan.fsio)
    assert "enospc@" in specs and "exit_fsync@" in specs
    assert plan.ticks >= max(s.tick for s in plan.storms)


def test_fsio_env_spec_grammar():
    rules = fsio.parse_env_spec(
        "raftsql-2:enospc@12;raftsql-1:exit_fsync@9:stall@4x3x50")
    assert rules[0] == {"substring": "raftsql-2",
                       "enospc_write_at": [12]}
    assert rules[1]["exit_at"] == [9]
    assert rules[1]["stall_at"] == [4, 5, 6]
    assert rules[1]["stall_s"] == 0.05
    assert fsio.parse_env_spec("") == []
    for bad in ("nocolon", ":enospc@1", "raftsql-1:enospc",
                "raftsql-1:bogus@3", "raftsql-1:stall@1x2"):
        with pytest.raises(ValueError):
            fsio.parse_env_spec(bad)


def test_fsio_install_from_env_round_trip():
    inj = fsio.install_from_env("raftsql-9:enospc@2")
    try:
        assert fsio.active() and inj is fsio.injector()
        assert inj.rules[0].enospc_write_at == {2}
    finally:
        fsio.uninstall()
    assert fsio.install_from_env("") is None and not fsio.active()


# ---------------------------------------------------------------------------
# the nemesis over real processes

def test_proc_chaos_seeded_run_all_families(tmp_path):
    """One full seeded nemesis run over 3 real server processes: every
    scripted fault family fires, no child dies of anything unscripted,
    every invariant holds (violations raise out of run()), and no
    acked write is lost (the convergence + post-mortem gates inside
    run())."""
    plan = dataclasses.replace(generate_procs(0, ticks=48),
                               tick_s=0.2, heal_ticks=25)
    r = ProcChaosRunner(plan, str(tmp_path)).run()
    assert r["schedule_digest"] == plan.digest()
    assert r["kills"] >= len(plan.kills)
    assert r["stalls"] >= len(plan.stalls)
    assert r["storm_restarts"] >= plan.peers * len(plan.storms)
    assert r["fsio_exits"] >= 1, r       # exit_fsync crash point fired
    assert r["fatal_exits"] >= 1, r      # env ENOSPC killed its child
    assert r["unexpected_exits"] == 0, r
    assert r["acked"] > 10, r            # the workload made progress


@pytest.mark.slow
def test_proc_chaos_verdict_digest_reproduces(tmp_path):
    """The `make chaos-procs` determinism contract: one seed, two runs,
    identical schedule + verdict digests (committed histories differ —
    real kernel scheduling — the VERDICT is what must reproduce)."""
    plan = dataclasses.replace(generate_procs(1, ticks=48),
                               tick_s=0.2, heal_ticks=25)
    a = ProcChaosRunner(plan, str(tmp_path / "a")).run()
    b = ProcChaosRunner(plan, str(tmp_path / "b")).run()
    assert (a["schedule_digest"], a["result_digest"]) \
        == (b["schedule_digest"], b["result_digest"])


@pytest.mark.slow
def test_proc_chaos_seed_sweep(tmp_path):
    for seed in (2, 3):
        plan = dataclasses.replace(generate_procs(seed, ticks=48),
                                   tick_s=0.2, heal_ticks=25)
        r = ProcChaosRunner(plan, str(tmp_path / f"s{seed}")).run()
        assert r["unexpected_exits"] == 0, r


# ---------------------------------------------------------------------------
# the SIGSTOP satellite: stall == GC pause / VM freeze, not death

def _role(doc, g="0"):
    return doc["groups"][g]["role"] if doc else None


def _term(doc, g="0"):
    return doc["groups"][g]["term"] if doc else 0


def test_sigstopped_leader_is_deposed_and_rejoins_as_follower(tmp_path):
    """A SIGSTOPped leader is indistinguishable from a dead one to its
    peers — they must elect a successor — but the process is NOT dead:
    on SIGCONT it wakes believing it still leads, must step down on
    first contact with the higher term, and every write acked before
    (and during) the stall must survive on every node."""
    c = ProcCluster(str(tmp_path), peers=3, tick=0.02)
    cli = RaftSQLClient([f"127.0.0.1:{p}" for p in c.http_ports],
                        timeout_s=3.0)
    try:
        for i in range(3):
            c.spawn(i)
        for i in range(3):
            cli.wait_healthy(i, deadline_s=60.0)
        cli.put("CREATE TABLE t (v text)", deadline_s=60.0)
        for k in range(5):
            cli.put(f"INSERT INTO t (v) VALUES ('w{k}')",
                    deadline_s=30.0)

        # Find the current leader of group 0.
        deadline = time.monotonic() + 30.0
        leader, old_term = None, 0
        while leader is None:
            assert time.monotonic() < deadline, "no leader emerged"
            for i in range(3):
                doc = cli.health(i)
                if _role(doc) == "leader":
                    leader, old_term = i, _term(doc)
                    break
            time.sleep(0.2)

        c.sigstop(leader)
        others = [i for i in range(3) if i != leader]
        # The survivors must depose the frozen leader: a new leader in
        # a STRICTLY higher term.
        deadline = time.monotonic() + 30.0
        new_term = 0
        while not new_term:
            assert time.monotonic() < deadline, \
                "no successor elected while leader was stalled"
            for i in others:
                doc = cli.health(i)
                if _role(doc) == "leader" and _term(doc) > old_term:
                    new_term = _term(doc)
                    break
            time.sleep(0.2)
        # A write acked DURING the stall (the client routes around the
        # frozen node) — it must survive the old leader's return.
        cli.put("INSERT INTO t (v) VALUES ('during-stall')",
                deadline_s=30.0)

        c.sigcont(leader)
        # The woken leader must abandon its old reign: its term must
        # catch up to the successor's, and it must pass through (and,
        # with a live leader heartbeating, stay in) the follower role.
        deadline = time.monotonic() + 30.0
        saw_follower = False
        while True:
            doc = cli.health(leader)
            if doc is not None and _term(doc) >= new_term:
                if _role(doc) == "follower":
                    saw_follower = True
                    break
            assert time.monotonic() < deadline, \
                f"stalled ex-leader never rejoined as follower: {doc}"
            time.sleep(0.2)
        assert saw_follower

        # Nothing acked before or during the stall may be lost —
        # including on the ex-leader itself.
        want = "".join(f"|{v}|\n" for v in
                       sorted(["during-stall"] + [f"w{k}"
                                                  for k in range(5)]))
        for i in range(3):
            cli.get_until("SELECT v FROM t ORDER BY v", want, node=i,
                          deadline_s=60.0)
    finally:
        c.stop_all()
