"""Snapshot-resume + WAL compaction (the checkpoint/resume subsystem
beyond the reference's delete-and-replay, SURVEY.md §5.4).

Key invariants:
  - resume mode applies each entry EXACTLY once across crashes (the
    applied_index is committed in the same SQLite transaction as the
    command, so double-apply would show up as duplicate rows);
  - WAL.rewrite drops snapshot-covered prefixes but restart still yields
    the same log positions/terms (boundary marker record);
  - a compacted node restarts correctly and keeps serving;
  - default mode stays reference-parity (file deleted, full replay).
"""
import os

import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.storage.wal import WAL, GroupLog, HardState
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport

TICK = 0.005
TIMEOUT = 30.0


class TestSQLiteResume:
    def test_applied_index_atomic_with_apply(self, tmp_path):
        p = str(tmp_path / "a.db")
        sm = SQLiteStateMachine(p, resume=True)
        assert sm.applied_index() == 0
        assert sm.apply("CREATE TABLE t (v int)", index=1) is None
        assert sm.apply("INSERT INTO t VALUES (7)", index=2) is None
        assert sm.applied_index() == 2
        sm.close()
        sm2 = SQLiteStateMachine(p, resume=True)
        assert sm2.applied_index() == 2
        assert sm2.query("SELECT * FROM t") == "|7|\n"
        sm2.close()

    def test_failed_apply_still_advances_index(self, tmp_path):
        p = str(tmp_path / "b.db")
        sm = SQLiteStateMachine(p, resume=True)
        assert sm.apply("CREATE TABLE t (v int)", index=1) is None
        assert sm.apply("INSERT INTO nosuch VALUES (1)", index=2) \
            is not None
        assert sm.applied_index() == 2
        sm.close()

    def test_default_mode_deletes_file(self, tmp_path):
        p = str(tmp_path / "c.db")
        sm = SQLiteStateMachine(p)
        sm.apply("CREATE TABLE t (v int)", index=1)
        sm.apply("INSERT INTO t VALUES (1)", index=2)
        sm.close()
        sm2 = SQLiteStateMachine(p)           # reference parity: nuked
        with pytest.raises(Exception):
            sm2.query("SELECT * FROM t")
        sm2.close()


class TestWALRewrite:
    def test_rewrite_preserves_positions(self, tmp_path):
        d = str(tmp_path / "w")
        w = WAL(d)
        for i in range(1, 11):
            w.append_entry(0, i, 1, f"e{i}".encode())
        w.set_hardstate(0, 1, 0, 10)
        w.close()
        gl = WAL.replay(d)[0]
        # Compact away entries <= 6.
        image = {0: GroupLog(hard=HardState(1, 0, 10),
                             entries=gl.entries[6:], start=6,
                             start_term=gl.entries[5][0])}
        WAL.rewrite(d, image)
        gl2 = WAL.replay(d)[0]
        assert gl2.start == 6
        assert gl2.start_term == 1
        assert gl2.log_len == 10
        assert [e[1] for e in gl2.entries] == [b"e7", b"e8", b"e9", b"e10"]
        # Appends after the rewrite keep working at absolute positions.
        w2 = WAL(d)
        w2.append_entry(0, 11, 2, b"e11")
        w2.close()
        gl3 = WAL.replay(d)[0]
        assert gl3.log_len == 11
        assert gl3.entries[-1] == (2, b"e11")


def _boot(tmp_path, hub, cfg, i, resume, compact_every=0):
    pipe = RaftPipe.create(
        i + 1, cfg.num_peers, cfg, LoopbackTransport(hub),
        data_dir=str(tmp_path / f"raftsql-{i + 1}"))
    return RaftDB(
        lambda g, i=i: SQLiteStateMachine(
            str(tmp_path / f"snap-{i}.db"), resume=resume),
        pipe, resume=resume, compact_every=compact_every,
        compact_keep=0)


class TestClusterResume:
    def test_exactly_once_across_restart(self, tmp_path):
        """INSERTs without keys: a double-apply after restart would show
        as duplicate rows."""
        hub = LoopbackHub()
        cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                         log_window=32, max_entries_per_msg=4)
        dbs = [_boot(tmp_path, hub, cfg, i, resume=True) for i in range(3)]
        try:
            assert dbs[0].propose(
                "CREATE TABLE t (v int)").wait(TIMEOUT) is None
            for k in range(10):
                assert dbs[0].propose(
                    f"INSERT INTO t VALUES ({k})").wait(TIMEOUT) is None
            import time
            deadline = time.monotonic() + TIMEOUT
            while dbs[1].query("SELECT count(*) FROM t") != "|10|\n":
                assert time.monotonic() < deadline
                time.sleep(0.02)
            dbs[1].close()
            dbs[1] = _boot(tmp_path, hub, cfg, 1, resume=True)
            # After restart + replay the count must be exactly 10: the
            # replayed prefix was skipped, not re-applied.
            deadline = time.monotonic() + TIMEOUT
            while True:
                v = dbs[1].query("SELECT count(*) FROM t")
                if v == "|10|\n":
                    break
                assert v in ("|10|\n",) or int(v.strip("|\n")) <= 10, \
                    f"double apply: {v!r}"
                assert time.monotonic() < deadline
                time.sleep(0.02)
        finally:
            for db in dbs:
                db.close()

    def test_compaction_shrinks_wal_and_restarts(self, tmp_path):
        hub = LoopbackHub()
        # Tiny segments so the 81-entry run rotates several times and
        # compaction can drop whole pre-floor segments (VERDICT: no
        # stop-the-world rewrite of live data).
        cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                         log_window=16, max_entries_per_msg=4,
                         wal_segment_bytes=2048)
        dbs = [_boot(tmp_path, hub, cfg, i, resume=True, compact_every=20)
               for i in range(3)]
        try:
            assert dbs[0].propose(
                "CREATE TABLE t (v int)").wait(TIMEOUT) is None
            for k in range(80):
                assert dbs[0].propose(
                    f"INSERT INTO t VALUES ({k})").wait(TIMEOUT) is None
            # At least one node compacted (keep clamps to log_window=16,
            # applied ~81 >> 16).
            assert any(db.metrics()["compactions"] > 0 for db in dbs)
            segs = sorted((tmp_path / "raftsql-1").glob("wal-*.log"))
            walsz = sum(os.path.getsize(s) for s in segs)
            # Un-compacted the 81-insert log spans many 2 KiB segments;
            # compaction must have unlinked the pre-floor ones.
            assert walsz < 6144, (walsz, segs)
            assert segs[0].name != "wal-0.log", segs   # oldest seg dropped
            # Restart a compacted node; it must come back consistent.
            dbs[0].close()
            dbs[0] = _boot(tmp_path, hub, cfg, 0, resume=True)
            import time
            deadline = time.monotonic() + TIMEOUT
            while dbs[0].query("SELECT count(*) FROM t") != "|80|\n":
                assert time.monotonic() < deadline
                time.sleep(0.02)
        finally:
            for db in dbs:
                db.close()


class TestSnapshotTermCheck:
    """Receiver-side term rule for InstallSnapshot (raft: reject RPCs with
    term < currentTerm; adopt term > currentTerm)."""

    def _node(self, tmp_path):
        from raftsql_tpu.runtime.node import RaftNode
        hub = LoopbackHub()
        cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                         log_window=16, max_entries_per_msg=4)
        node = RaftNode(1, 3, cfg, LoopbackTransport(hub),
                        str(tmp_path / "raftsql-1"))
        installs = []
        node.snapshot_installer = \
            lambda g, idx, blob: installs.append((g, idx, blob))
        return node, installs

    def test_stale_term_snapshot_rejected(self, tmp_path):
        from raftsql_tpu.transport.base import SnapshotRec
        node, installs = self._node(tmp_path)
        node.state = node.state._replace(
            term=node.state.term.at[0].set(5))
        node._stage_snaps[0] = SnapshotRec(
            group=0, last_idx=50, last_term=3, term=3, blob=b"{}")
        node._install_snapshots()
        assert installs == []           # deposed leader's transfer dropped
        assert int(node.state.term[0]) == 5
        assert int(node.state.commit[0]) == 0

    def test_higher_term_duplicate_still_steps_down(self, tmp_path):
        """Term adoption fires on receipt of a valid higher-term RPC even
        when the transfer itself is a duplicate (raft §5.1)."""
        from raftsql_tpu.config import FOLLOWER, LEADER
        from raftsql_tpu.transport.base import SnapshotRec
        node, installs = self._node(tmp_path)
        node.state = node.state._replace(
            term=node.state.term.at[0].set(5),
            role=node.state.role.at[0].set(LEADER),
            commit=node.state.commit.at[0].set(60))
        node._stage_snaps[0] = SnapshotRec(
            group=0, last_idx=50, last_term=7, term=7, blob=b"{}")
        node._install_snapshots()
        assert installs == []           # last_idx <= commit: not installed
        assert int(node.state.term[0]) == 7
        assert int(node.state.role[0]) == FOLLOWER

    def test_higher_term_snapshot_adopts_term(self, tmp_path):
        from raftsql_tpu.transport.base import SnapshotRec
        node, installs = self._node(tmp_path)
        node.state = node.state._replace(
            term=node.state.term.at[0].set(5),
            voted_for=node.state.voted_for.at[0].set(2))
        node._stage_snaps[0] = SnapshotRec(
            group=0, last_idx=50, last_term=7, term=7, blob=b"{}")
        node._install_snapshots()
        assert installs == [(0, 50, b"{}")]
        assert int(node.state.term[0]) == 7      # term catch-up
        assert int(node.state.commit[0]) == 50
        from raftsql_tpu.config import NO_VOTE
        assert int(node.state.voted_for[0]) == NO_VOTE


class TestInstallSnapshot:
    def test_follower_beyond_floor_gets_full_transfer(self, tmp_path):
        """Kill a follower, write + compact far past its position, then
        restart it: the prefix it needs is gone from every log, so the
        leader must ship a full state-machine image (InstallSnapshot) and
        resume replication above it."""
        import time
        hub = LoopbackHub()
        cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                         log_window=16, max_entries_per_msg=4)
        dbs = [_boot(tmp_path, hub, cfg, i, resume=True, compact_every=10)
               for i in range(3)]
        try:
            assert dbs[0].propose(
                "CREATE TABLE t (v int)").wait(TIMEOUT) is None
            dbs[1].close()
            dbs[1] = None
            for k in range(120):    # >> log_window + compact keep
                assert dbs[0].propose(
                    f"INSERT INTO t VALUES ({k})").wait(TIMEOUT) is None
            assert any(db is not None and db.metrics()["compactions"] > 0
                       for db in dbs)
            dbs[1] = _boot(tmp_path, hub, cfg, 1, resume=True)
            deadline = time.monotonic() + TIMEOUT
            while True:
                # "no such table" is a legitimate transient on the
                # freshly restarted replica (stale local reads by
                # design): if it died before applying the CREATE, its
                # kept SQLite file has no `t` until the InstallSnapshot
                # lands — poll through it (test_cluster_sql.py's
                # catch-up loops tolerate the same transient).
                try:
                    got = dbs[1].query("SELECT count(*) FROM t")
                except Exception:
                    got = None
                if got == "|120|\n":
                    break
                assert time.monotonic() < deadline, (
                    got, [db.metrics() for db in dbs if db])
                time.sleep(0.02)
            assert sum(db.metrics()["snapshots_sent"]
                       for db in dbs if db) > 0
            assert dbs[1].metrics()["snapshots_installed"] > 0
            # And the installed follower keeps replicating live traffic.
            assert dbs[0].propose(
                "INSERT INTO t VALUES (999)").wait(TIMEOUT) is None
            deadline = time.monotonic() + TIMEOUT
            while "999" not in dbs[1].query("SELECT v FROM t"):
                assert time.monotonic() < deadline
                time.sleep(0.02)

            # Installed state must be ON DISK, not a connection-local
            # in-memory copy: restart the installed follower and require
            # its applied_index/data to come back from the FILE without
            # needing another transfer (sqlite3.deserialize detaches to
            # memory — install writes the image to the path instead).
            installed_applied = dbs[1]._sms[0].applied_index()
            assert installed_applied >= 120
            dbs[1].close()
            dbs[1] = _boot(tmp_path, hub, cfg, 1, resume=True)
            assert dbs[1]._sms[0].applied_index() >= installed_applied
            assert "999" in dbs[1].query("SELECT v FROM t")
        finally:
            for db in dbs:
                if db is not None:
                    db.close()
