"""Unit tests for the batched raft core: election, replication, commit.

These cover what the reference delegates to the vendored etcd/raft library
(reference raft.go:30, L0 in SURVEY.md) and therefore never tests itself —
SURVEY.md §4 lists leader-election tests among the gaps to close.
"""
import jax
import jax.numpy as jnp
import numpy as np

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.core.cluster import (cluster_run, empty_cluster_inbox,
                                      init_cluster_state)
from raftsql_tpu.core.cluster import cluster_step_jit as cluster_step
from raftsql_tpu.core.state import init_peer_state, term_at


def small_cfg(**kw):
    defaults = dict(num_groups=4, num_peers=3, log_window=32,
                    max_entries_per_msg=4, election_ticks=10,
                    heartbeat_ticks=1, seed=42)
    defaults.update(kw)
    return RaftConfig(**defaults)


def run_ticks(cfg, states, inboxes, n, props=None):
    if props is None:
        props = jnp.zeros((n, cfg.num_peers, cfg.num_groups), jnp.int32)
    return cluster_run(cfg, states, inboxes, n, props)


def leaders_per_group(states, cfg):
    """[G] count of peers believing they lead, in the max term per group."""
    role = np.asarray(states.role)          # [P, G]
    term = np.asarray(states.term)
    max_term = term.max(axis=0)             # [G]
    is_leader = (role == LEADER) & (term == max_term[None, :])
    return is_leader.sum(axis=0)


class TestElection:
    def test_single_leader_emerges(self):
        cfg = small_cfg()
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 100)
        counts = leaders_per_group(states, cfg)
        assert (counts == 1).all(), f"leader counts per group: {counts}"

    def test_at_most_one_leader_per_term_always(self):
        # Election safety invariant checked at every tick.
        cfg = small_cfg(num_groups=8, seed=3)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        for _ in range(120):
            props = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)
            states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
            role = np.asarray(states.role)
            term = np.asarray(states.term)
            for g in range(cfg.num_groups):
                terms_led = term[:, g][role[:, g] == LEADER]
                assert len(set(terms_led.tolist())) == len(terms_led), (
                    f"two leaders share a term in group {g}: terms {terms_led}")

    def test_all_groups_agree_on_leader(self):
        cfg = small_cfg()
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 100)
        hint = np.asarray(states.leader_hint)   # [P, G]
        role = np.asarray(states.role)
        for g in range(cfg.num_groups):
            leader = int(np.argmax(role[:, g] == LEADER))
            assert (hint[:, g] == leader).all(), (
                f"group {g}: hints {hint[:, g]} vs leader {leader}")

    def test_five_peer_groups_elect(self):
        cfg = small_cfg(num_peers=5, num_groups=8, seed=7)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 150)
        assert (leaders_per_group(states, cfg) == 1).all()

    def test_single_peer_group_self_elects(self):
        cfg = small_cfg(num_peers=1, num_groups=2)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 40)
        assert (np.asarray(states.role) == LEADER).all()


class TestReplication:
    def elect(self, cfg, ticks=100):
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, ticks)
        assert (leaders_per_group(states, cfg) == 1).all()
        return states, inboxes

    def propose_at_leader(self, cfg, states, n):
        """prop_n [P, G] submitting n proposals at each group's leader."""
        role = np.asarray(states.role)               # [P, G]
        props = (role == LEADER).astype(np.int32) * n
        return jnp.asarray(props)

    def test_proposal_commits_everywhere(self):
        cfg = small_cfg()
        states, inboxes = self.elect(cfg)
        base_commit = np.asarray(states.commit).max(axis=0)
        props = self.propose_at_leader(cfg, states, 2)
        states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 10)
        commit = np.asarray(states.commit)            # [P, G]
        # Every peer of every group commits the new entries.
        assert (commit >= base_commit[None, :] + 2).all(), commit

    def test_logs_match_on_all_peers(self):
        cfg = small_cfg()
        states, inboxes = self.elect(cfg)
        for _ in range(3):
            props = self.propose_at_leader(cfg, states, 1)
            states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
            states, inboxes, _ = run_ticks(cfg, states, inboxes, 5)
        log_len = np.asarray(states.log_len)
        assert (log_len == log_len[0:1, :]).all(), log_len
        # Term sequences agree at every committed position.
        for g in range(cfg.num_groups):
            for idx in range(1, int(np.asarray(states.commit)[:, g].min()) + 1):
                terms = [int(term_at(states.log_term[p], states.log_len[p],
                                     jnp.asarray([idx] * cfg.num_groups),
                                     cfg.log_window)[g])
                         for p in range(cfg.num_peers)]
                assert len(set(terms)) == 1, (g, idx, terms)

    def test_noop_entry_on_election(self):
        # A fresh leader appends a no-op so old-term entries can commit
        # (raft §5.4.2); commit reaches >= 1 with zero client proposals.
        cfg = small_cfg()
        states, inboxes = self.elect(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 10)
        assert (np.asarray(states.commit).max(axis=0) >= 1).all()

    def test_follower_proposals_rejected(self):
        cfg = small_cfg()
        states, inboxes = self.elect(cfg)
        role = np.asarray(states.role)
        props = jnp.asarray((role != LEADER).astype(np.int32) * 3)
        before = np.asarray(states.log_len).copy()
        states, inboxes, info = cluster_step(cfg, states, inboxes, props)
        acc = np.asarray(info.prop_accepted)          # [P, G]
        assert (acc[np.asarray(states.role) != LEADER] == 0).all()


def isolate_peer(inboxes, peer):
    """Drop everything to and from `peer` (dense-inbox partition)."""
    return jax.tree.map(
        lambda x: x.at[peer].set(jnp.zeros((), x.dtype))
                   .at[:, :, peer].set(jnp.zeros((), x.dtype)), inboxes)


class TestLaggedFollower:
    def test_out_of_window_follower_does_not_depose_leader(self):
        """A follower lagging > log_window entries must keep receiving
        (empty prev=0) heartbeats, or its election timer deposes the live
        leader every timeout — sustained availability churn."""
        cfg = small_cfg(num_groups=2, log_window=16, max_entries_per_msg=4,
                        seed=2)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 100)
        assert (leaders_per_group(states, cfg) == 1).all()

        # Partition peer 2; commit W+ entries with the remaining quorum.
        lag = 2
        for _ in range(60):
            role = np.asarray(states.role)
            props = jnp.asarray((role == LEADER).astype(np.int32) * 2)
            states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
            inboxes = isolate_peer(inboxes, lag)
        gap = (np.asarray(states.log_len).max(axis=0)
               - np.asarray(states.log_len)[lag])
        assert (gap > cfg.log_window).all(), gap

        # Heal.  With prevote the rejoining follower's term never
        # inflated, so no deposal happens at all; either way the cluster
        # must settle to one stable leader with no further term churn.
        zero = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)
        for _ in range(80):
            states, inboxes, _ = cluster_step(cfg, states, inboxes, zero)
        settled_term = np.asarray(states.term).max(axis=0).copy()
        assert (leaders_per_group(states, cfg) == 1).all()
        for _ in range(120):
            states, inboxes, _ = cluster_step(cfg, states, inboxes, zero)
        final_term = np.asarray(states.term).max(axis=0)
        assert (final_term == settled_term).all(), (
            f"terms churned after settling: {settled_term} -> {final_term}")
        assert (leaders_per_group(states, cfg) == 1).all()


class TestPrevote:
    def test_partitioned_rejoin_zero_deposal(self):
        """A follower partitioned past many election timeouts must NOT
        depose the live leader on rejoin: prevote (raft §9.6) pins its
        term while its probes cannot reach a quorum, so the rejoin finds
        it at the cluster's own term with nothing to offer."""
        cfg = small_cfg(num_groups=4, seed=9)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 100)
        assert (leaders_per_group(states, cfg) == 1).all()
        role = np.asarray(states.role)
        lag = 2
        fg = np.nonzero(role[lag] != LEADER)[0]   # groups peer 2 follows
        assert fg.size, "seed must leave peer 2 a follower somewhere"
        term_before = np.asarray(states.term).max(axis=0).copy()
        lead_before = (role == LEADER).argmax(axis=0)
        zero = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)
        # ~6 election timeouts of isolation: plenty of probe attempts.
        for _ in range(120):
            states, inboxes, _ = cluster_step(cfg, states, inboxes, zero)
            inboxes = isolate_peer(inboxes, lag)
        # The partitioned peer's term must not have inflated.
        assert (np.asarray(states.term)[lag, fg]
                <= term_before[fg]).all(), np.asarray(states.term)[lag]
        # Heal.  Zero deposal: same leader, same term, immediately stable.
        for _ in range(60):
            states, inboxes, _ = cluster_step(cfg, states, inboxes, zero)
        role2 = np.asarray(states.role)
        term_after = np.asarray(states.term).max(axis=0)
        lead_after = (role2 == LEADER).argmax(axis=0)
        assert (term_after[fg] == term_before[fg]).all(), (
            f"terms inflated across rejoin: {term_before} -> {term_after}")
        assert (lead_after[fg] == lead_before[fg]).all(), (
            f"leader deposed by rejoin: {lead_before} -> {lead_after}")
        assert (leaders_per_group(states, cfg) == 1).all()

    def test_prevote_disabled_matches_legacy(self):
        """prevote=False keeps the original fire→candidate behavior."""
        cfg = small_cfg(prevote=False, seed=4)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        states, inboxes, _ = run_ticks(cfg, states, inboxes, 100)
        assert (leaders_per_group(states, cfg) == 1).all()


class TestRingAliasGuard:
    def test_stale_append_below_ring_window_rejected(self):
        """An append whose prev slid out of the W-entry term ring must be
        REJECTED even when the aliased ring slot happens to hold a
        matching term (e.g. a stale leader replaying after the follower
        installed a snapshot that cleared the ring): accepting it
        conflict-truncates a log it never actually matched.  Found by
        tests/test_stress.py — the crash wiped the payload log and
        regressed the publish cursor."""
        from raftsql_tpu.config import MSG_REQ
        from raftsql_tpu.core.state import (empty_inbox,
                                            install_snapshot_state,
                                            init_peer_state)
        from raftsql_tpu.core.step import peer_step

        cfg = small_cfg(num_groups=1, log_window=16, max_entries_per_msg=4)
        W = cfg.log_window
        st = init_peer_state(cfg, 1)
        # Snapshot-installed state: log == commit == 57, ring cleared
        # except the boundary slot (term 2).  Slot (41-1) % 16 ==
        # slot (57-1) % 16, so term_at(41) aliases the boundary.
        st = install_snapshot_state(st, 0, 57, 2, 2)
        ib = empty_inbox(cfg)
        ib = ib._replace(
            a_type=ib.a_type.at[0, 0].set(MSG_REQ),
            a_term=ib.a_term.at[0, 0].set(2),
            a_prev_idx=ib.a_prev_idx.at[0, 0].set(41),
            a_prev_term=ib.a_prev_term.at[0, 0].set(2),  # == aliased slot
            a_n=ib.a_n.at[0, 0].set(2),
            a_ents=ib.a_ents.at[0, 0, :2].set(3),
            a_commit=ib.a_commit.at[0, 0].set(45))
        st2, out, info = peer_step(cfg, st, ib,
                                   jnp.zeros((1,), jnp.int32),
                                   jnp.asarray(1, jnp.int32))
        assert int(info.app_from[0]) == -1, "stale append was accepted"
        assert int(st2.log_len[0]) == 57, "log truncated by stale append"
        assert int(st2.commit[0]) == 57
        assert not bool(info.app_conflict[0])


class TestCommitSafety:
    def test_commit_monotone(self):
        cfg = small_cfg(seed=11)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        prev_commit = np.zeros((cfg.num_peers, cfg.num_groups), np.int64)
        rng = np.random.default_rng(0)
        for t in range(150):
            props = jnp.asarray(
                rng.integers(0, 2, (cfg.num_peers, cfg.num_groups)),
                dtype=jnp.int32)
            states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
            commit = np.asarray(states.commit)
            assert (commit >= prev_commit).all(), f"commit regressed at {t}"
            prev_commit = commit

    def test_commit_never_exceeds_log(self):
        cfg = small_cfg(seed=13)
        states = init_cluster_state(cfg)
        inboxes = empty_cluster_inbox(cfg)
        rng = np.random.default_rng(1)
        for _ in range(150):
            props = jnp.asarray(
                rng.integers(0, 3, (cfg.num_peers, cfg.num_groups)),
                dtype=jnp.int32)
            states, inboxes, _ = cluster_step(cfg, states, inboxes, props)
            assert (np.asarray(states.commit)
                    <= np.asarray(states.log_len)).all()


class TestRinglessConfig:
    def test_ringless_matches_ringed_trajectory(self):
        """keep_ring=False (the benchmark's point-rule configuration) must
        be a pure representation change: identical consensus evolution,
        with log_term a [G, 1] stub."""
        import functools

        import jax

        from raftsql_tpu.core.cluster import (cluster_step,
                                              empty_cluster_inbox,
                                              init_cluster_state)

        def run(keep_ring):
            cfg = small_cfg(seed=21, keep_ring=keep_ring)
            step = jax.jit(functools.partial(cluster_step, cfg))
            st = init_cluster_state(cfg)
            ib = empty_cluster_inbox(cfg)
            rng = np.random.default_rng(3)
            for _ in range(80):
                props = jnp.asarray(
                    (rng.random((cfg.num_peers, cfg.num_groups)) < 0.5)
                    .astype(np.int32))
                st, ib, _ = step(st, ib, props)
            return st

        a, b = run(True), run(False)
        assert b.log_term.shape[-1] == 1
        for f in ("term", "role", "commit", "log_len", "tbl_pos",
                  "tbl_term", "match", "next_idx", "voted_for"):
            assert (np.asarray(getattr(a, f))
                    == np.asarray(getattr(b, f))).all(), f
        assert (np.asarray(a.commit) > 0).any()


class TestFloorResync:
    """A restarted/installed follower whose table floor is far above the
    leader's serving point must steer the leader UP, not down: the
    floor-reject hints the follower's full log length, and the leader
    treats a hint at-or-beyond its send point as a resync jump.
    Without either half, the pair livelocks on rejects at prev=0
    (found by the flaky tail of test_follower_catchup_below_table_floor
    at floors <= E; this covers the floor > E case the cluster test
    cannot reach)."""

    def test_floor_reject_hints_full_log_len(self):
        from raftsql_tpu.config import FLOOR_HINT_BIAS, MSG_REQ, MSG_RESP
        from raftsql_tpu.core.state import (empty_inbox,
                                            install_snapshot_state,
                                            init_peer_state)
        from raftsql_tpu.core.step import peer_step

        cfg = small_cfg(num_groups=1, log_window=16, max_entries_per_msg=4)
        st = init_peer_state(cfg, 1)
        st = install_snapshot_state(st, 0, 57, 2, 2)   # floor = 57 >> E
        ib = empty_inbox(cfg)
        # Leader's empty heartbeat at prev=0 (its floor-suppressed
        # fallback for an unservable follower).
        ib = ib._replace(
            a_type=ib.a_type.at[0, 0].set(MSG_REQ),
            a_term=ib.a_term.at[0, 0].set(2),
            a_commit=ib.a_commit.at[0, 0].set(57))
        st2, out, info = peer_step(cfg, st, ib,
                                   jnp.zeros((1,), jnp.int32),
                                   jnp.asarray(1, jnp.int32))
        assert int(info.app_from[0]) == -1, "below-floor hb accepted"
        assert int(out.a_type[0, 0]) == MSG_RESP
        assert not bool(out.a_success[0, 0])
        assert int(out.a_match[0, 0]) == 57 + FLOOR_HINT_BIAS, \
            "floor reject must hint the full log length, explicitly marked"

    def test_leader_jumps_next_idx_on_resync_hint(self):
        from raftsql_tpu.config import FLOOR_HINT_BIAS, LEADER, MSG_RESP
        from raftsql_tpu.core.state import empty_inbox, init_peer_state
        from raftsql_tpu.core.step import peer_step

        cfg = small_cfg(num_groups=1, log_window=16, max_entries_per_msg=4)
        st = init_peer_state(cfg, 0)
        st = st._replace(
            term=st.term.at[0].set(2),
            role=st.role.at[0].set(LEADER),
            log_len=st.log_len.at[0].set(60),
            commit=st.commit.at[0].set(60),
            tbl_pos=st.tbl_pos.at[0, -1].set(1),
            tbl_term=st.tbl_term.at[0, -1].set(2),
            match=st.match.at[0].set(jnp.asarray([60, 0, 0], jnp.int32)),
            next_idx=st.next_idx.at[0].set(
                jnp.asarray([61, 1, 61], jnp.int32)))
        ib = empty_inbox(cfg)
        # Follower 1's floor-reject of our prev=0 probe: explicitly
        # marked hint 57 -> resync jump to 58 (not a walk to 1).
        ib = ib._replace(
            a_type=ib.a_type.at[0, 1].set(MSG_RESP),
            a_term=ib.a_term.at[0, 1].set(2),
            a_success=ib.a_success.at[0, 1].set(False),
            a_match=ib.a_match.at[0, 1].set(57 + FLOOR_HINT_BIAS))
        st2, out, info = peer_step(cfg, st, ib,
                                   jnp.zeros((1,), jnp.int32),
                                   jnp.asarray(0, jnp.int32))
        assert int(st2.next_idx[0, 1]) == 58, int(st2.next_idx[0, 1])

    def test_stale_ordinary_reject_never_jumps_up(self):
        """A late in-flight ORDINARY reject whose hint sits at/above the
        (already walked-down) next_idx must not re-raise it: only the
        explicit floor marker may steer next_idx up.  Before the marker,
        hint >= next_idx was inferred as a resync request, so a stale
        conflict hint re-probed ground the leader had ruled out."""
        from raftsql_tpu.config import LEADER, MSG_RESP
        from raftsql_tpu.core.state import empty_inbox, init_peer_state
        from raftsql_tpu.core.step import peer_step

        cfg = small_cfg(num_groups=1, log_window=16, max_entries_per_msg=4)
        st = init_peer_state(cfg, 0)
        st = st._replace(
            term=st.term.at[0].set(2),
            role=st.role.at[0].set(LEADER),
            log_len=st.log_len.at[0].set(60),
            commit=st.commit.at[0].set(60),
            tbl_pos=st.tbl_pos.at[0, -1].set(1),
            tbl_term=st.tbl_term.at[0, -1].set(2),
            match=st.match.at[0].set(jnp.asarray([60, 0, 0], jnp.int32)),
            next_idx=st.next_idx.at[0].set(
                jnp.asarray([61, 2, 61], jnp.int32)))
        ib = empty_inbox(cfg)
        # Unbiased conflict hint 57 >= next_idx 2: walk (to
        # min(next_idx-1, hint+1) = 1), never jump to 58.
        ib = ib._replace(
            a_type=ib.a_type.at[0, 1].set(MSG_RESP),
            a_term=ib.a_term.at[0, 1].set(2),
            a_success=ib.a_success.at[0, 1].set(False),
            a_match=ib.a_match.at[0, 1].set(57))
        st2, out, info = peer_step(cfg, st, ib,
                                   jnp.zeros((1,), jnp.int32),
                                   jnp.asarray(0, jnp.int32))
        assert int(st2.next_idx[0, 1]) == 1, int(st2.next_idx[0, 1])
