"""Unit tests for the quorum / commit-scan / pallas kernels against a
straightforward numpy model of raft's commit rule (Figure 2 leader rule:
advance commit to the largest N replicated on a quorum with term match)."""
import numpy as np
import jax.numpy as jnp
import pytest

from raftsql_tpu.ops.commit_scan import (commit_latency_ticks,
                                         running_commit,
                                         windowed_commit_index)
from raftsql_tpu.ops.pallas_quorum import pallas_quorum_commit_index
from raftsql_tpu.ops.quorum import quorum_commit_index, quorum_match_index


def _random_case(rng, G=64, P=5, W=32):
    log_len = rng.integers(0, W, G).astype(np.int32)
    commit = np.array([rng.integers(0, l + 1) for l in log_len], np.int32)
    term = rng.integers(1, 5, G).astype(np.int32)
    # Ring with plausible terms at resident positions.
    log_term = np.zeros((G, W), np.int32)
    for g in range(G):
        t = 1
        for n in range(1, log_len[g] + 1):
            if rng.random() < 0.2 and t < term[g]:
                t += 1
            log_term[g, (n - 1) % W] = t
    match = np.minimum(rng.integers(0, W, (G, P)), log_len[:, None])
    match = match.astype(np.int32)
    is_leader = rng.random(G) < 0.7
    return match, log_term, log_len, commit, term, is_leader


def _model_commit(match, log_term, log_len, commit, term, is_leader,
                  quorum, point_only):
    """Direct per-group evaluation of the leader commit rule."""
    G, P = match.shape
    W = log_term.shape[1]
    out = commit.copy()
    for g in range(G):
        if not is_leader[g]:
            continue
        qm = int(np.sort(match[g])[P - quorum])
        cands = [qm] if point_only else range(qm, commit[g], -1)
        for n in cands:
            if n <= commit[g] or n < 1 or n > log_len[g]:
                continue
            if log_term[g, (n - 1) % W] == term[g]:
                out[g] = max(out[g], n)
                break
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_quorum_commit_matches_model(seed):
    rng = np.random.default_rng(seed)
    match, log_term, log_len, commit, term, is_leader = _random_case(rng)
    got = np.asarray(quorum_commit_index(
        jnp.asarray(match), jnp.asarray(log_term), jnp.asarray(log_len),
        jnp.asarray(commit), jnp.asarray(term), jnp.asarray(is_leader),
        quorum=3, window=32))
    want = _model_commit(match, log_term, log_len, commit, term, is_leader,
                         3, point_only=True)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_windowed_commit_matches_model(seed):
    rng = np.random.default_rng(seed)
    match, log_term, log_len, commit, term, is_leader = _random_case(rng)
    got = np.asarray(windowed_commit_index(
        jnp.asarray(match), jnp.asarray(log_term), jnp.asarray(log_len),
        jnp.asarray(commit), jnp.asarray(term), jnp.asarray(is_leader),
        quorum=3, window=32))
    want = _model_commit(match, log_term, log_len, commit, term, is_leader,
                         3, point_only=False)
    np.testing.assert_array_equal(got, want)


def test_windowed_never_below_point():
    # The windowed rule commits whenever the point rule does, plus cases
    # where the quorum index sits on an old-term entry.
    rng = np.random.default_rng(7)
    for _ in range(5):
        match, log_term, log_len, commit, term, is_leader = _random_case(rng)
        a = np.asarray(quorum_commit_index(
            jnp.asarray(match), jnp.asarray(log_term), jnp.asarray(log_len),
            jnp.asarray(commit), jnp.asarray(term), jnp.asarray(is_leader),
            quorum=3, window=32))
        b = np.asarray(windowed_commit_index(
            jnp.asarray(match), jnp.asarray(log_term), jnp.asarray(log_len),
            jnp.asarray(commit), jnp.asarray(term), jnp.asarray(is_leader),
            quorum=3, window=32))
        assert (b >= a).all()


@pytest.mark.parametrize("seed", [0, 3])
@pytest.mark.parametrize("P,quorum", [(3, 2), (5, 3)])
def test_pallas_quorum_matches_reference(seed, P, quorum):
    rng = np.random.default_rng(seed)
    match, log_term, log_len, commit, term, is_leader = _random_case(
        rng, G=100, P=P)
    args = (jnp.asarray(match), jnp.asarray(log_term), jnp.asarray(log_len),
            jnp.asarray(commit), jnp.asarray(term), jnp.asarray(is_leader))
    want = np.asarray(quorum_commit_index(*args, quorum=quorum, window=32))
    got = np.asarray(pallas_quorum_commit_index(
        *args, quorum=quorum, window=32, block_g=32, interpret=True))
    np.testing.assert_array_equal(got, want)


def test_quorum_match_index_is_qth_largest():
    m = jnp.asarray([[3, 1, 2], [5, 5, 0]], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(quorum_match_index(m, 2)), [2, 5])


def test_running_commit_and_latency():
    cand = jnp.asarray([[0, 1], [2, 0], [1, 3], [0, 2]], jnp.int32)
    traj = np.asarray(running_commit(cand))
    np.testing.assert_array_equal(traj, [[0, 1], [2, 1], [2, 3], [2, 3]])
    lat = np.asarray(commit_latency_ticks(jnp.asarray(traj),
                                          jnp.asarray([2, 3], jnp.int32)))
    np.testing.assert_array_equal(lat, [1, 2])
    # Never-committed target -> T.
    lat2 = np.asarray(commit_latency_ticks(jnp.asarray(traj),
                                           jnp.asarray([9, 3], jnp.int32)))
    np.testing.assert_array_equal(lat2, [4, 2])


@pytest.mark.parametrize("rule", ["windowed", "pallas"])
def test_cluster_converges_under_alternate_commit_rules(rule):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core import cluster

    cfg = RaftConfig(num_groups=4, num_peers=3, log_window=32,
                     max_entries_per_msg=4, commit_rule=rule)
    st = cluster.init_cluster_state(cfg)
    ib = cluster.empty_cluster_inbox(cfg)
    st, ib, _ = cluster.cluster_run(cfg, st, ib, 60,
                                    jnp.zeros((60, 3, 4), jnp.int32))
    roles = np.asarray(st.role)
    assert ((roles == 2).sum(axis=0) == 1).all(), roles
    st, ib, _ = cluster.cluster_run(cfg, st, ib, 20,
                                    jnp.full((20, 3, 4), 2, jnp.int32))
    assert (np.asarray(st.commit) >= 3).all()


# ---------------------------------------------------------------------------
# Dense (one-hot) gather path — the lowering the TPU deployment actually
# runs (ops/dense.py).  CI is CPU-only, where use_dense() picks the native
# gather, so these tests pin both paths explicitly and (a) check the dense
# primitives against their gather duals eagerly, (b) run a full fused
# cluster and require BIT-IDENTICAL state trajectories under both
# lowerings.
# ---------------------------------------------------------------------------


def test_dense_primitives_match_gather_duals(monkeypatch):
    from raftsql_tpu.ops import dense

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.integers(0, 50, (3, 40, 64)), jnp.int32)
    idx = jnp.asarray(rng.integers(0, 64, (3, 40, 9)), jnp.int32)
    monkeypatch.setenv("RAFTSQL_DENSE", "1")
    got = dense.take_last(x, idx)
    monkeypatch.setenv("RAFTSQL_DENSE", "0")
    want = dense.take_last(x, idx)
    assert (np.asarray(got) == np.asarray(want)).all()

    vals = jnp.asarray(rng.integers(0, 90, (40, 8)), jnp.int32)
    rel = jnp.asarray(rng.integers(0, 64, (40, 64)), jnp.int32)
    n = jnp.asarray(rng.integers(0, 9, (40,)), jnp.int32)
    monkeypatch.setenv("RAFTSQL_DENSE", "1")
    got = dense.ring_gather_values(vals, rel, n)
    monkeypatch.setenv("RAFTSQL_DENSE", "0")
    want = dense.ring_gather_values(vals, rel, n)
    assert (np.asarray(got) == np.asarray(want)).all()

    # pick_peer / pick_batch are dense on every backend; check vs numpy.
    xb = jnp.asarray(rng.integers(0, 99, (40, 3, 5)), jnp.int32)
    src = jnp.asarray(rng.integers(0, 3, (40,)), jnp.int32)
    got = np.asarray(dense.pick_peer(xb, src))
    want = np.asarray(xb)[np.arange(40), np.asarray(src)]
    assert (got == want).all()
    got = np.asarray(dense.pick_batch(vals, n % 8))
    want = np.asarray(vals)[np.arange(40), np.asarray(n % 8)]
    assert (got == want).all()


def test_cluster_trajectory_identical_on_dense_path(monkeypatch):
    """The dense lowering must be a pure implementation detail: the same
    seed and proposal schedule produce bit-identical PeerState on both
    paths.  (Fresh jit wrappers per path — the env var is read at trace
    time, so reusing cluster_step_jit's cache would mask the flip.)"""
    import functools

    import jax

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core import cluster

    cfg = RaftConfig(num_groups=8, num_peers=3, log_window=32,
                     max_entries_per_msg=4, seed=13)

    def run(path):
        monkeypatch.setenv("RAFTSQL_DENSE", path)
        step = jax.jit(functools.partial(cluster.cluster_step, cfg))
        st = cluster.init_cluster_state(cfg)
        ib = cluster.empty_cluster_inbox(cfg)
        rng = np.random.default_rng(5)
        for t in range(60):
            props = jnp.asarray(
                (rng.random((cfg.num_peers, cfg.num_groups)) < 0.4)
                .astype(np.int32))
            st, ib, _ = step(st, ib, props)
        return st

    a, b = run("1"), run("0")
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert (np.asarray(la) == np.asarray(lb)).all()
