"""FusedClusterNode — the durable co-located runtime (runtime/fused.py).

Covers: election + identical commit streams on every peer, the
durable-before-send barrier (every peer's WAL fsync between consecutive
device dispatches), crash-restart WAL replay with the nil-sentinel
protocol (reference raft.go:122-134, 131-132), and KV apply off the
commit stream.
"""
import raftsql_tpu.runtime.fused as fused_mod
from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.kv_sm import KVStateMachine
from raftsql_tpu.runtime.db import _expand_commit_item
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.storage.wal import WAL


def mkcfg(groups=4):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, tick_interval_s=0.0)


def elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


def drain(node, peer):
    out, sentinels = [], 0
    q = node.commit_q(peer)
    while True:
        try:
            item = q.get_nowait()
        except Exception:
            break
        if item is None:
            sentinels += 1
            continue
        out.extend(_expand_commit_item(item))
    return out, sentinels


def test_fused_commits_identically_on_all_peers(tmp_path):
    cfg = mkcfg()
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)                      # discard noops/sentinel
    for g in range(cfg.num_groups):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(10)])
    for _ in range(40):
        node.tick()
    streams = [drain(node, p)[0] for p in range(3)]
    assert len(streams[0]) == 4 * 10
    # Per-group total order is identical across replicas (§2d.1 — each
    # group is its own raft; cross-group interleave is unordered).
    for g in range(cfg.num_groups):
        per = [[(i, q) for (gg, i, q) in s if gg == g] for s in streams]
        assert per[0] == per[1] == per[2]
        assert len(per[0]) == 10
    node.stop()


def test_fused_durable_barrier_every_dispatch(tmp_path, monkeypatch):
    """Between any two consecutive device dispatches, every peer's WAL
    was fsynced — the fused analog of save-before-send
    (reference raft.go:227-235; the dispatch IS the send)."""
    events = []
    real_step = fused_mod.cluster_step_host
    real_sync = WAL.sync

    def spy_step(*a, **k):
        events.append("dispatch")
        return real_step(*a, **k)

    def spy_sync(self):
        events.append("sync")
        return real_sync(self)

    monkeypatch.setattr(fused_mod, "cluster_step_host", spy_step)
    monkeypatch.setattr(WAL, "sync", spy_sync)

    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    node.propose_many(0, [b"SET a 1", b"SET b 2"])
    for _ in range(10):
        node.tick()
    node.stop()
    # Every inter-dispatch gap carries one sync per peer.
    gaps = " ".join(events).split("dispatch")
    for gap in gaps[1:-1]:                  # complete gaps only
        assert gap.count("sync") >= cfg.num_peers, events[:30]


def test_fused_restart_replays_wal(tmp_path):
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(6)])
    for _ in range(30):
        node.tick()
    live, sent = drain(node, 0)
    assert sent == 1                        # fresh boot: one nil sentinel
    assert len(live) == 12
    node.stop()

    def per_group(items):
        return {g: [(i, q) for (gg, i, q) in items if gg == g]
                for g in range(2)}

    node2 = FusedClusterNode(cfg, str(tmp_path))
    for p in range(3):
        rep, sent = drain(node2, p)
        # Replayed committed prefix arrives BEFORE the sentinel and
        # matches what was committed pre-crash (raftsql_test.go:138-146
        # counts replay via this protocol).
        assert sent == 1
        assert per_group(rep) == per_group(live)
    elect(node2)
    node2.propose_many(0, [b"SET post 1"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert [q for (_, _, q) in post] == ["SET post 1"]
    node2.stop()


def test_fused_kv_apply_converges(tmp_path):
    cfg = mkcfg(groups=3)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)
    sms = {p: [KVStateMachine() for _ in range(cfg.num_groups)]
           for p in range(3)}
    for g in range(3):
        node.propose_many(g, [f"SET x{i} v{g}.{i}".encode()
                              for i in range(5)])
    for _ in range(30):
        node.tick()
    for p in range(3):
        items, _ = drain(node, p)
        for (g, idx, cmd) in items:
            assert sms[p][g].apply(cmd, idx) is None
    for g in range(3):
        assert sms[0][g]._data == sms[1][g]._data == sms[2][g]._data
        assert sms[0][g]._data["x4"] == f"v{g}.4"
    node.stop()


def test_fused_compaction_bounds_log_under_load(tmp_path):
    """Sustained load + periodic compact(): floors advance, the payload
    log's retained span stays bounded, and commits keep flowing
    (VERDICT r4 task 8 — the soak's invariant at test scale)."""
    cfg = RaftConfig(num_groups=4, num_peers=3, log_window=32,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)
    committed = 0
    for round_no in range(12):
        for g in range(4):
            node.propose_many(g, [b"SET k v"] * 16)
        for _ in range(4):
            node.tick()
        committed += len(drain(node, 0)[0])
        node.compact(keep=32)
    assert committed >= 4 * 12 * 10        # load flowed throughout
    for g in range(4):
        floor = node.plogs[0].start(g)
        span = node.plogs[0].length(g) - floor
        assert floor > 0, f"g{g} floor never advanced"
        # keep(=W) + in-flight slack bounds the retained span.
        assert span <= 32 + 4 * 8 + 16, (g, span)
    # Restart: replay from the compacted WAL (floors + suffix) works.
    # Read the cursor AFTER stop(): it flushes the deferred publish of
    # the final tick, advancing applied one last time.
    node.stop()
    applied_before = int(node._applied[0][0])
    node2 = FusedClusterNode(cfg, str(tmp_path))
    rep, _ = drain(node2, 0)
    assert int(node2._applied[0][0]) == applied_before
    assert rep, "nothing replayed above the compaction floor"
    elect(node2)
    node2.propose_many(0, [b"SET post compaction"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert any(q == "SET post compaction" for (_, _, q) in post)
    node2.stop()


def test_fused_pipe_raftdb_sql_stack(tmp_path, monkeypatch):
    """The --fused deployment's stack: FusedClusterNode -> FusedPipe ->
    RaftDB(SQLite) serves writes with blocking acks, local reads, and
    linearizable reads, in one process (server/main.py build_fused_node
    wiring, driven in-process here)."""
    monkeypatch.chdir(tmp_path)
    from raftsql_tpu.server.main import build_fused_node

    rdb = build_fused_node(groups=2, peers=3, tick=0.002)
    try:
        assert rdb.propose("CREATE TABLE t (v text)", 0).wait(30) is None
        assert rdb.propose("INSERT INTO t (v) VALUES ('x')",
                           0).wait(30) is None
        # Group isolation: group 1 has its own database.
        err = rdb.propose("INSERT INTO t (v) VALUES ('y')", 1).wait(30)
        assert err is not None          # no such table in group 1
        assert rdb.query("SELECT v FROM t", 0) == "|x|\n"
        # Linearizable read: single-controller cluster, leader commit
        # is the linearization point (runtime/fused.py read_index).
        assert rdb.query("SELECT count(*) FROM t", 0,
                         linear=True, timeout=30) == "|1|\n"
    finally:
        rdb.close()


def test_fused_native_payload_plane(tmp_path, monkeypatch):
    """RAFTSQL_FUSED_NATIVE_PLOG=1: the C payload store + combined
    walplog calls produce the same commit streams and survive restart
    replay (the opt-in native plane must stay correct even while the
    Python store is the measured default)."""
    monkeypatch.setenv("RAFTSQL_FUSED_NATIVE_PLOG", "1")
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    if not hasattr(node.plogs[0], "handle"):
        import pytest
        pytest.skip("native library unavailable")
    elect(node)
    drain(node, 0)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(6)])
    for _ in range(30):
        node.tick()
    live, _ = drain(node, 0)
    assert len(live) == 12
    node.stop()
    node2 = FusedClusterNode(cfg, str(tmp_path))
    rep, sent = drain(node2, 0)
    assert sent == 1 and len(rep) == 12
    node2.stop()


def test_fused_crash_with_torn_tail_recovers(tmp_path):
    """Hard-crash recovery: no graceful stop (buffered frames lost), a
    torn half-record appended to one peer's active segment — replay
    repairs the tail and the cluster serves again with the durable
    prefix intact on every peer (storage-level repair wired end to
    end)."""
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(5)])
    for _ in range(30):
        node.tick()
    live, _ = drain(node, 0)
    assert len(live) == 10
    # Crash: skip stop() entirely (pending publish + close are lost);
    # then tear peer 1's active segment with a half-written frame.
    segs = sorted((tmp_path / "p1").glob("wal-*.log"))
    with open(segs[-1], "ab") as f:
        f.write(b"\x12\x34\x56")                  # torn frame header
    del node

    node2 = FusedClusterNode(cfg, str(tmp_path))
    for p in range(3):
        rep, sent = drain(node2, p)
        assert sent == 1
        # Every fsynced commit survives; the torn bytes do not.
        per_g = {g: [q for (gg, _, q) in rep if gg == g]
                 for g in range(2)}
        for g in range(2):
            assert per_g[g] == [f"SET k{i} g{g}" for i in range(5)]
    elect(node2)
    node2.propose_many(0, [b"SET post crash"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert any(q == "SET post crash" for (_, _, q) in post)
    node2.stop()
