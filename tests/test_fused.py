"""FusedClusterNode — the durable co-located runtime (runtime/fused.py).

Covers: election + identical commit streams on every peer, the
durable-before-send barrier (every peer's WAL fsync between consecutive
device dispatches), crash-restart WAL replay with the nil-sentinel
protocol (reference raft.go:122-134, 131-132), and KV apply off the
commit stream.
"""
import os

import numpy as np

import raftsql_tpu.runtime.fused as fused_mod
from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.kv_sm import KVStateMachine
from raftsql_tpu.runtime.db import _expand_commit_item
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.storage.wal import WAL


def mkcfg(groups=4):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, tick_interval_s=0.0)


def elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


def drain(node, peer):
    out, sentinels = [], 0
    q = node.commit_q(peer)
    while True:
        try:
            item = q.get_nowait()
        except Exception:
            break
        if item is None:
            sentinels += 1
            continue
        out.extend(_expand_commit_item(item))
    return out, sentinels


def test_fused_commits_identically_on_all_peers(tmp_path):
    cfg = mkcfg()
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)                      # discard noops/sentinel
    for g in range(cfg.num_groups):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(10)])
    for _ in range(40):
        node.tick()
    streams = [drain(node, p)[0] for p in range(3)]
    assert len(streams[0]) == 4 * 10
    # Per-group total order is identical across replicas (§2d.1 — each
    # group is its own raft; cross-group interleave is unordered).
    for g in range(cfg.num_groups):
        per = [[(i, q) for (gg, i, q) in s if gg == g] for s in streams]
        assert per[0] == per[1] == per[2]
        assert len(per[0]) == 10
    node.stop()


def test_fused_durable_barrier_every_dispatch(tmp_path, monkeypatch):
    """SERIALIZED pipeline (overlap off): between any two consecutive
    device dispatches, every peer's WAL was fsynced — the fused analog
    of save-before-send (reference raft.go:227-235; the dispatch IS
    the send).  The double-buffered default relaxes dispatch timing
    but not durability ordering — pinned separately below."""
    monkeypatch.setenv("RAFTSQL_OVERLAP_DISPATCH", "0")
    events = []
    real_step = fused_mod.cluster_step_host
    real_sync = WAL.sync

    def spy_step(*a, **k):
        events.append("dispatch")
        return real_step(*a, **k)

    def spy_sync(self):
        events.append("sync")
        return real_sync(self)

    monkeypatch.setattr(fused_mod, "cluster_step_host", spy_step)
    monkeypatch.setattr(WAL, "sync", spy_sync)

    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    node.propose_many(0, [b"SET a 1", b"SET b 2"])
    for _ in range(10):
        node.tick()
    node.stop()
    # Every inter-dispatch gap carries one sync per peer.
    gaps = " ".join(events).split("dispatch")
    for gap in gaps[1:-1]:                  # complete gaps only
        assert gap.count("sync") >= cfg.num_peers, events[:30]


def test_fused_overlap_barrier_ordering(tmp_path, monkeypatch):
    """DOUBLE-BUFFERED pipeline (the default): tick t's durable phase
    may run inside dispatch t+1's device window, but (a) barriers never
    interleave — a dispatch gap carries a WHOLE tick's syncs or none —
    and (b) no tick's commits are handed to the publish plane before
    that tick's own barrier completed (save-before-externalize)."""
    monkeypatch.setenv("RAFTSQL_OVERLAP_DISPATCH", "1")
    events = []
    real_step = fused_mod.cluster_step_host
    real_sync = WAL.sync
    real_finish = FusedClusterNode._finish_durable

    def spy_step(*a, **k):
        events.append("dispatch")
        return real_step(*a, **k)

    def spy_sync(self):
        events.append("sync")
        return real_sync(self)

    def spy_finish(self, step_infos, staged):
        got = real_finish(self, step_infos, staged)
        events.append("barrier")
        return got

    monkeypatch.setattr(fused_mod, "cluster_step_host", spy_step)
    monkeypatch.setattr(WAL, "sync", spy_sync)
    monkeypatch.setattr(FusedClusterNode, "_finish_durable", spy_finish)

    publishes = []
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    real_enq = node._enqueue_publish
    real_pub = node._publish

    def spy_enq(pinfo):
        publishes.append(len([e for e in events if e == "barrier"]))
        events.append("publish")
        return real_enq(pinfo)

    def spy_pub(pinfo):
        publishes.append(len([e for e in events if e == "barrier"]))
        events.append("publish")
        return real_pub(pinfo)

    node._enqueue_publish = spy_enq
    node._publish = spy_pub
    elect(node)
    node.propose_many(0, [b"SET a 1", b"SET b 2"])
    for _ in range(10):
        node.tick()
    assert node.metrics.overlap_ticks > 0       # the pipeline engaged
    node.stop()
    # (b) the k-th publish only after the k-th completed barrier.
    for k, barriers_before in enumerate(publishes):
        assert barriers_before >= k + 1, (k, publishes)
    # (a) barriers never straddle a dispatch: the syncs between two
    # consecutive barriers live in one dispatch gap.
    gaps = " ".join(events).split("dispatch")
    P = cfg.num_peers
    for gap in gaps[1:-1]:
        assert gap.count("sync") % P == 0 or "barrier" in gap, \
            events[:40]


def test_fused_restart_replays_wal(tmp_path):
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(6)])
    for _ in range(30):
        node.tick()
    live, sent = drain(node, 0)
    assert sent == 1                        # fresh boot: one nil sentinel
    assert len(live) == 12
    node.stop()

    def per_group(items):
        return {g: [(i, q) for (gg, i, q) in items if gg == g]
                for g in range(2)}

    node2 = FusedClusterNode(cfg, str(tmp_path))
    for p in range(3):
        rep, sent = drain(node2, p)
        # Replayed committed prefix arrives BEFORE the sentinel and
        # matches what was committed pre-crash (raftsql_test.go:138-146
        # counts replay via this protocol).
        assert sent == 1
        assert per_group(rep) == per_group(live)
    elect(node2)
    node2.propose_many(0, [b"SET post 1"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert [q for (_, _, q) in post] == ["SET post 1"]
    node2.stop()


def test_fused_kv_apply_converges(tmp_path):
    cfg = mkcfg(groups=3)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)
    sms = {p: [KVStateMachine() for _ in range(cfg.num_groups)]
           for p in range(3)}
    for g in range(3):
        node.propose_many(g, [f"SET x{i} v{g}.{i}".encode()
                              for i in range(5)])
    for _ in range(30):
        node.tick()
    for p in range(3):
        items, _ = drain(node, p)
        for (g, idx, cmd) in items:
            assert sms[p][g].apply(cmd, idx) is None
    for g in range(3):
        assert sms[0][g]._data == sms[1][g]._data == sms[2][g]._data
        assert sms[0][g]._data["x4"] == f"v{g}.4"
    node.stop()


def test_fused_compaction_bounds_log_under_load(tmp_path):
    """Sustained load + periodic compact(): floors advance, the payload
    log's retained span stays bounded, and commits keep flowing
    (VERDICT r4 task 8 — the soak's invariant at test scale)."""
    cfg = RaftConfig(num_groups=4, num_peers=3, log_window=32,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for p in range(3):
        drain(node, p)
    committed = 0
    for round_no in range(12):
        for g in range(4):
            node.propose_many(g, [b"SET k v"] * 16)
        for _ in range(4):
            node.tick()
        committed += len(drain(node, 0)[0])
        node.compact(keep=32)
    assert committed >= 4 * 12 * 10        # load flowed throughout
    for g in range(4):
        floor = node.plogs[0].start(g)
        span = node.plogs[0].length(g) - floor
        assert floor > 0, f"g{g} floor never advanced"
        # keep(=W) + in-flight slack bounds the retained span.
        assert span <= 32 + 4 * 8 + 16, (g, span)
    # Restart: replay from the compacted WAL (floors + suffix) works.
    # Read the cursor AFTER stop(): it flushes the deferred publish of
    # the final tick, advancing applied one last time.
    node.stop()
    applied_before = int(node._applied[0][0])
    node2 = FusedClusterNode(cfg, str(tmp_path))
    rep, _ = drain(node2, 0)
    assert int(node2._applied[0][0]) == applied_before
    assert rep, "nothing replayed above the compaction floor"
    elect(node2)
    node2.propose_many(0, [b"SET post compaction"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert any(q == "SET post compaction" for (_, _, q) in post)
    node2.stop()


def test_fused_pipe_raftdb_sql_stack(tmp_path, monkeypatch):
    """The --fused deployment's stack: FusedClusterNode -> FusedPipe ->
    RaftDB(SQLite) serves writes with blocking acks, local reads, and
    linearizable reads, in one process (server/main.py build_fused_node
    wiring, driven in-process here)."""
    monkeypatch.chdir(tmp_path)
    from raftsql_tpu.server.main import build_fused_node

    rdb = build_fused_node(groups=2, peers=3, tick=0.002)
    try:
        assert rdb.propose("CREATE TABLE t (v text)", 0).wait(30) is None
        assert rdb.propose("INSERT INTO t (v) VALUES ('x')",
                           0).wait(30) is None
        # Group isolation: group 1 has its own database.
        err = rdb.propose("INSERT INTO t (v) VALUES ('y')", 1).wait(30)
        assert err is not None          # no such table in group 1
        assert rdb.query("SELECT v FROM t", 0) == "|x|\n"
        # Linearizable read: single-controller cluster, leader commit
        # is the linearization point (runtime/fused.py read_index).
        assert rdb.query("SELECT count(*) FROM t", 0,
                         linear=True, timeout=30) == "|1|\n"
    finally:
        rdb.close()


def test_fused_native_payload_plane(tmp_path, monkeypatch):
    """RAFTSQL_FUSED_NATIVE_PLOG=1: the C payload store + combined
    walplog calls produce the same commit streams and survive restart
    replay (the opt-in native plane must stay correct even while the
    Python store is the measured default)."""
    monkeypatch.setenv("RAFTSQL_FUSED_NATIVE_PLOG", "1")
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    if not hasattr(node.plogs[0], "handle"):
        import pytest
        pytest.skip("native library unavailable")
    elect(node)
    drain(node, 0)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(6)])
    for _ in range(30):
        node.tick()
    live, _ = drain(node, 0)
    assert len(live) == 12
    node.stop()
    node2 = FusedClusterNode(cfg, str(tmp_path))
    rep, sent = drain(node2, 0)
    assert sent == 1 and len(rep) == 12
    node2.stop()


def test_multistep_dispatch_equals_single_step_ticks(tmp_path):
    """RAFTSQL_FUSED_STEPS=S must be EXACTLY S single-step ticks: same
    consensus math (same seed), same durable bytes, same published
    commits — only the dispatch/barrier granularity changes.  Drives
    two clusters through the identical step sequence (proposals enter
    at dispatch boundaries in both) and compares hard states, payload
    logs, applied KV state, and a restart replay of the multi-step
    node's WALs."""
    S = 4
    cfg = mkcfg()
    a = FusedClusterNode(cfg, str(tmp_path / "single"), seed=11)
    b = FusedClusterNode(cfg, str(tmp_path / "multi"), seed=11)
    b._steps = S
    try:
        # Same total warmup steps for both (b ticks S steps at a time).
        warm = 40 * cfg.election_ticks
        for _ in range(warm):
            a.tick()
        for _ in range(warm // S):
            b.tick()
        assert (a._hints >= 0).all() and (b._hints >= 0).all()
        assert (a._hints == b._hints).all()

        for r in range(6):
            for g in range(cfg.num_groups):
                cmds = [f"SET k{r}_{i} g{g}".encode() for i in range(3)]
                a.propose_many(g, cmds)
                b.propose_many(g, cmds)
            for _ in range(S):
                a.tick()
            b.tick()
        for _ in range(2 * S):
            a.tick()
        for _ in range(2):
            b.tick()

        # Identical device-visible state...
        assert (a._hard == b._hard).all()
        # ...identical durable payload bytes on every peer...
        for p in range(cfg.num_peers):
            for g in range(cfg.num_groups):
                assert a.plogs[p].length(g) == b.plogs[p].length(g)
                n = a.plogs[p].length(g)
                ta_, da_ = a.plogs[p].slice_columns(g, 1, n)
                tb_, db_ = b.plogs[p].slice_columns(g, 1, n)
                assert list(ta_) == list(tb_) and list(da_) == list(db_)
        # ...identical published commit streams (as applied KV state).
        def applied_state(node):
            sms = [KVStateMachine() for _ in range(cfg.num_groups)]
            items, _ = drain(node, 0)
            for (g, idx, cmd) in items:
                assert sms[g].apply(cmd, idx) is None
            return [sm.snapshot() for sm in sms]
        assert applied_state(a) == applied_state(b)
    finally:
        a.stop()
        b.stop()

    # The multi-step node's WALs replay to the same state.
    c = FusedClusterNode(cfg, str(tmp_path / "multi"), seed=11)
    try:
        assert (c._hard == b._hard).all()
    finally:
        c.stop()


def test_multistep_uncommitted_dispatch_dropped_on_restart(tmp_path):
    """Crash mid-barrier atomicity: a multi-step dispatch fsynced on
    SOME peers but never epoch-committed must vanish everywhere on
    restart — otherwise one peer could durably remember observing a
    message (vote grant, append) its sender never persisted, the
    classic two-leaders-in-one-term replay hazard."""
    S = 4
    cfg = mkcfg()
    d = str(tmp_path / "n")
    node = FusedClusterNode(cfg, d, seed=5)
    node._steps = S
    try:
        elect(node)
        for g in range(cfg.num_groups):
            node.propose_many(g, [b"SET a 1", b"SET b 2"])
        for _ in range(4):
            node.tick()
        node.publish_flush()
        lens = [[node.plogs[p].length(g) for g in range(cfg.num_groups)]
                for p in range(cfg.num_peers)]
        hard = node._hard.copy()
        committed_epoch = node._epoch_no
        assert committed_epoch > 0       # multi-step framing was live
    finally:
        node.stop()

    # Simulate the crash: peer 0's WAL gains a complete dispatch frame
    # (BEGIN + entries + hard state + END) and even fsyncs it, but the
    # cluster epoch-commit never happened; peer 1 tore mid-frame
    # (BEGIN only).  Peer 2 wrote nothing.
    w0 = WAL(os.path.join(d, "p1"))
    w0.epoch_mark(committed_epoch + 1, end=False)
    w0.append_ranges([0], [lens[0][0] + 1], [1], [99], [b"SET z 9"])
    w0.set_hardstates(np.array([0]), np.array([99]), np.array([-1]),
                      np.array([lens[0][0] + 1]))
    w0.epoch_mark(committed_epoch + 1, end=True)
    w0.sync()
    w0.close()
    w1 = WAL(os.path.join(d, "p2"))
    w1.epoch_mark(committed_epoch + 1, end=False)
    w1.sync()
    w1.close()

    node2 = FusedClusterNode(cfg, d, seed=5)
    try:
        # The whole uncommitted dispatch is gone on every peer: same
        # payload lengths, same hard states as before the "crash".
        for p in range(cfg.num_peers):
            for g in range(cfg.num_groups):
                assert node2.plogs[p].length(g) == lens[p][g], (p, g)
        assert (node2._hard == hard).all()
        assert node2._epoch_no == committed_epoch
    finally:
        node2.stop()


def test_first_multistep_dispatch_uncommitted_dropped(tmp_path):
    """ADVICE r5 high: a crash mid-barrier during the FIRST-ever
    multi-step dispatch of a data_dir leaves epoch-1 BEGIN-framed
    records durable on some peers with NO EPOCHS file (it is created
    lazily at commit).  Restart must still run epoch repair (committed
    epoch 0) and drop the frame everywhere — before the fix the
    repair was gated on EPOCHS existing, and a durable vote grant
    whose sender's state was lost would survive replay."""
    cfg = mkcfg()
    d = str(tmp_path / "n")
    # Peer 1 fsynced its whole epoch-1 frame (a vote at term 5 and an
    # entry); peer 2 tore mid-frame (BEGIN only); peer 3 wrote nothing.
    # No EPOCHS file exists — the commit fsync never happened.
    w0 = WAL(os.path.join(d, "p1"))
    w0.epoch_mark(1, end=False)
    w0.append_ranges([0], [1], [1], [5], [b"SET z 9"])
    w0.set_hardstates(np.array([0]), np.array([5]), np.array([1]),
                      np.array([0]))
    w0.epoch_mark(1, end=True)
    w0.sync()
    w0.close()
    w1 = WAL(os.path.join(d, "p2"))
    w1.epoch_mark(1, end=False)
    w1.sync()
    w1.close()

    node = FusedClusterNode(cfg, d, seed=5)
    try:
        # The whole uncommitted dispatch is gone on every peer: no
        # remembered vote/term, no appended entry.
        assert node._hard[0, 0, 0] == 0, "term from dropped frame"
        assert node._hard[0, 0, 1] == -1, "vote from dropped frame"
        assert node.plogs[0].length(0) == 0
        # The cluster still elects and serves afterwards.
        elect(node)
        node.propose_many(0, [b"SET post repair"])
        for _ in range(25):
            node.tick()
        post, _ = drain(node, 0)
        assert any(q == "SET post repair" for (_, _, q) in post)
    finally:
        node.stop()


def test_epoch_file_creation_fsyncs_directory(tmp_path):
    """ADVICE r5 medium: the first _commit_epoch creates EPOCHS and
    fsyncs its record, but the directory ENTRY must also be fsynced
    before the epoch counts as committed — otherwise a crash can drop
    the whole file while the peers' WAL bytes survive, and recovery
    misclassifies committed (published/acked) dispatches as
    uncommitted.  Crash simulation via the fsio event log: the
    data_dir fsync must directly follow the EPOCHS record fsync."""
    from raftsql_tpu.storage import fsio

    cfg = mkcfg(groups=2)
    d = str(tmp_path / "n")
    inj = fsio.StorageFaultInjector()
    with fsio.installed(inj):
        node = FusedClusterNode(cfg, d, seed=2)
        node._steps = 2
        try:
            elect(node)
            node.propose_many(0, [b"SET a 1"])
            for _ in range(6):
                node.tick()
            assert node._epoch_no > 0    # epoch framing was live
        finally:
            node.stop()
    epath = os.path.join(d, "EPOCHS")
    ev = inj.events
    first = next(i for i, (kind, p) in enumerate(ev)
                 if kind == "fsync" and p == epath)
    assert ev[first + 1] == ("fsync_dir", d), (
        "EPOCHS dirent not made durable before the epoch was treated "
        f"as committed: {ev[first:first + 3]}")


def test_epoch_commit_file_rotates_and_recovers(tmp_path):
    """The epoch-commit file keeps only what recovery needs: rotation
    rewrites it to the newest record once it crosses the threshold, and
    a restart reads the committed epoch back across rotations."""
    from raftsql_tpu.runtime.fused import _read_committed_epoch

    cfg = mkcfg(groups=2)
    d = str(tmp_path / "n")
    n = FusedClusterNode(cfg, d)
    n._EPOCH_ROTATE_BYTES = 60          # rotate every 5 records
    try:
        for i in range(23):
            n._commit_epoch(i + 1)
        n._epoch_no = 23
    finally:
        n.stop()
    path = os.path.join(d, "EPOCHS")
    assert os.path.getsize(path) <= 60  # bounded by rotation
    assert _read_committed_epoch(path) == 23
    n2 = FusedClusterNode(cfg, d)
    try:
        assert n2._epoch_no == 23
    finally:
        n2.stop()


def test_fused_crash_with_torn_tail_recovers(tmp_path):
    """Hard-crash recovery: no graceful stop (buffered frames lost), a
    torn half-record appended to one peer's active segment — replay
    repairs the tail and the cluster serves again with the durable
    prefix intact on every peer (storage-level repair wired end to
    end)."""
    cfg = mkcfg(groups=2)
    node = FusedClusterNode(cfg, str(tmp_path))
    elect(node)
    for g in range(2):
        node.propose_many(g, [f"SET k{i} g{g}".encode()
                              for i in range(5)])
    for _ in range(30):
        node.tick()
    live, _ = drain(node, 0)
    assert len(live) == 10
    # Crash: skip stop() entirely (pending publish + close are lost);
    # then tear peer 1's active segment with a half-written frame.
    segs = sorted((tmp_path / "p1").glob("wal-*.log"))
    with open(segs[-1], "ab") as f:
        f.write(b"\x12\x34\x56")                  # torn frame header
    del node

    node2 = FusedClusterNode(cfg, str(tmp_path))
    for p in range(3):
        rep, sent = drain(node2, p)
        assert sent == 1
        # Every fsynced commit survives; the torn bytes do not.
        per_g = {g: [q for (gg, _, q) in rep if gg == g]
                 for g in range(2)}
        for g in range(2):
            assert per_g[g] == [f"SET k{i} g{g}" for i in range(5)]
    elect(node2)
    node2.propose_many(0, [b"SET post crash"])
    for _ in range(25):
        node2.tick()
    post, _ = drain(node2, 0)
    assert any(q == "SET post crash" for (_, _, q) in post)
    node2.stop()
