"""The read-replica tier (raftsql_tpu/replica/) — the shm delta
stream promoted to a replicated wire protocol.

Covers, without ever booting the raft engine:
  - the frame codec: round trips for every frame kind, CRC corruption
    and impossible lengths surface as the typed StreamCorruptError
    (never an out-of-bounds slice), EOF as StreamClosed;
  - publisher tee -> stream server -> subscriber folding end to end
    over loopback TCP, against a real ShmSnapshotPublisher;
  - resume: a reconnecting subscriber presents its {group: applied}
    vector and the server replays only the tail;
  - log overflow -> stream RESYNC (ISSUE 19 satellite): once the mmap
    log is full the local shm plane dies, but the stream re-images
    subscribers with fresh KIND_BASE serializations — and the replica
    never serves a row count that goes backwards in between;
  - the ReplicaDB fail-closed ladder: every unprovable mode refuses
    with a 421-class ReplicaRefusal toward the write tier.
"""
import socket
import threading
import time

import pytest

from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.replica import stream as wire
from raftsql_tpu.replica.node import (GATE_WAIT_S, ReplicaDB,
                                      ReplicaRefusal, ReplicaSubscriber)
from raftsql_tpu.replica.publisher import ReplicaStreamServer
from raftsql_tpu.runtime.db import NotLeaderError
from raftsql_tpu.runtime.shm import KIND_DELTA, ShmSnapshotPublisher

TIMEOUT = 30.0
SCHEMA = "CREATE TABLE t (k INTEGER PRIMARY KEY, v TEXT)"


# -- codec ------------------------------------------------------------------


def _pipe():
    a, b = socket.socketpair()
    a.settimeout(TIMEOUT)
    b.settimeout(TIMEOUT)
    return a, b


def test_codec_round_trips():
    a, b = _pipe()
    try:
        a.sendall(wire.encode_hello(7, 3, 2))
        kind, body = wire.read_frame(b)
        assert kind == wire.K_HELLO
        assert wire.decode_hello(body) == {"epoch": 7, "keymap_epoch": 3,
                                           "groups": 2}

        a.sendall(wire.encode_subscribe("h:1", {0: 5, 1: 0}))
        kind, body = wire.read_frame(b)
        assert kind == wire.K_SUB
        assert wire.decode_subscribe(body) == ("h:1", {0: 5, 1: 0})

        a.sendall(wire.encode_ack({1: 9}))
        kind, body = wire.read_frame(b)
        assert wire.decode_ack(body) == {1: 9}

        a.sendall(wire.encode_rec(KIND_DELTA, 1, 42, b"INSERT ..."))
        kind, body = wire.read_frame(b)
        assert kind == wire.K_REC
        assert wire.decode_rec(body) == (KIND_DELTA, 1, 42, b"INSERT ...")

        rows = [(5, 6, 1, 250_000, 2), (0, 0, 0, 0, 0)]
        a.sendall(wire.encode_table(7, 3, True, rows))
        kind, body = wire.read_frame(b)
        assert kind == wire.K_TABLE
        assert wire.decode_table(body) == (7, 3, True,
                                           [tuple(r) for r in rows])
    finally:
        a.close()
        b.close()


def test_codec_corruption_is_typed_never_a_wrong_row():
    # CRC mismatch: flip one payload byte.
    frame = bytearray(wire.encode_rec(KIND_DELTA, 0, 1, b"INSERT 1"))
    frame[-1] ^= 0x40
    a, b = _pipe()
    try:
        a.sendall(bytes(frame))
        with pytest.raises(wire.StreamCorruptError):
            wire.read_frame(b)
    finally:
        a.close()
        b.close()
    # Impossible declared length: bounds-checked before any slice.
    a, b = _pipe()
    try:
        a.sendall(wire._FRAME.pack(wire.MAX_FRAME + 1, 0))
        with pytest.raises(wire.StreamCorruptError):
            wire.read_frame(b)
    finally:
        a.close()
        b.close()
    # EOF mid-frame is a connection fault, not corruption.
    a, b = _pipe()
    try:
        a.sendall(wire.encode_hello(1, 0, 1)[:5])
        a.close()
        with pytest.raises(wire.StreamClosed):
            wire.read_frame(b)
    finally:
        b.close()


def test_short_rec_and_table_bodies_fail_closed():
    with pytest.raises(wire.StreamCorruptError):
        wire.decode_rec(b"\x01\x02")
    with pytest.raises(wire.StreamCorruptError):
        wire.decode_table(b"\x00" * 4)


# -- stream end to end ------------------------------------------------------


class _Upstream:
    """A stand-in engine: per-group authoritative state machines whose
    applies mirror into a real ShmSnapshotPublisher, exactly as
    runtime/db.py's apply thread does."""

    def __init__(self, tmp, groups=1, size=None):
        self.sms = [SQLiteStateMachine(":memory:", resume=True)
                    for _ in range(groups)]
        self.pub = ShmSnapshotPublisher(str(tmp), num_groups=groups,
                                        size=size)
        self.pub.start(self._serialize, self._applied)
        self.commit = [0] * groups

    def _serialize(self, g):
        idx, blob = self.sms[g].serialize_with_index()
        return (idx, blob) if idx > 0 else None

    def _applied(self, g):
        return self.sms[g].applied_index()

    def apply(self, g, sql, index):
        self.sms[g].apply(sql, index)
        self.pub.publish_deltas({g: [(sql, index)]})
        self.commit[g] = index

    def refresh(self, lease_s=0.0):
        self.pub.refresh(lambda g: self.commit[g], lambda g: 1,
                         lambda g: lease_s)

    def close(self):
        self.pub.close()
        for sm in self.sms:
            sm.close()


def _wait(pred, timeout=TIMEOUT):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


def _applied_of(sub, group=0):
    with sub._cond:
        return sub.applied_locked(group)


def test_stream_folds_and_serves_the_ladder(tmp_path):
    up = _Upstream(tmp_path)
    srv = ReplicaStreamServer(up.pub, 0, host="127.0.0.1")
    srv.start()
    sub = ReplicaSubscriber(("127.0.0.1", srv.port), advertise="h:9")
    rdb = ReplicaDB(sub)
    try:
        up.apply(0, SCHEMA, 1)
        for k in range(5):
            up.apply(0, f"INSERT INTO t VALUES ({k}, 'v{k}')", k + 2)
        sub.start()
        assert _wait(lambda: _applied_of(sub) >= 6)

        assert rdb.query("SELECT count(*) FROM t").strip() == "|5|"
        assert rdb.query("SELECT count(*) FROM t", mode="session",
                         watermark=6).strip() == "|5|"
        # Uncovered watermark: refuse within the bounded gate wait.
        with pytest.raises(ReplicaRefusal) as e:
            rdb.query("SELECT 1", mode="session", watermark=7,
                      timeout=0.05)
        assert e.value.reason == "watermark-uncovered"

        # follower/linear need the TABLE heartbeat; keep it fresh from
        # a background refresher (the engine's 2ms thread, compressed).
        stop = threading.Event()

        def beat():
            while not stop.is_set():
                up.refresh(lease_s=time.monotonic() + 0.05)
                time.sleep(0.002)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            assert _wait(lambda: rdb.watermark(0) >= 6)
            assert rdb.query("SELECT count(*) FROM t",
                             mode="follower").strip() == "|5|"
            assert rdb.query("SELECT count(*) FROM t",
                             mode="linear").strip() == "|5|"
        finally:
            stop.set()
            t.join()

        # Writes refuse toward the write tier with the leader hint.
        with pytest.raises(NotLeaderError) as e:
            rdb.propose("INSERT INTO t VALUES (9, 'x')", 0)
        assert e.value.leader == 2          # leader_of()=1 -> 1-based 2
        # The refusal counters feed /metrics.
        m = rdb.metrics()
        assert m["replica_refusals"]["read-only-tier"] == 1
        assert m["replica_reads"]["linear"] == 1
        doc = rdb.health_doc()
        assert doc["replica"]["connected"]
        assert doc["groups"]["0"]["applied"] >= 6
    finally:
        rdb.close()
        srv.stop()
        up.close()


def test_resume_replays_only_the_tail(tmp_path):
    """Reconnect with a high-water vector: the server's log replay
    skips records at or below it (the wire's resume contract)."""
    up = _Upstream(tmp_path)
    srv = ReplicaStreamServer(up.pub, 0, host="127.0.0.1")
    srv.start()
    sub = ReplicaSubscriber(("127.0.0.1", srv.port))
    try:
        up.apply(0, SCHEMA, 1)
        up.apply(0, "INSERT INTO t VALUES (1, 'a')", 2)
        sub.start()
        assert _wait(lambda: _applied_of(sub) >= 2)

        # Sever the connection server-side; the subscriber reconnects
        # and presents applied=2 — the replay must skip 1 and 2.
        with srv._mu:
            conns = [s.conn for s in srv._subs]
        for c in conns:
            c.shutdown(socket.SHUT_RDWR)
        up.apply(0, "INSERT INTO t VALUES (2, 'b')", 3)
        assert _wait(lambda: _applied_of(sub) >= 3)
        with sub._cond:
            assert sub.connects >= 2
            got = sub._sms[0].query("SELECT count(*) FROM t")
        assert got.strip() == "|2|"
        # No resync happened: the log covered the reconnect.
        with sub._cond:
            assert sub.resyncs == 0
    finally:
        sub.stop()
        srv.stop()
        up.close()


def test_log_overflow_resyncs_the_stream_with_fresh_bases(tmp_path):
    """ISSUE 19 satellite: overflow kills the local shm fast path, but
    the STREAM re-images subscribers from fresh serializations — and
    the replica's visible row count never goes backwards or serves a
    partial prefix in between."""
    up = _Upstream(tmp_path, size=1)       # min region: ~1 MiB log
    srv = ReplicaStreamServer(up.pub, 0, host="127.0.0.1")
    srv.start()
    sub = ReplicaSubscriber(("127.0.0.1", srv.port))
    counts = []
    stop = threading.Event()

    def watch():
        while not stop.is_set():
            with sub._cond:
                sm = sub._sms.get(0)
                got = sm.query("SELECT count(*) FROM t") if sm else None
            if got is not None:
                counts.append(int(got.strip().strip("|")))
            time.sleep(0.002)

    t = threading.Thread(target=watch, daemon=True)
    try:
        up.apply(0, SCHEMA, 1)
        up.apply(0, "INSERT INTO t VALUES (0, 'seed')", 2)
        sub.start()
        assert _wait(lambda: _applied_of(sub) >= 2)
        t.start()

        big = "-- " + "x" * 600_000        # two of these overflow
        up.apply(0, "INSERT INTO t VALUES (1, 'a') " + big, 3)
        up.apply(0, "INSERT INTO t VALUES (2, 'b') " + big, 4)
        assert up.pub.log_full             # local shm plane is dead...
        up.apply(0, "INSERT INTO t VALUES (3, 'c')", 5)
        # ...but the stream keeps folding: the tee fires even after
        # overflow, so subscribers never notice.
        assert _wait(lambda: _applied_of(sub) >= 5)
        with sub._cond:
            got = sub._sms[0].query("SELECT count(*) FROM t")
        assert got.strip() == "|4|"

        # A LATE subscriber can't bootstrap from the full log: the
        # server re-images it with fresh KIND_BASE records instead.
        late = ReplicaSubscriber(("127.0.0.1", srv.port))
        late.start()
        try:
            assert _wait(lambda: _applied_of(late) >= 5)
            with late._cond:
                got = late._sms[0].query("SELECT count(*) FROM t")
                bases = late.bases_rx
            assert got.strip() == "|4|"
            assert bases >= 1              # bootstrapped via re-image
        finally:
            late.stop()
        assert srv.resyncs >= 1
    finally:
        stop.set()
        if t.is_alive():
            t.join()
        sub.stop()
        srv.stop()
        up.close()
    # The watcher never saw the count regress (no stale row served
    # between overflow and re-image).
    assert all(a <= b for a, b in zip(counts, counts[1:])), counts


def test_queue_lap_resyncs_instead_of_blocking_the_apply(tmp_path):
    """A subscriber whose tee queue laps is re-imaged, not blocked on:
    mark needs_resync directly (the deterministic equivalent of a full
    queue) and require the fresh-bases path to land."""
    up = _Upstream(tmp_path)
    srv = ReplicaStreamServer(up.pub, 0, host="127.0.0.1")
    srv.start()
    sub = ReplicaSubscriber(("127.0.0.1", srv.port))
    try:
        up.apply(0, SCHEMA, 1)
        up.apply(0, "INSERT INTO t VALUES (1, 'a')", 2)
        sub.start()
        assert _wait(lambda: _applied_of(sub) >= 2)
        with srv._mu:
            assert len(srv._subs) == 1
            srv._subs[0].needs_resync = True
        assert _wait(lambda: srv.resyncs >= 1)
        up.apply(0, "INSERT INTO t VALUES (2, 'b')", 3)
        assert _wait(lambda: _applied_of(sub) >= 3)
        with sub._cond:
            got = sub._sms[0].query("SELECT count(*) FROM t")
        assert got.strip() == "|2|"
    finally:
        sub.stop()
        srv.stop()
        up.close()


# -- the fail-closed ladder (no stream attached) ----------------------------


def _detached_rdb():
    sub = ReplicaSubscriber(("127.0.0.1", 1))   # never started
    return ReplicaDB(sub), sub


def test_ladder_refuses_everything_before_attach():
    rdb, _sub = _detached_rdb()
    for mode in ("local", "session", "follower", "linear"):
        with pytest.raises(ReplicaRefusal) as e:
            rdb.query("SELECT 1", mode=mode, timeout=0.01)
        assert e.value.reason == "no-stream"
    m = rdb.metrics()
    assert m["replica_refusals"]["no-stream"] == 4
    assert m["replica"]["refusals"] == 4


def test_ladder_gates_after_attach_without_heartbeat():
    rdb, sub = _detached_rdb()
    with sub._cond:
        sub.epoch = 99            # attached once...
        sub.num_groups = 1        # ...but no TABLE ever arrived
    assert rdb.query("SELECT 1").strip() == "|1|"   # local always serves
    with pytest.raises(ReplicaRefusal) as e:
        rdb.query("SELECT 1", mode="follower", timeout=0.01)
    assert e.value.reason == "heartbeat-stale"
    with pytest.raises(ReplicaRefusal) as e:
        rdb.query("SELECT 1", mode="linear", timeout=0.01)
    assert e.value.reason == "heartbeat-stale"
    with pytest.raises(ValueError):
        rdb.query("SELECT 1", group=5)
    with pytest.raises(ValueError):
        rdb.query("DELETE FROM t")         # read-only tier, 400-class


def test_linear_refuses_on_lapsed_lease():
    rdb, sub = _detached_rdb()
    now = time.monotonic_ns()
    with sub._cond:
        sub.epoch = 99
        sub.num_groups = 1
        sub._tbl = {"rx_ns": now + (1 << 40), "log_full": False,
                    "rows": [(0, 0, 0, now - 1, 3)]}   # lease in the past
    with pytest.raises(ReplicaRefusal) as e:
        rdb.query("SELECT 1", mode="linear", timeout=0.01)
    assert e.value.reason == "lease-lapsed"
    assert e.value.leader == 3             # hint points at the leader


def test_gate_wait_is_bounded():
    """A replica refuses FAST: the ladder's wait is capped at
    GATE_WAIT_S regardless of the client's request timeout."""
    rdb, sub = _detached_rdb()
    with sub._cond:
        sub.epoch = 99
        sub.num_groups = 1
    t0 = time.monotonic()
    with pytest.raises(ReplicaRefusal):
        rdb.query("SELECT 1", mode="session", watermark=10, timeout=30.0)
    assert time.monotonic() - t0 < GATE_WAIT_S + 1.0


def test_render_surfaces_are_json_lines():
    import json
    rdb, sub = _detached_rdb()
    for render in (rdb.render_health, rdb.render_metrics,
                   rdb.render_members, rdb.render_trace,
                   rdb.render_events):
        out = render()
        assert out.endswith("\n")
        json.loads(out)
    prom = rdb.render_metrics_prom()
    assert "raftsql_replica_refusals" in prom
