"""C++ KV apply plane (native/wal.cc kv_*) — parity with the Python
KVStateMachine and end-to-end behavior on the fused runtime."""
import random

import pytest

from raftsql_tpu.models.kv_sm import KVStateMachine


@pytest.fixture()
def nat():
    from raftsql_tpu.native.build import load_native_plog
    lib = load_native_plog()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def _mk_plog(lib, num_groups):
    from raftsql_tpu.storage.log import NativePayloadLog
    return NativePayloadLog(num_groups, lib)


class TestKvParity:
    def test_command_grammar_matches_python_sm(self, nat):
        """Race the two planes over a randomized command stream —
        including the grammar edges (empty values/keys, extra spaces,
        bad commands) — and compare final states key by key."""
        from raftsql_tpu.models.kv_native import NativeKV

        rng = random.Random(7)
        cmds = []
        for i in range(400):
            r = rng.random()
            if r < 0.5:
                cmds.append(f"SET k{rng.randrange(40)} v{i} with spaces")
            elif r < 0.65:
                cmds.append(f"SET k{rng.randrange(40)} ")   # empty value
            elif r < 0.8:
                cmds.append(f"DEL k{rng.randrange(40)}")
            elif r < 0.85:
                cmds.append("SET onlykey")                  # bad
            elif r < 0.9:
                cmds.append("DEL two tokens")               # bad
            elif r < 0.95:
                cmds.append("NOP whatever")                 # bad
            else:
                cmds.append("SET  leading")   # empty key, value ok

        py = KVStateMachine()
        n_bad = 0
        for i, c in enumerate(cmds):
            if py.apply(c, i + 1) is not None:
                n_bad += 1

        plog = _mk_plog(nat, 1)
        plog.put(0, 1, [c.encode() for c in cmds], [1] * len(cmds))
        kv = NativeKV(1, nat)
        done = kv.apply_plog(plog.handle, [0], [1], [len(cmds)])
        assert kv.bad_commands == n_bad
        assert done == len(cmds) - n_bad
        snap = py.snapshot()
        assert kv.count(0) == len(snap)
        for k, v in snap.items():
            assert kv.get(0, k) == v, k
        kv.close()
        plog.close()

    def test_exactly_once_on_overlapping_ranges(self, nat):
        from raftsql_tpu.models.kv_native import NativeKV

        plog = _mk_plog(nat, 2)
        plog.put(1, 1, [b"SET a 1", b"SET a 2", b"", b"SET b 3"],
                 [1, 1, 1, 1])
        kv = NativeKV(2, nat)
        assert kv.apply_plog(plog.handle, [1], [1], [4]) == 3
        assert kv.applied_index(1) == 4
        # Re-applying the same (or a prefix) range is a no-op.
        assert kv.apply_plog(plog.handle, [1], [1], [4]) == 0
        assert kv.apply_plog(plog.handle, [1], [2], [2]) == 0
        assert kv.get(1, "a") == "2" and kv.get(1, "b") == "3"
        # Empty payloads (no-op entries) advance applied, apply nothing.
        assert kv.count(1) == 2
        kv.close()
        plog.close()

    def test_out_of_window_raises_like_python_path(self, nat):
        """A committed index with no payload-log backing is a fault,
        not a silent truncation: the wrapper raises (the Python publish
        path's 'payload log shorter than commit' contract) and the work
        done before the fault is recorded, so a repaired retry does not
        double-apply."""
        from raftsql_tpu.models.kv_native import NativeKV

        plog = _mk_plog(nat, 2)
        plog.put(0, 1, [b"SET a 1", b"SET a 2"], [1, 1])
        kv = NativeKV(2, nat)
        with pytest.raises(RuntimeError):
            kv.apply_plog(plog.handle, [0, 1], [1, 1], [5, 1])
        # Entries 1-2 applied before the fault; applied[] reflects it.
        assert kv.applied_index(0) == 2
        assert kv.get(0, "a") == "2"
        assert kv.total_applied == 0      # faulted batch not counted
        # Repair the log and retry the batch: only the new entries run.
        plog.put(0, 3, [b"SET a 3", b"", b"SET b 9"], [1, 1, 1])
        plog.put(1, 1, [b"SET c 7"], [1])
        assert kv.apply_plog(plog.handle, [0, 1], [1, 1], [5, 1]) == 3
        assert kv.get(0, "a") == "3" and kv.get(0, "b") == "9"
        assert kv.get(1, "c") == "7"
        kv.close()
        plog.close()

    def test_long_values_round_trip(self, nat):
        from raftsql_tpu.models.kv_native import NativeKV

        plog = _mk_plog(nat, 1)
        big = "x" * 5000
        plog.put(0, 1, [f"SET big {big}".encode()], [1])
        kv = NativeKV(1, nat)
        assert kv.apply_plog(plog.handle, [0], [1], [1]) == 1
        assert kv.get(0, "big") == big      # > first 256-byte buffer
        assert kv.get(0, "absent") is None
        kv.close()
        plog.close()


class TestFusedNativeApply:
    def test_fused_runtime_applies_through_c_plane(self, nat, tmp_path):
        """End to end on the fused durable runtime: proposals committed
        by consensus land in the C KV store without any Python-side
        consumer, and the values match what was proposed."""
        from raftsql_tpu.config import RaftConfig
        from raftsql_tpu.models.kv_native import NativeKV
        from raftsql_tpu.runtime.fused import FusedClusterNode

        import os
        os.environ["RAFTSQL_FUSED_NATIVE_PLOG"] = "1"
        try:
            cfg = RaftConfig(num_groups=3, num_peers=3, log_window=64,
                             max_entries_per_msg=4, tick_interval_s=0.0)
            node = FusedClusterNode(cfg, str(tmp_path / "data"))
            assert hasattr(node.plogs[0], "handle")
            kv = NativeKV(3, node._plog_lib)
            node.native_kv = kv
            node.publish_peers = {0}
            for t in range(400):
                node.tick()
                if t > 10 and (node._hints >= 0).all():
                    break
            assert (node._hints >= 0).all()
            for g in range(3):
                node.propose_many(g, [f"SET g{g}k{i} val{i}".encode()
                                      for i in range(6)])
            for _ in range(30):
                node.tick()
                if all(kv.applied_index(g) >= 6 for g in range(3)):
                    break
            for g in range(3):
                for i in range(6):
                    assert kv.get(g, f"g{g}k{i}") == f"val{i}", (g, i)
            node.stop()
            kv.close()
        finally:
            del os.environ["RAFTSQL_FUSED_NATIVE_PLOG"]
