"""Test configuration: force an 8-device virtual CPU platform.

Tests must run without TPU hardware and must exercise multi-device
sharding, so we ask XLA for 8 host-platform devices.  This is the
multi-node-without-a-real-cluster trick of the reference test harness
(reference raftsql_test.go:16-28, loopback TCP on localhost ports) in its
TPU-native form.

IMPORTANT: this environment's `sitecustomize` imports jax at interpreter
startup and registers the remote-TPU ("axon") backend, so jax's
`jax_platforms` config was already captured from the environment before
this conftest runs.  Setting os.environ here is too late — we must update
the live jax config, otherwise every test computation silently round-trips
through the single shared TPU tunnel (and concurrent test runs wedge it).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_platforms or jax.config.jax_platforms == "cpu"
