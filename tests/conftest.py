"""Test configuration: force an 8-device virtual CPU platform.

Tests must run without TPU hardware and must exercise multi-device sharding,
so we ask XLA for 8 host-platform devices before jax initializes.  This is
the multi-node-without-a-real-cluster trick of the reference test harness
(reference raftsql_test.go:16-28, loopback TCP on localhost ports) in its
TPU-native form.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
