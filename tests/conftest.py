"""Test configuration: force an 8-device virtual CPU platform.

Tests must run without TPU hardware and must exercise multi-device
sharding, so we ask XLA for 8 host-platform devices.  This is the
multi-node-without-a-real-cluster trick of the reference test harness
(reference raftsql_test.go:16-28, loopback TCP on localhost ports) in its
TPU-native form.

IMPORTANT: this environment's `sitecustomize` imports jax at interpreter
startup and registers the remote-TPU ("axon") backend, so jax's
`jax_platforms` config was already captured from the environment before
this conftest runs.  Setting os.environ here is too late — we must update
the live jax config, otherwise every test computation silently round-trips
through the single shared TPU tunnel (and concurrent test runs wedge it).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_platforms or jax.config.jax_platforms == "cpu"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running sweeps (deep chaos schedules), excluded "
        "from the tier-1 run via -m 'not slow'")


def free_port() -> int:
    """An OS-assigned localhost port.  Bind-and-release has the usual
    TOCTOU window: the OS may hand the released port to someone else
    before the caller binds it.  Ephemeral-range collisions are rare and
    the suites run nodes that fail loudly on bind conflict; callers that
    need a narrower window should reserve with `reserve_ports` instead."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def reserve_ports(n: int):
    """Bind n distinct localhost ports and HOLD them; returns
    (ports, release) where release() closes the sockets.  Guarantees
    in-batch uniqueness and shrinks the reuse window to after release."""
    import socket
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]

    def release():
        for s in socks:
            s.close()

    return ports, release
