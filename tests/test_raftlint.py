"""raftlint (raftsql_tpu/analysis/) — checker fixtures + live tree.

Per checker: a must-flag snippet (the defect class, distilled) and a
must-pass twin (the sanctioned idiom), run through `unit_from_source`
against a stub config so the fixtures are hermetic.  Then the teeth:
the COMMITTED tree must be raftlint-clean (the tier-1 gate behind
`make vet`), and the jit compile-count tripwire must observe exactly
one compilation of the fused cluster step across a mini chaos run —
the runtime falsifier for the jit-stability rule.
"""
import dataclasses
import tempfile

import pytest

from raftsql_tpu.analysis import config as live_config
from raftsql_tpu.analysis.core import (all_checkers, run_suite,
                                       run_units, unit_from_source)


class StubConfig:
    """Bare config: every scope empty unless a test opts in."""
    DEFAULT_PATHS = []
    DETERMINISM_PATHS = ["src/"]
    JIT_ENTRY_POINTS = {"step_jit"}
    JIT_STATIC_ARGS = {"step_jit": {0, "cfg"}}
    JIT_SKIP_MIXING_PREFIXES = ()
    OWNERSHIP_REQUIRED = {}
    FAILCLOSED_REQUIRED = {}
    ALLOWLIST = []
    allowlist = ALLOWLIST


def lint(src, relpath="src/mod.py", rules=None, config=None):
    unit = unit_from_source(src, relpath)
    return run_units([unit], config or StubConfig(), rules=rules)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- framework ----------------------------------------------------------

def test_registered_rule_set():
    names = {c.name for c in all_checkers()}
    assert {"unused-import", "duplicate-def", "mutable-default",
            "assert-tuple", "bare-except", "wall-clock",
            "unseeded-random", "jit-stability", "thread-ownership",
            "fail-closed", "memory-model"} <= names


def test_suppression_comment_silences_one_line():
    src = "import time\ntime.time()  # raftlint: disable=wall-clock -- test\n"
    assert lint(src, rules=["wall-clock"]) == []
    src = "import time\ntime.time()\n"
    assert rules_of(lint(src, rules=["wall-clock"])) == ["wall-clock"]


def test_skip_file_opts_out_entirely():
    src = "# raftlint: skip-file\nimport time\ntime.time()\n"
    assert lint(src) == []


def test_allowlist_requires_matching_entry():
    cfg = StubConfig()
    cfg.allowlist = [{"rule": "wall-clock", "path": "src/mod.py",
                      "why": "test"}]
    assert lint("import time\ntime.time()\n", rules=["wall-clock"],
                config=cfg) == []


# -- the five classic rules --------------------------------------------

@pytest.mark.parametrize("rule,bad,good", [
    ("unused-import", "import os\n", "import os\nos.getcwd()\n"),
    ("duplicate-def", "def f():\n    pass\ndef f():\n    pass\n",
     "def f():\n    pass\ndef g():\n    pass\n"),
    ("mutable-default", "def f(x=[]):\n    pass\n",
     "def f(x=None):\n    pass\n"),
    ("assert-tuple", "assert (1 == 1, 'msg')\n", "assert 1 == 1, 'msg'\n"),
    ("bare-except", "try:\n    pass\nexcept:\n    pass\n",
     "try:\n    pass\nexcept ValueError:\n    pass\n"),
])
def test_classic_rules(rule, bad, good):
    assert rules_of(lint(bad, rules=[rule])) == [rule]
    assert lint(good, rules=[rule]) == []


# -- determinism --------------------------------------------------------

def test_wall_clock_flags_time_time_in_scope_only():
    src = "import time\nt = time.time()\n"
    assert rules_of(lint(src, rules=["wall-clock"])) == ["wall-clock"]
    # Out of DETERMINISM_PATHS scope: clean.
    assert lint(src, relpath="tools/x.py", rules=["wall-clock"]) == []
    # The sanctioned clock is untouched.
    assert lint("import time\nt = time.monotonic()\n",
                rules=["wall-clock"]) == []


def test_unseeded_random_flags_global_rng_not_keyed_jax():
    bad = "import random\nx = random.random()\n"
    assert rules_of(lint(bad, rules=["unseeded-random"])) \
        == ["unseeded-random"]
    assert rules_of(lint("import random\nr = random.Random()\n",
                         rules=["unseeded-random"])) == ["unseeded-random"]
    # Seeded constructions and keyed jax.random are the sanctioned forms.
    assert lint("import random\nr = random.Random(42)\n",
                rules=["unseeded-random"]) == []
    assert lint("import jax\nx = jax.random.randint(key, (), 0, 9)\n",
                rules=["unseeded-random"]) == []
    assert lint("import numpy as np\nr = np.random.default_rng(7)\n",
                rules=["unseeded-random"]) == []
    assert rules_of(lint("import numpy as np\nr = np.random.default_rng()\n",
                         rules=["unseeded-random"])) == ["unseeded-random"]


# -- jit-stability ------------------------------------------------------

DTYPE_SWITCH = """\
def tick(self, timer_inc=None):
    ti = 1 if timer_inc is None else jnp.asarray(timer_inc)
    return step_jit(cfg, state, ti)
"""

BOOT_FIXED = """\
def tick(self, timer_inc=None):
    ti = self._ti_ones if timer_inc is None else jnp.asarray(timer_inc)
    return step_jit(cfg, state, ti)
"""


def test_jit_stability_flags_conditional_literal_arg():
    # The PR 12 defect class, distilled: scalar on one branch, array on
    # the other, feeding a jit entry point -> two trace signatures.
    assert rules_of(lint(DTYPE_SWITCH, rules=["jit-stability"])) \
        == ["jit-stability"]
    assert lint(BOOT_FIXED, rules=["jit-stability"]) == []


def test_jit_stability_flags_cross_site_literal_mixing():
    src = ("def a():\n    return step_jit(cfg, state, 1)\n"
           "def b(arr):\n    return step_jit(cfg, state, arr)\n")
    assert rules_of(lint(src, rules=["jit-stability"])) \
        == ["jit-stability"]
    # Same literal everywhere: one signature, clean.
    same = ("def a():\n    return step_jit(cfg, state, 1)\n"
            "def b():\n    return step_jit(cfg, state, 1)\n")
    assert lint(same, rules=["jit-stability"]) == []


def test_jit_stability_static_args_exempt():
    # cfg (static_argnums=0) varies as a Python value by design.
    src = ("def a(c1, c2, x):\n"
           "    step_jit(c1, state, x)\n"
           "    step_jit(2, state, x)\n")
    assert lint(src, rules=["jit-stability"]) == []


def test_jit_stability_flags_jit_in_loop():
    src = ("import jax\n"
           "def f(xs):\n"
           "    for x in xs:\n"
           "        g = jax.jit(lambda y: y)\n"
           "        g(x)\n")
    assert rules_of(lint(src, rules=["jit-stability"])) \
        == ["jit-stability"]


# -- thread-ownership ---------------------------------------------------

LOCKFREE_WRITE = """\
import threading

class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._props = []  # raftlint: guarded-by=_lock

    def propose(self, item):
        self._props.append(item)
"""

LOCKED_WRITE = """\
import threading

class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._props = []  # raftlint: guarded-by=_lock

    def propose(self, item):
        with self._lock:
            self._props.append(item)

    def peek(self):
        return len(self._props)   # lock-free READ: sanctioned idiom
"""

OWNER_OPT_OUT = """\
import threading

class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._props = []  # raftlint: guarded-by=_lock

    def drain(self):  # raftlint: owner=tick-thread -- close() joins first
        self._props = []
"""


def test_ownership_flags_lock_free_write():
    got = lint(LOCKFREE_WRITE, rules=["thread-ownership"])
    assert rules_of(got) == ["thread-ownership"]
    assert "_props" in got[0].message and "_lock" in got[0].message


def test_ownership_passes_locked_write_and_lock_free_read():
    assert lint(LOCKED_WRITE, rules=["thread-ownership"]) == []


def test_ownership_owner_annotation_opts_method_out():
    assert lint(OWNER_OPT_OUT, rules=["thread-ownership"]) == []


def test_ownership_registry_pins_required_annotations():
    cfg = StubConfig()
    cfg.OWNERSHIP_REQUIRED = {("mod.py", "Plane"): {"_props": "_lock"}}
    bare = ("class Plane:\n"
            "    def __init__(self):\n"
            "        self._props = []\n")
    got = lint(bare, relpath="src/mod.py", rules=["thread-ownership"],
               config=cfg)
    assert rules_of(got) == ["thread-ownership"]
    assert "guarded-by=_lock" in got[0].message


# -- fail-closed + memory-model ----------------------------------------

FALLS_OFF_END = """\
def try_read(mode):  # raftlint: fail-closed
    if mode == "local":
        return 1
    elif mode == "linear":
        return 2
"""

EXPLICIT_FALLBACK = """\
def try_read(mode):  # raftlint: fail-closed
    if mode == "local":
        return 1
    elif mode == "linear":
        return 2
    return None
"""

SWALLOWING_HANDLER = """\
def try_read(q):  # raftlint: fail-closed
    try:
        out = run(q)
    except Exception:
        out = None
    return out
"""


def test_fail_closed_flags_fall_off_the_end():
    got = lint(FALLS_OFF_END, rules=["fail-closed"])
    assert rules_of(got) == ["fail-closed"]
    assert lint(EXPLICIT_FALLBACK, rules=["fail-closed"]) == []


def test_fail_closed_flags_swallowing_handler():
    assert rules_of(lint(SWALLOWING_HANDLER, rules=["fail-closed"])) \
        == ["fail-closed"]


def test_fail_closed_only_applies_to_annotated_defs():
    plain = "def f(mode):\n    if mode:\n        return 1\n"
    assert lint(plain, rules=["fail-closed"]) == []


def test_memory_model_requires_file_level_assumes():
    bare = "def read():  # raftlint: seqlock\n    return 1\n"
    assert rules_of(lint(bare, rules=["memory-model"])) \
        == ["memory-model"]
    declared = ("# raftlint: assumes=x86-tso\n"
                "def read():  # raftlint: seqlock\n    return 1\n")
    assert lint(declared, rules=["memory-model"]) == []


# -- the teeth ----------------------------------------------------------

def test_live_tree_is_raftlint_clean():
    """The committed tree passes the full suite — same gate as
    `make vet` / the CI lint job."""
    findings = run_suite(live_config.DEFAULT_PATHS)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_allowlist_entries_carry_justifications():
    for entry in live_config.ALLOWLIST:
        assert entry.get("why"), f"allowlist entry without why: {entry}"
        assert entry.get("rule") and entry.get("path")


def test_tripwire_single_compile_fused():
    """Runtime falsifier for jit-stability: a fused chaos run compiles
    each jit entry point it exercises exactly once — the None and the
    skew timer_inc branches, the restart path, and every nemesis
    transform all feed ONE trace signature."""
    from raftsql_tpu.analysis.tripwire import JitTripwire
    from raftsql_tpu.chaos.schedule import generate_skew
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner

    # The skew family flips timer_inc between None and a [P] vector
    # mid-run — the exact historical recompile schedule.
    sched = generate_skew(3)
    sched = dataclasses.replace(sched, ticks=min(sched.ticks, 120))
    tw = JitTripwire()
    with tempfile.TemporaryDirectory(prefix="raftlint-tw-") as d:
        FusedChaosRunner(sched, d).run()
    compiles = tw.compiles()
    # A fresh process must compile exactly once; when an earlier test
    # in the suite already warmed the cache, a hit (delta 0) is the
    # same single-signature property — never a second compile.
    warm = tw.baseline("cluster_step_host") or 0
    assert compiles.get("cluster_step_host") in \
        ({0, 1} if warm else {1}), compiles
    assert tw.offenders(limit=1) == {}, compiles
