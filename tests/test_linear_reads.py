"""Linearizable reads (ReadIndex, raft §6.4) — beyond reference parity.

The reference serves GETs from the local replica and documents the
staleness (db.go:128-130, raftsql_test.go:150-158).  `query(...,
linear=True)` upgrades a read: only the group's current leader serves
it, after a quorum re-confirms its leadership on a round started after
the call and the local apply catches up to the read point.  These tests
pin the three behaviors that make that linearizable:

  - read-your-writes at the leader, immediately after the ack;
  - non-leaders refuse with the leader's identity (no silent staleness);
  - a leader cut off from its quorum cannot serve (no stale reads from
    a deposed leader that doesn't know it yet).
"""
import os
import time

import pytest

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import NotLeaderError, RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.loopback import (FaultPlan, LoopbackHub,
                                            LoopbackTransport)

TICK = 0.005
TIMEOUT = 30.0


@pytest.fixture
def cluster(tmp_path):
    faults = FaultPlan()
    hub = LoopbackHub(faults=faults)
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=TICK,
                     election_ticks=10, log_window=64,
                     max_entries_per_msg=4)
    dbs = []
    for i in range(3):
        pipe = RaftPipe.create(
            i + 1, 3, cfg, LoopbackTransport(hub),
            data_dir=os.path.join(str(tmp_path), f"raftsql-{i + 1}"))
        dbs.append(RaftDB(
            lambda g, i=i: SQLiteStateMachine(
                os.path.join(str(tmp_path), f"db-{i}.db")),
            pipe, num_groups=1))
    yield dbs, faults
    for db in dbs:
        try:
            db.close()
        except Exception:
            pass


def leader_index(dbs, timeout=TIMEOUT) -> int:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for i, db in enumerate(dbs):
            node = db.pipe.node
            if node._last_role[0] == LEADER:
                return i
        time.sleep(0.02)
    raise AssertionError("no leader elected")


def test_linear_read_your_writes_at_leader(cluster):
    dbs, _ = cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = leader_index(dbs)
    for k in range(5):
        assert dbs[lead].propose(
            f"INSERT INTO t (v) VALUES ('k{k}')").wait(TIMEOUT) is None
        # Immediately after the ack, a linear read at the leader must see
        # the write (the ack already implies local apply; the quorum
        # round proves the leader is still current).
        got = dbs[lead].query("SELECT count(*) FROM t", linear=True,
                              timeout=TIMEOUT)
        assert got == f"|{k + 1}|\n", got


def test_linear_read_rejected_at_follower(cluster):
    dbs, _ = cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = leader_index(dbs)
    follower = (lead + 1) % 3
    # Followers must refuse rather than serve a possibly-stale answer,
    # and must say who the leader is.
    with pytest.raises(NotLeaderError) as ei:
        dbs[follower].query("SELECT count(*) FROM t", linear=True,
                            timeout=5.0)
    assert ei.value.leader == lead + 1
    # Plain (reference-parity) reads still work on followers — but they
    # are STALE by design, so poll until the follower's replica has
    # applied the schema (reference raftsql_test.go:159-170).
    deadline = time.monotonic() + TIMEOUT
    while True:
        try:
            assert dbs[follower].query(
                "SELECT count(*) FROM t").startswith("|")
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def test_linear_read_blocked_without_quorum(cluster):
    """A leader partitioned from its quorum must NOT serve a linear read
    — that is the exact staleness window ReadIndex closes (the deposed
    leader may not know a new leader committed past it)."""
    dbs, faults = cluster
    assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) is None
    lead = leader_index(dbs)
    faults.isolate(lead + 1, range(1, 4))
    # Allow in-flight quorum confirmations to drain past reg_tick + 2.
    time.sleep(20 * TICK)
    t0 = time.monotonic()
    with pytest.raises((TimeoutError, NotLeaderError)):
        dbs[lead].query("SELECT count(*) FROM t", linear=True, timeout=1.5)
    assert time.monotonic() - t0 < 10.0
    faults.heal()
