"""Observability subsystem (raftsql_tpu/obs/): device-plane event
ring, host-plane lifecycle spans, Chrome-trace (Perfetto) export, the
/trace and /events HTTP endpoints, the propose→commit histograms in
/metrics, and the chaos flight recorder — plus the PR 8 production
telemetry plane: the tick-phase profiler (overlap-aware attribution),
per-group traffic accounting (top-K hot groups), the Prometheus text
exposition on both HTTP planes, and the cross-process /trace merge of
a --workers deployment.

The schema checks here ARE the acceptance gate for "Perfetto accepts
the emitted JSON": validate_chrome_trace enforces the trace-event
object form (name/ph/ts/pid, X needs dur, C needs numeric args) that
both Perfetto and chrome://tracing require; scripts/check_prom.py's
parse_prom is the same gate for the Prometheus exposition.
"""
import http.client
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.obs.device_ring import EVENT_FIELDS
from raftsql_tpu.obs.export import chrome_trace, validate_chrome_trace
from raftsql_tpu.obs.spans import SpanTracer
from raftsql_tpu.runtime.fused import FusedClusterNode


def mkcfg(groups=4):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, election_ticks=10,
                      heartbeat_ticks=1, tick_interval_s=0.0)


def elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


@pytest.fixture
def traced_node(tmp_path):
    node = FusedClusterNode(mkcfg(), str(tmp_path))
    node.enable_tracing(ring_depth=16)
    yield node
    node.stop()


# -- device plane ------------------------------------------------------

def test_device_ring_records_every_tick(traced_node):
    node = traced_node
    elect(node)
    for g in range(node.cfg.num_groups):
        node.propose_many(g, [f"SET k{g} v{i}".encode()
                              for i in range(6)])
    for _ in range(20):
        node.tick()
    node.publish_flush()
    node.ring.drain()
    rows = node.ring.rows()
    assert len(rows) == node.metrics.ticks
    # Tick-indexed, in order, with a batch drain every ring_depth ticks.
    assert [r["tick"] for r in rows] == list(range(len(rows)))
    assert node.ring.drains >= len(rows) // 16
    last = rows[-1]
    assert set(EVENT_FIELDS) - {"tick"} <= set(last)
    P, G = node.cfg.num_peers, node.cfg.num_groups
    assert len(last["term"]) == P and len(last["term"][0]) == G
    # Post-election, post-commit state is visible per (peer, group).
    assert all(t >= 1 for row in last["term"] for t in row)
    assert all(c >= 6 for row in last["commit"] for c in row)
    # An elected leader holds a vote quorum for its group somewhere.
    assert any(v >= 2 for row in last["votes"] for v in row)


def test_ring_disabled_by_default(tmp_path):
    node = FusedClusterNode(mkcfg(1), str(tmp_path))
    try:
        assert node.ring is None and node.tracer is None
        for _ in range(5):
            node.tick()     # no tracing machinery runs
    finally:
        node.stop()


# -- host plane (spans) ------------------------------------------------

def test_span_lifecycle_fused(traced_node):
    node = traced_node
    elect(node)
    node.propose_many(1, [b"SET k1 v1", b"SET k1 v2"])
    for _ in range(15):
        node.tick()
    node.publish_flush()
    snap = node.tracer.snapshot()
    spans = [s for s in snap["spans"] if s["group"] == 1
             and s["key"].startswith("SET k1")]
    assert len(spans) == 2
    for s in spans:
        ph = s["phases"]
        # The fused runner has no apply/ack layer on the raw node; the
        # pipeline up to commit must be stamped and ordered.
        assert ph["propose"] <= ph["append"] <= ph["replicate"] \
            <= ph["commit"]
        assert s["index"] >= 1
    # WAL fsync events landed on the timeline ring.
    assert any(e["name"] == "wal.fsync" for e in snap["events"])


def test_span_tracer_bounded_and_threadsafe():
    tr = SpanTracer(max_pending=8, max_live=8, max_done=16)
    for i in range(100):
        tr.begin(0, f"q{i}")
    assert tr.dropped == 100 - 8
    tr.note_append(0, 1, [f"q{i}" for i in range(92, 100)])
    tr.note_commit(0, 8)
    for i in range(92, 100):
        tr.note_ack(0, f"q{i}")
    snap = tr.snapshot()
    assert len(snap["spans"]) <= 16
    done = [s for s in snap["spans"] if "ack" in s["phases"]]
    assert len(done) == 8


def test_span_unknown_keys_are_skipped():
    """Forwarded/replayed payloads with no local span must not crash or
    mis-bind (tracing is an observer)."""
    tr = SpanTracer()
    tr.note_append(0, 5, ["never-proposed"])
    tr.note_commit(0, 10)
    tr.note_apply(0, 5)
    tr.note_ack(0, "never-proposed")
    assert tr.snapshot()["spans"] == []


# -- chrome trace export ----------------------------------------------

def test_chrome_trace_schema_from_live_run(traced_node):
    node = traced_node
    elect(node)
    node.propose_many(0, [b"SET k0 v0"])
    for _ in range(10):
        node.tick()
    node.publish_flush()
    node.ring.drain()
    doc = chrome_trace(node.tracer.snapshot(), node.ring.rows())
    validate_chrome_trace(doc)
    # Round-trips through JSON (what GET /trace and make trace emit).
    doc2 = json.loads(json.dumps(doc))
    validate_chrome_trace(doc2)
    evs = doc["traceEvents"]
    assert any(e["ph"] == "X" and "→" in e["name"] for e in evs)
    assert any(e["ph"] == "C" for e in evs)


def test_validate_rejects_malformed():
    validate_chrome_trace({"traceEvents": []})      # empty is valid
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"name": "x", "ph": "Z", "pid": 1, "ts": 0}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"name": "x", "ph": "X", "pid": 1, "ts": -1, "dur": 1}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"name": "x", "ph": "C", "pid": 1, "ts": 0,
             "args": {"value": "not-a-number"}}]})


def test_trace_demo_writes_valid_perfetto_json(tmp_path):
    """`make trace` end to end: the demo runs a traced cluster and the
    emitted file passes the Perfetto schema check."""
    from raftsql_tpu.obs.trace_demo import run_demo
    out = str(tmp_path / "trace.json")
    run_demo(out, groups=2, ticks=60)
    with open(out) as f:
        doc = json.load(f)
    validate_chrome_trace(doc)
    assert len(doc["traceEvents"]) > 10


# -- HTTP endpoints + /metrics histograms ------------------------------

@pytest.fixture(params=["threaded", "aio"])
def server(request, tmp_path):
    from raftsql_tpu.api.aio import AioSQLServer
    from raftsql_tpu.api.http import SQLServer
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import (LoopbackHub,
                                                LoopbackTransport)

    cfg = RaftConfig(num_groups=2, num_peers=1, tick_interval_s=0.005,
                     log_window=64, max_entries_per_msg=4)
    pipe = RaftPipe.create(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                           data_dir=str(tmp_path / "raftsql-1"))
    pipe.node.enable_tracing()
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        str(tmp_path / f"obs-g{g}.db")), pipe, num_groups=2)
    srv_cls = SQLServer if request.param == "threaded" else AioSQLServer
    srv = srv_cls(0, rdb, host="127.0.0.1", timeout_s=30.0)
    srv.start()
    yield srv
    srv.stop()
    rdb.close()


def _get(srv, path):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


def _put(srv, body):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    try:
        conn.request("PUT", "/", body=body)
        r = conn.getresponse()
        r.read()
        return r.status
    finally:
        conn.close()


def test_http_trace_and_events_endpoints(server):
    assert _put(server, b"CREATE TABLE main.o (v text)") == 204
    assert _put(server, b'INSERT INTO main.o (v) VALUES ("a")') == 204

    status, data = _get(server, "/trace")
    assert status == 200
    doc = json.loads(data)
    validate_chrome_trace(doc)
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])

    status, data = _get(server, "/events")
    assert status == 200
    ev = json.loads(data)
    assert ev["tracing"] is True
    spans = ev["host"]["spans"]
    full = [s for s in spans if {"propose", "append", "commit",
                                 "apply", "ack"} <= set(s["phases"])]
    assert full, spans
    ph = full[0]["phases"]
    assert ph["propose"] <= ph["append"] <= ph["commit"] \
        <= ph["apply"] <= ph["ack"]


def test_metrics_has_propose_commit_histogram(server):
    for i in range(3):
        code = _put(server, b"CREATE TABLE IF NOT EXISTS main.h (v text)"
                    if i == 0 else
                    f'INSERT INTO main.h (v) VALUES ("{i}")'.encode())
        assert code == 204
    status, data = _get(server, "/metrics")
    assert status == 200
    m = json.loads(data)
    for k in ("propose_commit_p50_ms", "propose_commit_p95_ms",
              "propose_commit_p99_ms", "propose_ack_p50_ms",
              "propose_ack_p99_ms"):
        assert k in m, k
        assert isinstance(m[k], float), (k, m[k])
    # Commit is observed before apply+ack resolves.
    assert m["propose_commit_p50_ms"] <= m["propose_ack_p99_ms"]


def test_metrics_exports_membership_state(server):
    """Membership observability (raftsql_tpu/membership/): /metrics
    carries the live per-cluster voter/learner slot totals and the
    applied conf-change counter — the operator's view of the active
    configuration's shape without scraping /members."""
    status, data = _get(server, "/metrics")
    assert status == 200
    m = json.loads(data)
    # 1 voter slot x 2 groups, no learners, nothing churned yet.
    assert m["members_voters"] == 2
    assert m["members_learners"] == 0
    assert m["conf_changes_applied"] == 0


# -- production telemetry plane (PR 8) ---------------------------------


def _load_check_prom():
    """scripts/check_prom.py as a module: the tests and the CI lint
    must enforce the exact same exposition grammar."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_prom", os.path.join(repo, "scripts", "check_prom.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_prom_exposition_parses_and_round_trips(server):
    """GET /metrics?format=prom (and Accept negotiation) on both HTTP
    planes: parses under the strict parser, and every numeric field of
    the JSON document appears as a sample (name + labels)."""
    for i in range(3):
        code = _put(server, b"CREATE TABLE IF NOT EXISTS main.p (v text)"
                    if i == 0 else
                    f'INSERT INTO main.p (v) VALUES ("{i}")'.encode())
        assert code == 204
    check_prom = _load_check_prom()
    status, data = _get(server, "/metrics")
    assert status == 200
    json_doc = json.loads(data)
    status, prom = _get(server, "/metrics?format=prom")
    assert status == 200
    samples = check_prom.parse_prom(prom.decode())
    assert samples
    missing = check_prom.check_round_trip(json_doc, samples)
    assert not missing, missing[:10]
    # Accept-header negotiation returns the exposition with the prom
    # content type; the bare GET stays JSON.
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=10)
    try:
        conn.request("GET", "/metrics",
                     headers={"Accept": "application/openmetrics-text"})
        r = conn.getresponse()
        body = r.read().decode()
        assert r.status == 200
        assert (r.getheader("Content-Type") or "").startswith(
            "text/plain")
        check_prom.parse_prom(body)
    finally:
        conn.close()
    json.loads(_get(server, "/metrics")[1])     # default unchanged


def test_per_group_traffic_ranks_hot_group_first(tmp_path):
    """A deliberately skewed workload: the hot group must rank first
    in the top-K table with matching counters and its live leader."""
    node = FusedClusterNode(mkcfg(groups=4), str(tmp_path))
    try:
        elect(node)
        node.propose_many(2, [f"SET h{i} v".encode()
                              for i in range(40)])
        node.propose_many(0, [b"SET cold 1"])
        for _ in range(40):
            node.tick()
        node.publish_flush()
        doc = node.traffic.doc(leader_of=node.leader_of)
        assert doc["proposed"] == 41
        hot = doc["hot_groups"]
        assert hot[0]["group"] == 2, hot
        assert hot[0]["proposed"] == 40
        assert hot[0]["committed"] >= 40        # +fresh-leader no-op
        assert hot[0]["leader"] == node.leader_of(2) + 1
        assert hot[0]["propose_rate"] >= hot[-1]["propose_rate"]
        cold = [r for r in hot if r["group"] == 0]
        assert cold and cold[0]["proposed"] == 1
    finally:
        node.stop()


def test_profiler_attribution_matches_across_overlap_modes(
        tmp_path, monkeypatch):
    """Overlap-aware attribution: a stashed durable phase that retires
    inside tick t+1's dispatch window belongs to tick t.  The SAME
    deterministic workload must therefore yield the SAME set of
    fsync/wal_write-owning ticks with RAFTSQL_OVERLAP_DISPATCH on and
    off (naive record-where-it-ran attribution shifts every hot tick
    by one)."""
    results = {}
    for overlap in ("1", "0"):
        monkeypatch.setenv("RAFTSQL_OVERLAP_DISPATCH", overlap)
        node = FusedClusterNode(mkcfg(groups=2),
                                str(tmp_path / f"ov{overlap}"))
        try:
            assert node.prof is not None        # default ON
            elect(node)
            for i in range(6):
                node.propose_many(0, [f"SET a{i} v".encode()])
                node.tick()
            for _ in range(6):
                node.tick()
            node.publish_flush()                # retires any stash
            results[overlap] = {
                "fsync": node.prof.phase_ticks("fsync"),
                "wal": node.prof.phase_ticks("wal_write"),
                "overlap_ticks": node.metrics.overlap_ticks,
            }
        finally:
            node.stop()
    assert results["1"]["overlap_ticks"] > 0    # the pipeline engaged
    assert results["0"]["overlap_ticks"] == 0
    assert results["1"]["fsync"] == results["0"]["fsync"]
    assert results["1"]["wal"] == results["0"]["wal"]


def test_phase_tracks_in_trace_doc(traced_node):
    """The profiler's phase events land as pid-4 Perfetto tracks next
    to the span/device tracks, on one shared time axis."""
    node = traced_node
    elect(node)
    node.propose_many(0, [b"SET k v"])
    for _ in range(10):
        node.tick()
    node.publish_flush()
    doc = chrome_trace(node.tracer.snapshot(),
                       phase_events=node.prof.events(),
                       base_monotonic=node.tracer.t0)
    validate_chrome_trace(doc)
    phases = [e for e in doc["traceEvents"]
              if e.get("pid") == 4 and e.get("ph") == "X"]
    assert {e["name"] for e in phases} >= {"dispatch", "fsync"}
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in phases)


def test_flight_bundle_carries_serving_state(tmp_path):
    """Flight bundles now carry the PR 7 serving-plane state: overlap
    stash status at crash time, the group-commit batch histogram, and
    per-worker ring cursors/depths."""
    from raftsql_tpu.obs.flight import FlightRecorder
    from raftsql_tpu.runtime.ring import RingServer

    node = FusedClusterNode(mkcfg(groups=2), str(tmp_path / "d"),
                            group_commit=True)
    rs = None
    try:
        elect(node)
        node.propose_many(0, [b"SET x 1", b"SET y 2"])
        node.tick()     # hot tick: the overlap pipeline stashes
        assert node._stash is not None

        class _Rdb:
            serving_metrics = None

        rs = RingServer(_Rdb(), str(tmp_path / "rings"), workers=2)
        rs.start()
        path = FlightRecorder(str(tmp_path / "flights")).dump(
            "serving-unit", "unit-test", node=node, ring_server=rs)
        with open(path) as f:
            doc = json.load(f)
        s = doc["serving"]
        assert s["overlap"]["enabled"] is True
        assert s["overlap"]["stashed"] is True
        assert isinstance(s["overlap"]["stash_tick"], int)
        assert s["overlap"]["stash_entries"] >= 2
        assert s["wal_group_commit"]["group_commits"] >= 1
        assert isinstance(s["wal_group_commit"]["batch_hist"], dict)
        assert "phase_profile" in s and "group_traffic" in s
        rings = s["rings"]["rings"]
        assert len(rings) == 2
        assert all(r["req_tail"] >= r["req_head"] for r in rings)
    finally:
        if rs is not None:
            rs.stop()
        node.stop()


def test_workers_trace_merge_multiprocess(tmp_path):
    """--fused --workers 2 --trace: the engine's GET /trace is ONE
    merged Perfetto timeline carrying spans from all three pids (the
    engine plus both worker processes), and the prom exposition works
    through a worker's ring facade."""
    from raftsql_tpu.api.client import RaftSQLClient

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
         "--workers", "2", "--groups", "2", "--port", str(port),
         "--tick", "0.004", "--trace"],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    client = RaftSQLClient([port], timeout_s=10)

    def healthz_fresh_conn():
        # A FRESH connection per request: SO_REUSEPORT hashes the
        # 4-tuple, so new ephemeral ports spread across both workers.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/healthz")
            assert conn.getresponse().status == 200
        finally:
            conn.close()

    try:
        client.wait_healthy(0, deadline_s=90)
        for g in range(2):
            client.put("CREATE TABLE t (v text)", group=g,
                       deadline_s=60)
        for i in range(10):
            client.put(f"INSERT INTO t (v) VALUES ('w{i}')",
                       group=i % 2, deadline_s=30)
        for _ in range(15):
            healthz_fresh_conn()
        # Segment flush cadence is 0.5 s after a completion batch:
        # wait it out, then drive one more round so both workers flush
        # everything above.
        time.sleep(0.8)
        for _ in range(15):
            healthz_fresh_conn()
        status, _, text = client.raw(0, "GET", "/trace")
        assert status == 200
        doc = json.loads(text)
        validate_chrome_trace(doc)
        evs = doc["traceEvents"]
        worker_pids = {e["pid"] for e in evs
                       if e.get("ph") == "M"
                       and e.get("name") == "process_name"
                       and "http worker" in e["args"].get("name", "")}
        assert len(worker_pids) == 2, worker_pids
        for pid in worker_pids:
            assert any(e.get("pid") == pid and e.get("ph") == "X"
                       for e in evs), f"no spans from worker pid {pid}"
        # Engine-side tracks on the same timeline: proposal spans
        # (pid 1) and the profiler's phase tracks (pid 4).
        assert any(e.get("pid") == 1 and e.get("ph") == "X"
                   for e in evs)
        assert any(e.get("pid") == 4 and e.get("ph") == "X"
                   for e in evs)
        # Prom exposition through a worker's RingClient facade.
        status, _, prom = client.raw(0, "GET", "/metrics?format=prom")
        assert status == 200
        _load_check_prom().parse_prom(prom)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        client.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


# -- flight recorder ---------------------------------------------------

def test_flight_recorder_dumps_on_invariant_failure(tmp_path,
                                                    monkeypatch):
    """A chaos run that trips an invariant must leave a post-mortem
    artifact holding BOTH planes: device-plane tick events and
    host-plane spans."""
    from raftsql_tpu.chaos.invariants import InvariantViolation
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner
    from raftsql_tpu.chaos.schedule import ChaosSchedule

    monkeypatch.setenv("RAFTSQL_FLIGHT_DIR", str(tmp_path / "flights"))
    sched = ChaosSchedule(seed=7, ticks=60)
    runner = FusedChaosRunner(sched, str(tmp_path / "data"))
    # Poison the commit-monotonicity matrix MID-run (after elections and
    # real traffic, so the trace has history): the next observation
    # reads as a regression — a forced invariant failure.
    orig_observe = FusedChaosRunner._observe

    def poisoned(self, t):
        if t == 40:
            self.monotonic._hi[:, :] = 10 ** 6
        orig_observe(self, t)

    monkeypatch.setattr(FusedChaosRunner, "_observe", poisoned)
    with pytest.raises(InvariantViolation):
        runner.run()
    path = tmp_path / "flights" / "flight-fused-seed7.json"
    assert path.exists()
    doc = json.loads(path.read_text())
    assert "commit regressed" in doc["reason"]
    assert doc["meta"]["schedule_digest"] == sched.digest()
    rows = doc["device_events"]
    assert rows, "flight dump must carry device-plane tick events"
    assert set(EVENT_FIELDS) - {"tick"} <= set(rows[-1])
    spans = doc["host_spans"]["spans"]
    assert spans, "flight dump must carry host-plane spans"
    assert any("commit" in s["phases"] for s in spans)


def test_chaos_runs_remain_deterministic_with_tracing(tmp_path):
    """Tracing is an observer: two runs of one seed must still produce
    identical schedule AND result digests (the `make chaos` gate)."""
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner
    from raftsql_tpu.chaos.schedule import generate

    sched = generate(11, ticks=100)
    reports = []
    for i in range(2):
        d = tmp_path / f"run{i}"
        os.makedirs(d)
        reports.append(FusedChaosRunner(sched, str(d)).run())
    assert reports[0]["schedule_digest"] == reports[1]["schedule_digest"]
    assert reports[0]["result_digest"] == reports[1]["result_digest"]
