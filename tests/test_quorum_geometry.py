"""Quorum geometry: flexible quorums + witness peers (PR 17).

Pins the whole geometry contract at every layer it crosses:

  * config.py validation — the intersection invariants W + E > N and
    2E > N are refused at construction (FPaxos §3: a leader's election
    quorum must overlap every committed write's quorum), witness slots
    are range/duplicate/voter-checked, and `unsafe_quorum_geometry` is
    the only way past (the chaos falsification harness needs it).
  * ops/quorum.py sized kernels — `mask_threshold` applies an explicit
    size ONLY to a full mask; a reduced mask (mid membership change)
    falls back to its own majority, because the explicit size was
    validated against all P slots and carries no intersection
    guarantee over a subset.
  * the fused runtime — a witness votes, appends and fsyncs (its WAL
    stream is real, `witness_appends` counts it) but never campaigns,
    never leads, never publishes a commit stream, and is refused as a
    leadership-transfer target.  SIGKILL-equivalent restart replays
    its WAL for votes/terms/log and still publishes NOTHING.
  * RaftDB — a witness replica never invokes the SQLite factory (no
    shard file or directory is ever created), refuses every read up
    front, and after a restart its WAL vote keeps the cluster writable
    when a full voter dies (2 of 3 = leader + witness).
  * membership/manager.py — a conf change that would re-open a
    non-intersecting geometry, or leave only witness voters, is
    refused across BOTH joint halves.
  * placement + reshard — witnesses are never nominated as transfer
    destinations and migrate-to-witness is a typed refusal.
  * jit-stability — the quorum chaos family (partitions, crashes,
    skew, witness cluster) feeds ONE trace of the fused step.
"""
import dataclasses
import os
import tempfile
import time

import numpy as np
import pytest

from raftsql_tpu.config import RaftConfig

TIMEOUT = 30.0


# -- config validation --------------------------------------------------


def _cfg(**kw):
    kw.setdefault("num_groups", 1)
    kw.setdefault("num_peers", 3)
    kw.setdefault("tick_interval_s", 0.0)
    return RaftConfig(**kw)


def test_config_rejects_non_intersecting_write_election():
    with pytest.raises(ValueError, match="must exceed num_peers"):
        _cfg(write_quorum=1, election_quorum=2)
    with pytest.raises(ValueError, match="non-intersecting"):
        _cfg(num_peers=5, write_quorum=2, election_quorum=3)


def test_config_rejects_disjoint_election_quorums():
    # W + E > N alone is not enough: terms are shared, so two election
    # quorums must intersect too (else two candidates win one term).
    with pytest.raises(ValueError, match="2 \\* election_quorum"):
        _cfg(write_quorum=3, election_quorum=1)


def test_config_rejects_out_of_range_sizes():
    with pytest.raises(ValueError, match="write_quorum must be in"):
        _cfg(write_quorum=0, election_quorum=3)
    with pytest.raises(ValueError, match="election_quorum must be in"):
        _cfg(write_quorum=3, election_quorum=4)


def test_config_unsafe_flag_is_the_only_bypass():
    c = _cfg(write_quorum=1, election_quorum=2,
             unsafe_quorum_geometry=True)
    assert c.write_size == 1 and c.election_size == 2
    assert not c.default_geometry


def test_config_default_geometry_flag():
    assert _cfg().default_geometry
    assert _cfg().write_size == 2 and _cfg().election_size == 2
    # Explicit majority sizes are VALID but not the default-geometry
    # fast path: the flag keys the digest-pinned static kernels.
    c = _cfg(write_quorum=2, election_quorum=2)
    assert not c.default_geometry
    assert c.write_size == 2 and c.election_size == 2


def test_config_witness_validation():
    with pytest.raises(ValueError, match="out of peer-slot range"):
        _cfg(witnesses=(3,))
    with pytest.raises(ValueError, match="duplicates"):
        _cfg(witnesses=(2, 2))
    with pytest.raises(ValueError, match="must be voters"):
        _cfg(initial_voters=(0, 1), witnesses=(2,))
    with pytest.raises(ValueError, match="non-witness"):
        _cfg(witnesses=(0, 1, 2))
    c = _cfg(witnesses=(2,))
    assert c.witness_set == frozenset({2})
    assert not c.default_geometry


# -- sized quorum kernels (ops/quorum.py) --------------------------------


def test_mask_threshold_full_mask_takes_explicit_size():
    import jax.numpy as jnp
    from raftsql_tpu.ops.quorum import mask_majority, mask_threshold

    full = jnp.ones((4, 5), bool)
    assert (mask_threshold(full, None)
            == mask_majority(full)).all()          # None == majority
    for size in range(1, 6):
        assert (mask_threshold(full, size) == size).all()


def test_mask_threshold_reduced_mask_falls_back_to_majority():
    import jax.numpy as jnp
    from raftsql_tpu.ops.quorum import mask_threshold

    # Popcount 2 of 3: the explicit size was validated against 3 slots
    # and guarantees nothing over a 2-slot subset — majority (2) wins.
    m = jnp.array([[True, True, False]])
    for size in (1, 2, 3):
        assert int(mask_threshold(m, size)[0]) == 2
    # Empty mask: threshold 1, which a masked tally of 0 never reaches.
    assert int(mask_threshold(jnp.zeros((1, 3), bool), 1)[0]) == 1


def test_masked_vote_win_with_explicit_size():
    import jax.numpy as jnp
    from raftsql_tpu.ops.quorum import masked_vote_win

    full = jnp.ones((1, 3), bool)
    two = jnp.array([[True, True, False]])
    one = jnp.array([[True, False, False]])
    # E=2 on a full 3-mask: two votes win, one loses.
    assert bool(masked_vote_win(two, full, full, 2)[0])
    assert not bool(masked_vote_win(one, full, full, 2)[0])
    # E=1 (unsafe harness geometry): a single vote wins.
    assert bool(masked_vote_win(one, full, full, 1)[0])
    # Joint config: BOTH masks must reach the threshold.
    joint = jnp.array([[False, True, True]])       # C_old = {1, 2}
    assert not bool(masked_vote_win(one, full, joint, 1)[0])


def test_masked_quorum_match_index_with_explicit_size():
    import jax.numpy as jnp
    from raftsql_tpu.ops.quorum import masked_quorum_match_index

    match = jnp.array([[5, 3, 1]], dtype=jnp.int32)
    full = jnp.ones((1, 3), bool)
    assert int(masked_quorum_match_index(match, full, None)[0]) == 3
    assert int(masked_quorum_match_index(match, full, 1)[0]) == 5
    assert int(masked_quorum_match_index(match, full, 2)[0]) == 3
    assert int(masked_quorum_match_index(match, full, 3)[0]) == 1


# -- fused runtime: witness behavior ------------------------------------


def _wcfg(groups=2):
    return RaftConfig(num_groups=groups, num_peers=3, log_window=32,
                      max_entries_per_msg=4, tick_interval_s=0.0,
                      witnesses=(2,))


def _elect(node, max_ticks=200):
    for t in range(max_ticks):
        node.tick()
        if t > 10 and (node._hints >= 0).all():
            return
    raise AssertionError("no full leadership within budget")


def _drain(node, peer):
    from raftsql_tpu.runtime.db import _expand_commit_item
    out, sentinels = [], 0
    q = node.commit_q(peer)
    while True:
        try:
            item = q.get_nowait()
        except Exception:
            break
        if item is None:
            sentinels += 1
            continue
        out.extend(_expand_commit_item(item))
    return out, sentinels


def test_fused_witness_votes_appends_never_leads_never_publishes(
        tmp_path):
    from raftsql_tpu.runtime.fused import FusedClusterNode
    from raftsql_tpu.runtime.node import TransferRefused

    cfg = _wcfg()
    node = FusedClusterNode(cfg, str(tmp_path))
    try:
        _elect(node)
        assert (np.asarray(node._hints) != 2).all(), \
            "witness slot 2 won an election"
        for p in range(3):
            _drain(node, p)
        for g in range(cfg.num_groups):
            node.propose_many(g, [f"SET k{i} g{g}".encode()
                                  for i in range(8)])
        for _ in range(40):
            node.tick()
            assert (np.asarray(node._hints) != 2).all()
        # Full voters see identical commit streams; the witness's
        # publish queue stays EMPTY (it has no apply plane) even
        # though its WAL appended every entry.
        s0, _ = _drain(node, 0)
        s1, _ = _drain(node, 1)
        sw, _ = _drain(node, 2)
        assert len(s0) == cfg.num_groups * 8
        # Per-group total order matches (cross-group interleave is
        # unordered by design — each group is its own raft).
        for g in range(cfg.num_groups):
            assert [(i, q) for (gg, i, q) in s0 if gg == g] \
                == [(i, q) for (gg, i, q) in s1 if gg == g]
        assert sw == []
        assert node.metrics.witness_appends >= cfg.num_groups * 8
        # Not a legal transfer destination either.
        with pytest.raises(TransferRefused, match="witness"):
            node.transfer_leadership(0, 2)
    finally:
        node.stop()


def test_fused_witness_restart_replays_wal_publishes_nothing(tmp_path):
    """SIGKILL-equivalent restart of the whole fused cluster: the
    witness's WAL replay restores its vote/term/log (the cluster
    re-elects and keeps committing over it) but re-publishes NOTHING —
    the boot-replay path must skip the witness exactly like the live
    publish path does."""
    from raftsql_tpu.runtime.fused import FusedClusterNode

    cfg = _wcfg(groups=1)
    node = FusedClusterNode(cfg, str(tmp_path))
    _elect(node)
    _drain(node, 0)
    node.propose_many(0, [f"SET k{i} v{i}".encode() for i in range(6)])
    for _ in range(30):
        node.tick()
    live, _ = _drain(node, 0)
    assert len(live) == 6
    node.stop()
    # The witness's WAL stream is real bytes on disk (slot 2 -> p3).
    wdir = os.path.join(str(tmp_path), "p3")
    assert any(os.path.getsize(os.path.join(r, f))
               for r, _, fs in os.walk(wdir) for f in fs), \
        "witness WAL dir is empty — nothing was made durable"

    node2 = FusedClusterNode(cfg, str(tmp_path))
    try:
        # Full voters replay the committed prefix; the witness's
        # replayed commits are cursor-advanced, never enqueued.
        rep, sent = _drain(node2, 0)
        assert sent == 1 and [q for (_, _, q) in rep] \
            == [q for (_, _, q) in live]
        repw, _ = _drain(node2, 2)
        assert repw == []
        _elect(node2)
        assert (np.asarray(node2._hints) != 2).all()
        node2.propose_many(0, [b"SET post 1"])
        for _ in range(30):
            node2.tick()
        post, _ = _drain(node2, 0)
        assert [q for (_, _, q) in post] == ["SET post 1"]
        assert node2.metrics.witness_appends > 0
    finally:
        node2.stop()


# -- RaftDB: the witness owns no SQLite shard ----------------------------


def test_raftdb_witness_no_shard_no_reads_survives_voter_loss(tmp_path):
    """Lockstep 3-node cluster (RaftPipe + loopback) with slot 2 a
    witness: the SQLite factory is NEVER invoked on it (no shard file
    ever exists), reads are refused up front, and after a witness
    restart its replayed WAL vote keeps the cluster writable when a
    full voter dies (leader + witness = write quorum 2 of 3)."""
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import LoopbackHub, \
        LoopbackTransport

    tick = 0.005
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=tick,
                     election_ticks=10, log_window=64,
                     max_entries_per_msg=4, witnesses=(2,))
    hub = LoopbackHub()
    factory_calls = []

    def mk(i):
        def factory(g, _i=i):
            path = os.path.join(str(tmp_path), f"shard-{_i}.db")
            factory_calls.append(_i)
            return SQLiteStateMachine(path)
        pipe = RaftPipe.create(
            i + 1, 3, cfg, LoopbackTransport(hub),
            data_dir=os.path.join(str(tmp_path), f"raftsql-{i + 1}"))
        return RaftDB(factory, pipe, num_groups=1)

    dbs = [mk(i) for i in range(3)]
    try:
        assert dbs[2].witness_self and not dbs[0].witness_self
        err = dbs[0].propose(
            "CREATE TABLE t (id int primary key asc, v text)"
        ).wait(TIMEOUT)
        assert err is None, err
        assert dbs[0].propose(
            'INSERT INTO t (v) VALUES ("a")').wait(TIMEOUT) is None
        # Full voters serve; the witness refuses every read up front
        # and never created a shard.
        deadline = time.monotonic() + TIMEOUT
        while '|a|' not in dbs[0].query("SELECT v FROM t"):
            assert time.monotonic() < deadline
            time.sleep(tick)
        with pytest.raises(ValueError, match="serves no reads"):
            dbs[2].query("SELECT v FROM t")
        assert 2 not in factory_calls
        assert not os.path.exists(
            os.path.join(str(tmp_path), "shard-2.db"))
        assert dbs[2].metrics()["quorum"] == {
            "write_size": 2, "election_size": 2, "witnesses": 1}
        assert dbs[2].health_doc()["witness"] is True

        # Witness SIGKILL + restart: replayed WAL, still no shard.
        dbs[2].close()
        dbs[2] = mk(2)
        assert 2 not in factory_calls
        # Kill a FULL voter: the remaining quorum is leader + witness,
        # so every further ack proves the restarted witness is voting
        # and appending off its replayed hard state.
        dbs[1].close()
        dbs[1] = None
        deadline = time.monotonic() + TIMEOUT
        while True:
            try:
                e = dbs[0].propose(
                    'INSERT INTO t (v) VALUES ("post")').wait(5.0)
            except TimeoutError as exc:     # election still settling
                e = exc
            if e is None:
                break
            assert time.monotonic() < deadline, e
            time.sleep(10 * tick)
        assert dbs[2].pipe.node.metrics.witness_appends > 0
        assert not os.path.exists(
            os.path.join(str(tmp_path), "shard-2.db"))
    finally:
        for db in dbs:
            if db is not None:
                db.close()


# -- membership: geometry re-validated across joint halves ---------------


def test_membership_change_cannot_reopen_intersection_hole():
    from raftsql_tpu.membership.manager import (MembershipError,
                                                MembershipManager)

    # Boot voters {0, 1}: a 2-slot mask uses its own majority (2, 2),
    # so the explicit W=1/E=2 is dormant and the boot geometry is
    # safe.  Promoting slot 2 makes the mask FULL — the explicit
    # sizes activate and W + E <= N would lose committed writes.
    def promote_third(mm):
        entry = mm.make_change(0, "add", 2)     # learner first
        mm.apply(0, 1, entry)
        return mm.make_change(0, "promote", 2)

    mm = MembershipManager(3, 1, initial_voters=(0, 1),
                           write_quorum=1, election_quorum=2)
    with pytest.raises(MembershipError, match="non-intersecting"):
        promote_third(mm)
    # The chaos harness's explicit bypass is honored here too.
    mm2 = MembershipManager(3, 1, initial_voters=(0, 1),
                            write_quorum=1, election_quorum=2,
                            unsafe_geometry=True)
    assert promote_third(mm2)


def test_membership_change_cannot_leave_only_witness_voters():
    from raftsql_tpu.membership.manager import (MembershipError,
                                                MembershipManager)

    mm = MembershipManager(3, 1, witnesses=(1, 2))
    with pytest.raises(MembershipError, match="only witness voters"):
        mm.make_change(0, "remove", 0)
    # Removing a witness voter is fine: {0, 1} still has an applier.
    assert mm.make_change(0, "remove", 2)


# -- placement + reshard: witnesses are never destinations ---------------


class _FakeEngine:
    def __init__(self, leaders, rates, witnesses):
        from raftsql_tpu.utils.metrics import GroupTraffic
        self.cfg = RaftConfig(num_groups=len(leaders), num_peers=3,
                              tick_interval_s=0.0, witnesses=witnesses)
        self.traffic = GroupTraffic(len(leaders), alpha=1.0)
        for g, n in enumerate(rates):
            self.traffic.add_propose(g, n)
        self.traffic._last_t -= 1.0       # one whole EWMA window
        self.leaders = list(leaders)
        self.transfers = []

    def leader_of(self, g):
        return self.leaders[g]

    def transfer_leadership(self, g, target):
        self.transfers.append((g, target))


def test_placement_never_nominates_a_witness_target():
    from raftsql_tpu.placement.controller import PlacementController

    # Peer 2 (the witness) leads nothing — it would be the coldest
    # slot by load, but it can never lead, so the mover must pick the
    # coldest FULL voter (peer 1) instead.
    eng = _FakeEngine(leaders=[0, 0, 1, 1], rates=[60, 40, 8, 0],
                      witnesses=(2,))
    pc = PlacementController(eng, imbalance=2.0, min_rate=1.0)
    d = pc.evaluate()
    assert d is not None and eng.transfers == [(1, 1)]
    assert all(t != 2 for (_, t) in eng.transfers)


def test_placement_all_witness_cold_side_skips_pass():
    from raftsql_tpu.placement.controller import PlacementController

    # Every non-hot slot is a witness: there is no legal destination,
    # so the pass issues nothing rather than burning refusals.
    eng = _FakeEngine(leaders=[0, 0], rates=[50, 30],
                      witnesses=(1, 2))
    pc = PlacementController(eng, imbalance=2.0, min_rate=1.0)
    assert pc.evaluate() is None
    assert eng.transfers == []


def test_reshard_refuses_migrate_to_witness():
    from raftsql_tpu.reshard.coordinator import (ReshardCoordinator,
                                                 ReshardRefused)
    from raftsql_tpu.reshard.keymap import KeyMap

    class _Backend:
        def journal(self, group, rec):
            pass

        def publish(self, km):
            pass

    coord = ReshardCoordinator(_Backend(), KeyMap.initial(2, 8),
                               num_groups=2, witness_peers=(1,))
    with pytest.raises(ReshardRefused, match="witness"):
        coord.enqueue("migrate", 0, 1)
    # A full-voter destination is accepted (refusal is typed, not a
    # blanket migrate ban).
    assert coord.enqueue("migrate", 0, 0) >= 1


def test_build_fused_node_with_witness(tmp_path, monkeypatch):
    """The --fused deployment with `--witness 2`: real SQL stack on a
    2-voter+1-witness group — writes ack on W=2 (leader + either
    remaining stream), reads serve, the geometry shows in /metrics,
    and the witness banked real WAL appends.  Slot 0 is the fused
    apply stream and is refused as a witness."""
    monkeypatch.chdir(tmp_path)
    from raftsql_tpu.server.main import build_fused_node

    rdb = build_fused_node(groups=1, peers=3, tick=0.002,
                           witnesses=(2,))
    try:
        assert rdb.propose("CREATE TABLE t (v text)",
                           0).wait(30) is None
        assert rdb.propose("INSERT INTO t (v) VALUES ('x')",
                           0).wait(30) is None
        assert rdb.query("SELECT v FROM t", 0) == "|x|\n"
        assert rdb.metrics()["quorum"] == {
            "write_size": 2, "election_size": 2, "witnesses": 1}
        assert rdb.pipe.node.metrics.witness_appends > 0
    finally:
        rdb.close()
    with pytest.raises(ValueError, match="slot 0"):
        build_fused_node(groups=1, peers=3, witnesses=(0,))


def test_client_read_rotation_skips_witnesses():
    """The front router (api/client.py): a witness answers every read
    with 400 — a terminal answer, not a retry — so the read rotation
    must drop known witnesses, while writes (forwarded like any
    follower) and an explicitly pinned node keep the full rotation."""
    from raftsql_tpu.api.client import RaftSQLClient

    c = RaftSQLClient([9001, 9002, 9003])
    c._witness = {2}
    for _ in range(6):                     # every round-robin phase
        assert 2 not in c._order(0, None, for_read=True)
        assert sorted(c._order(0, None)) == [0, 1, 2]   # writes
    assert c._order(0, 2, for_read=True) == [2]         # pinned
    # Fail open if (misconfigured) every node were a witness: an
    # empty rotation would turn one bad sweep into total blindness.
    c._witness = {0, 1, 2}
    assert sorted(c._order(0, None, for_read=True)) == [0, 1, 2]
    c.close()


# -- jit-stability: the quorum family feeds one trace --------------------


def test_tripwire_single_compile_quorum_family():
    """The quorum nemesis (flexible geometry + witness cluster under
    partitions/crashes/skew) compiles the fused step exactly once —
    the geometry is a static config constant, so masked thresholds and
    witness gates must never add a retrace on the chaos path."""
    from raftsql_tpu.analysis.tripwire import JitTripwire
    from raftsql_tpu.chaos.scenarios import QuorumChaosRunner
    from raftsql_tpu.chaos.schedule import generate_quorum

    plan = dataclasses.replace(generate_quorum(3), ticks=120)
    tw = JitTripwire()
    with tempfile.TemporaryDirectory(prefix="raftlint-twq-") as d:
        QuorumChaosRunner(plan, d).run()
    compiles = tw.compiles()
    warm = tw.baseline("cluster_step_host") or 0
    assert compiles.get("cluster_step_host") in \
        ({0, 1} if warm else {1}), compiles
    assert tw.offenders(limit=1) == {}, compiles
