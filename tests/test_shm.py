"""The shared-memory snapshot plane (runtime/shm.py) — PR 12's
zero-round-trip read path.

Covers the fail-closed contract from every angle the nemesis can't
reach deterministically:
  - publisher → reader round trips for every read mode, including the
    lease-gated linear fast path;
  - the seqlock: a writer parked inside its critical section makes
    readers fall back (never serve torn state), and a concurrent
    publish/read storm never yields a row count that goes backwards;
  - epoch pinning: an engine crash/restart re-creates the region under
    a fresh epoch and the OLD mapping permanently fails closed — at
    the RingClient level that means the ring path silently takes over;
  - log overflow and an unserializable group both fail the WHOLE plane
    closed rather than serve a truncated delta stream;
  - pre-start deltas buffer until the base images open the log, so a
    replica can never replay a stream whose prefix it is missing;
  - batched ReadIndex (runtime/node.py read_join): concurrent linear
    reads on the distributed runtime share quorum rounds, and the
    batch metrics attribute them.
"""
import os
import threading
import time

import pytest

from raftsql_tpu.runtime.shm import (DEFAULT_BYTES, ShmSnapshotPublisher,
                                     ShmSnapshotReader)

TIMEOUT = 30.0


def _mk_pair(tmp, groups=1, size=None):
    pub = ShmSnapshotPublisher(str(tmp), num_groups=groups, size=size)
    pub.start(lambda g: None, lambda g: 0)
    rdr = ShmSnapshotReader(str(tmp))
    return pub, rdr


SCHEMA = "CREATE TABLE t (k INTEGER PRIMARY KEY, v TEXT)"


# -- round trips ------------------------------------------------------------


def test_local_and_session_roundtrip(tmp_path):
    pub, rdr = _mk_pair(tmp_path)
    try:
        pub.publish_deltas({0: [(SCHEMA, 1)]})
        pub.publish_deltas({0: [(f"INSERT INTO t VALUES ({k}, 'v{k}')",
                                 k + 2) for k in range(5)]})
        got = rdr.try_read("local", 0, "SELECT count(*) FROM t")
        assert got is not None
        rows, wm = got
        assert rows.strip() == "|5|" and wm == 6
        # Session at a covered watermark serves; an uncovered one MUST
        # fall back (the engine blocks for the watermark, we can't).
        assert rdr.try_read("session", 0, "SELECT count(*) FROM t",
                            watermark=6) is not None
        assert rdr.try_read("session", 0, "SELECT count(*) FROM t",
                            watermark=7) is None
        # Unknown mode / out-of-range group: fail closed, not raise.
        assert rdr.try_read("weird", 0, "SELECT 1") is None
        assert rdr.try_read("local", 3, "SELECT 1") is None
        # SQL errors surface through the authoritative ring path.
        assert rdr.try_read("local", 0, "SELECT boom FROM missing") is None
        # Non-SELECT must fall back for the engine's 400 — and must NOT
        # mutate the worker-side replica on the way.
        assert rdr.try_read("local", 0, "DELETE FROM t") is None
        got = rdr.try_read("local", 0, "SELECT count(*) FROM t")
        assert got is not None and got[0].strip() == "|5|"
    finally:
        rdr.close()
        pub.close()


def test_follower_and_linear_gates(tmp_path):
    """follower needs applied >= commit; linear additionally needs a
    live published lease and a fresh publisher heartbeat."""
    pub, rdr = _mk_pair(tmp_path)
    try:
        pub.publish_deltas({0: [(SCHEMA, 1), ("INSERT INTO t VALUES "
                                              "(1, 'a')", 2)]})
        # Commit column still 0: a follower read serves at watermark 0,
        # where the replica has no table yet — SQL error → fall back.
        # No lease yet → linear falls back too.
        assert rdr.try_read("follower", 0, "SELECT count(*) FROM t") is None
        assert rdr.try_read("linear", 0, "SELECT count(*) FROM t") is None
        # Stamp commit + a live lease the way the RingServer refresh
        # thread does; linear now serves.
        pub.refresh(lambda g: 2, lambda g: 0,
                    lambda g: time.monotonic() + 0.05)
        got = rdr.try_read("linear", 0, "SELECT count(*) FROM t")
        assert got is not None and got[0].strip() == "|1|"
        assert rdr.try_read("follower", 0, "SELECT count(*) FROM t") \
            is not None
        assert rdr.leader_of(0) == 1
        # Linearizability across the refresh window: a write applied
        # (and thus acked — publish_deltas runs before acks) but whose
        # commit column the ~2ms refresh thread hasn't restamped yet
        # MUST be visible to a linear read.  Serving at the stale
        # commit column here would drop an acked PUT.
        pub.refresh(lambda g: 2, lambda g: 0,
                    lambda g: time.monotonic() + 5.0)
        pub.publish_deltas({0: [("INSERT INTO t VALUES (2, 'b')", 3)]})
        got = rdr.try_read("linear", 0, "SELECT count(*) FROM t")
        assert got is not None and got[0].strip() == "|2|"
        # An expired lease fails closed again.
        pub.refresh(lambda g: 2, lambda g: 0, lambda g: 0.0)
        assert rdr.try_read("linear", 0, "SELECT count(*) FROM t") is None
        # Commit ahead of applied: follower can't prove freshness.
        pub.refresh(lambda g: 99, lambda g: 0,
                    lambda g: time.monotonic() + 0.05)
        assert rdr.try_read("follower", 0, "SELECT 1") is None
        assert rdr.try_read("linear", 0, "SELECT 1") is None
    finally:
        rdr.close()
        pub.close()


# -- seqlock ----------------------------------------------------------------


def test_seqlock_writer_in_critical_fails_closed(tmp_path):
    """A writer parked mid-critical-section (odd seq) makes readers
    fall back after bounded retries — never serve possibly-torn state
    — and the reader recovers as soon as the write completes."""
    pub, rdr = _mk_pair(tmp_path)
    try:
        pub.publish_deltas({0: [(SCHEMA, 1)]})
        assert rdr.try_read("local", 0, "SELECT count(*) FROM t") \
            is not None
        with pub._lock:
            pub._seq += 1                        # odd: "mid-update"
            pub._write_header(time.monotonic_ns())
        assert rdr.try_read("local", 0, "SELECT count(*) FROM t") is None
        with pub._lock:
            pub._seq += 1                        # even: consistent
            pub._write_header(time.monotonic_ns())
        assert rdr.try_read("local", 0, "SELECT count(*) FROM t") \
            is not None
    finally:
        rdr.close()
        pub.close()


def test_seqlock_concurrent_publish_read_storm(tmp_path):
    """Reads racing a continuously-publishing writer: every successful
    read parses and the observed row count never goes backwards (the
    seqlock retry path, exercised for real)."""
    pub, rdr = _mk_pair(tmp_path)
    try:
        pub.publish_deltas({0: [(SCHEMA, 1)]})
        stop = threading.Event()
        state = {"n": 0}

        def writer():
            while not stop.is_set():
                k = state["n"]
                pub.publish_deltas(
                    {0: [(f"INSERT INTO t VALUES ({k}, 'v')", k + 2)]})
                state["n"] = k + 1
        th = threading.Thread(target=writer, daemon=True)
        th.start()
        last = 0
        hits = 0
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            got = rdr.try_read("local", 0, "SELECT count(*) FROM t")
            if got is None:
                continue
            n = int(got[0].strip().strip("|"))
            assert n >= last, (n, last)
            last = n
            hits += 1
        stop.set()
        th.join(5)
        assert hits > 0 and last > 0
    finally:
        rdr.close()
        pub.close()


# -- fail-closed hard states ------------------------------------------------


def test_epoch_change_permanently_kills_reader(tmp_path):
    """An engine restart re-creates the region under a fresh epoch: the
    old mapping must refuse to serve FOREVER (its replicas may hold
    state from the previous life), while a fresh mapping works."""
    pub, rdr = _mk_pair(tmp_path)
    pub.publish_deltas({0: [(SCHEMA, 1)]})
    assert rdr.try_read("local", 0, "SELECT 1") is not None
    pub.close()
    pub2 = ShmSnapshotPublisher(str(tmp_path), num_groups=1)
    pub2.start(lambda g: None, lambda g: 0)
    try:
        pub2.publish_deltas({0: [(SCHEMA, 1)]})
        assert rdr.try_read("local", 0, "SELECT 1") is None
        assert rdr._dead
        # ... and stays dead even though the region itself is valid.
        assert rdr.try_read("local", 0, "SELECT 1") is None
        rdr2 = ShmSnapshotReader(str(tmp_path))
        assert rdr2.try_read("local", 0, "SELECT 1") is not None
        rdr2.close()
    finally:
        rdr.close()
        pub2.close()


def test_log_overflow_fails_whole_plane_closed(tmp_path):
    """Once the append-only log is full the publisher flags the region
    and every reader goes dead — a truncated delta stream must never
    serve."""
    pub = ShmSnapshotPublisher(str(tmp_path), num_groups=1, size=1)
    pub.start(lambda g: None, lambda g: 0)     # min region: ~1 MiB log
    rdr = ShmSnapshotReader(str(tmp_path))
    try:
        big = "-- " + "x" * 600_000            # two of these overflow
        pub.publish_deltas({0: [(SCHEMA, 1)]})
        pub.publish_deltas({0: [(big, 2)]})
        assert not pub.log_full
        pub.publish_deltas({0: [(big, 3)]})
        assert pub.log_full
        assert rdr.try_read("local", 0, "SELECT 1") is None
        assert rdr._dead
    finally:
        rdr.close()
        pub.close()


def test_unserializable_applied_group_fails_closed(tmp_path):
    """A group with applied state but no base image would leave
    replicas a truncated stream — start() fails the whole plane."""
    pub = ShmSnapshotPublisher(str(tmp_path), num_groups=2)
    pub.start(lambda g: None, lambda g: 7 if g == 1 else 0)
    rdr = ShmSnapshotReader(str(tmp_path))
    try:
        assert pub.log_full
        assert rdr.try_read("local", 0, "SELECT 1") is None
    finally:
        rdr.close()
        pub.close()


def test_pre_start_deltas_buffer_until_log_opens(tmp_path):
    """Deltas published before start() (applies racing engine boot)
    flush AFTER the base images, in arrival order — the replica's
    stream prefix is always complete."""
    pub = ShmSnapshotPublisher(str(tmp_path), num_groups=1)
    pub.publish_deltas({0: [(SCHEMA, 1)]})
    pub.publish_deltas({0: [("INSERT INTO t VALUES (1, 'early')", 2)]})
    pub.start(lambda g: None, lambda g: 0)
    rdr = ShmSnapshotReader(str(tmp_path))
    try:
        got = rdr.try_read("local", 0, "SELECT v FROM t")
        assert got is not None and got[0].strip() == "|early|"
        assert got[1] == 2
    finally:
        rdr.close()
        pub.close()


def test_default_region_size_env_floor():
    assert DEFAULT_BYTES == 32 << 20


# -- RingClient integration: fast path + restart fallback -------------------


def _mk_rdb(tmp):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.fused import FusedClusterNode, FusedPipe

    cfg = RaftConfig(num_groups=2, num_peers=3, log_window=32,
                     max_entries_per_msg=4, tick_interval_s=0.0)
    node = FusedClusterNode(cfg, os.path.join(tmp, "data"))
    node.start(interval_s=0.0005)
    pipe = FusedPipe(node)

    def smf(g):
        return SQLiteStateMachine(os.path.join(tmp, f"g{g}.db"))

    return RaftDB(smf, pipe, num_groups=2)


def test_ring_client_shm_fastpath_and_restart_fallback(tmp_path):
    """The worker-side fast path serves local/session GETs from the
    mapping (hits counted, watermark echoed), and after a simulated
    engine restart (region re-created under a new epoch) the SAME
    client keeps answering correctly through the ring path."""
    from raftsql_tpu.runtime.ring import RingClient, RingServer

    rdb = _mk_rdb(str(tmp_path))
    ring_dir = str(tmp_path / "rings")
    srv = RingServer(rdb, ring_dir, workers=1)
    srv.start()
    rc = RingClient(ring_dir, 0)
    try:
        assert rc._shm is not None, "shm plane should attach"
        assert rc.propose("CREATE TABLE t (v text)").wait(30) is None
        assert rc.propose("INSERT INTO t (v) VALUES ('x')").wait(30) \
            is None
        wm = rc.watermark(0)
        assert wm > 0
        deadline = time.monotonic() + TIMEOUT
        while rc._shm_hits == 0 and time.monotonic() < deadline:
            assert rc.query("SELECT count(*) FROM t", mode="session",
                            watermark=wm).strip() == "|1|"
            time.sleep(0.005)
        assert rc._shm_hits > 0, "fast path never served"
        # Simulate the engine dying and restarting: the snapshot region
        # is re-created under a fresh epoch.  The client's mapping goes
        # permanently dead and every read silently takes the ring.
        pub2 = ShmSnapshotPublisher(ring_dir, num_groups=2)
        pub2.start(lambda g: None, lambda g: 0)
        before = rc._shm_fallbacks
        assert rc.query("SELECT count(*) FROM t", mode="local") \
            .strip() == "|1|"
        assert rc._shm_fallbacks > before
        assert rc._shm._dead
        pub2.close()
    finally:
        rc.close()
        srv.stop()
        rdb.close()


# -- batched ReadIndex (distributed runtime) --------------------------------


def test_batched_read_index_shares_rounds(tmp_path):
    """Concurrent linear reads at a lease-less leader ride the batched
    ReadIndex path: all succeed with read-your-writes, the batch
    counter attributes them, and a follower still refuses."""
    from raftsql_tpu.config import LEADER, RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import NotLeaderError, RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import (LoopbackHub,
                                                LoopbackTransport)

    hub = LoopbackHub()
    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=0.005,
                     election_ticks=10, log_window=64,
                     max_entries_per_msg=4)
    dbs = []
    for i in range(3):
        pipe = RaftPipe.create(
            i + 1, 3, cfg, LoopbackTransport(hub),
            data_dir=os.path.join(str(tmp_path), f"raftsql-{i + 1}"))
        dbs.append(RaftDB(
            lambda g, i=i: SQLiteStateMachine(
                os.path.join(str(tmp_path), f"db-{i}.db")),
            pipe, num_groups=1))
    try:
        assert dbs[0].propose("CREATE TABLE t (v text)").wait(TIMEOUT) \
            is None
        deadline = time.monotonic() + TIMEOUT
        lead = None
        while lead is None and time.monotonic() < deadline:
            for i, db in enumerate(dbs):
                if db.pipe.node._last_role[0] == LEADER:
                    lead = i
            time.sleep(0.02)
        assert lead is not None
        assert dbs[lead].propose(
            "INSERT INTO t (v) VALUES ('w')").wait(TIMEOUT) is None

        errs = []

        def rloop():
            try:
                for _ in range(3):
                    got = dbs[lead].query("SELECT count(*) FROM t",
                                          mode="linear", timeout=TIMEOUT)
                    assert got.strip() == "|1|", got
            except Exception as e:             # noqa: BLE001
                errs.append(e)
        threads = [threading.Thread(target=rloop, daemon=True)
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(TIMEOUT)
        assert not errs, errs
        m = dbs[lead].pipe.node.metrics
        # Every read went through the batcher (a read may re-join a
        # second round across a tick boundary, so >=, not ==).
        assert m.reads_read_index_batched >= 24
        assert m.reads_read_index >= 24
        # The hist stamps batch sizes at promote; a re-joined read
        # lands in two promoted batches but confirms once.
        assert sum(int(k) * v for k, v in m.read_batch_hist.items()) \
            >= m.reads_read_index_batched
        # A follower's read_join refuses (the db layer surfaces the
        # typed redirect).
        fol = (lead + 1) % 3
        assert dbs[fol].pipe.node.read_join(0) is None
        with pytest.raises(NotLeaderError):
            dbs[fol].query("SELECT 1", mode="linear", timeout=2.0)
    finally:
        for db in dbs:
            try:
                db.close()
            except Exception:                  # noqa: BLE001
                pass
