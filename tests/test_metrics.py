"""LatencyTimer (utils/metrics.py): ring wraparound, percentile edge
cases, and the record/percentile locking contract — percentile must
copy under the lock and sort OUTSIDE it, so a /metrics scrape can never
stall record() on the tick hot path."""
import math
import threading

from raftsql_tpu.utils.metrics import LatencyTimer


def test_empty_percentile_is_nan():
    t = LatencyTimer()
    assert math.isnan(t.percentile(0.5))
    assert all(math.isnan(v) for v in t.percentiles((0.0, 0.5, 1.0)))


def test_single_sample_every_quantile():
    t = LatencyTimer()
    t.record(0.25)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert t.percentile(q) == 0.25


def test_q_one_is_max_and_q_zero_is_min():
    t = LatencyTimer(cap=16)
    for v in (5.0, 1.0, 3.0, 2.0):
        t.record(v)
    assert t.percentile(0.0) == 1.0
    # q=1.0 indexes past the end without the clamp; must be the max.
    assert t.percentile(1.0) == 5.0


def test_ring_wraparound_past_cap_keeps_recent_samples():
    cap = 8
    t = LatencyTimer(cap=cap)
    for i in range(30):                       # 30 > 3 * cap
        t.record(float(i))
    assert len(t._samples) == cap
    # Ring semantics: only the newest `cap` samples survive, so the
    # minimum percentile can never reach the overwritten early values.
    assert t.percentile(0.0) >= 30 - cap
    assert t.percentile(1.0) == 29.0


def test_percentiles_one_snapshot_many_quantiles():
    t = LatencyTimer(cap=64)
    for i in range(50):
        t.record(float(i))
    p50, p95, p99 = t.percentiles((0.5, 0.95, 0.99))
    assert p50 == 25.0 and p95 == 47.0 and p99 == 49.0


def test_concurrent_record_and_percentile_smoke():
    """Writers hammer record() while readers take percentiles: no
    exception, no deadlock, and the ring stays bounded."""
    t = LatencyTimer(cap=128)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        try:
            while not stop.is_set():
                t.record(i * 1e-6)
                i += 1
        except Exception as e:                # noqa: BLE001
            errs.append(e)

    def reader():
        try:
            while not stop.is_set():
                t.percentiles((0.5, 0.95, 0.99))
        except Exception as e:                # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(2)] \
        + [threading.Thread(target=reader) for _ in range(2)]
    for th in threads:
        th.start()
    timer = threading.Timer(0.5, stop.set)
    timer.start()
    for th in threads:
        th.join(timeout=10)
    timer.cancel()
    assert not errs, errs[:1]
    assert not any(th.is_alive() for th in threads)
    assert len(t._samples) <= 128
    p = t.percentile(0.5)
    assert p == p                             # a real number by now


# ---------------------------------------------------------------------------
# GroupTraffic: the EWMA feed the placement controller balances on.
# Time is driven by rewinding _last_t (the clock the rate window uses),
# so windows are exact and the tests never sleep.


def _window(t, seconds=1.0):
    """Force one EWMA window of `seconds` onto the traffic object."""
    t._last_t -= seconds
    with t._mu:
        t._advance_rates_locked()


def test_group_traffic_ewma_decays_to_zero_when_idle():
    from raftsql_tpu.utils.metrics import GroupTraffic
    t = GroupTraffic(4, alpha=0.5)
    t.add_propose([1], [100])
    _window(t)
    hot = t._rate_p[1]
    assert hot > 0
    # The group goes idle: every further window sees zero new
    # proposals, so the EWMA must decay geometrically toward zero —
    # a placement controller keyed on stale heat would move leadership
    # of groups nobody writes to any more.
    prev = hot
    for _ in range(20):
        _window(t)
        assert t._rate_p[1] <= prev
        prev = t._rate_p[1]
    assert 0.0 <= t._rate_p[1] < hot * 1e-3
    # Untouched groups never acquire a rate at all.
    assert t._rate_p[0] == 0.0 and t._rate_p[2] == 0.0


def test_group_traffic_idle_group_total_still_listed():
    from raftsql_tpu.utils.metrics import GroupTraffic
    t = GroupTraffic(2, alpha=0.5)
    t.add_propose([0], [10])
    for _ in range(30):
        _window(t)
    # Rate has decayed to ~0 but the all-time total keeps the row in
    # the hot-groups table (volume history is still reportable).
    doc = t.doc()
    assert [r["group"] for r in doc["hot_groups"]] == [0]
    assert doc["hot_groups"][0]["propose_rate"] == 0.0
    assert doc["hot_groups"][0]["proposed"] == 10


def test_group_traffic_topk_ties_rank_by_group_id():
    from raftsql_tpu.utils.metrics import GroupTraffic
    t = GroupTraffic(8, top_k=8)
    # Four groups with IDENTICAL totals and no rate window yet: the
    # ranking must be deterministic (ascending group id on ties), not
    # an artifact of sort instability.
    t.add_propose([7, 2, 5, 1], [10, 10, 10, 10])
    ids = [r["group"] for r in t.doc()["hot_groups"]]
    assert ids == [1, 2, 5, 7]
    # Stable across repeated scrapes.
    assert ids == [r["group"] for r in t.doc()["hot_groups"]]


def test_group_traffic_topk_truncation_under_ties_is_stable():
    from raftsql_tpu.utils.metrics import GroupTraffic
    t = GroupTraffic(8, top_k=2)
    t.add_propose([3, 6, 4], [5, 5, 5])
    # k=2 must pick the same two of the three tied groups every time:
    # the lowest ids win.
    for _ in range(3):
        assert [r["group"] for r in t.doc()["hot_groups"]] == [3, 4]


def test_group_traffic_rate_breaks_total_ties():
    from raftsql_tpu.utils.metrics import GroupTraffic
    t = GroupTraffic(4, top_k=4, alpha=1.0)
    t.add_propose([0, 1], [10, 10])
    _window(t)                    # both groups: rate 10/s
    t.add_propose([1], [50])      # group 1 gets hot
    _window(t)
    ids = [r["group"] for r in t.doc()["hot_groups"]]
    assert ids[0] == 1            # rate-first ranking
