"""LatencyTimer (utils/metrics.py): ring wraparound, percentile edge
cases, and the record/percentile locking contract — percentile must
copy under the lock and sort OUTSIDE it, so a /metrics scrape can never
stall record() on the tick hot path."""
import math
import threading

from raftsql_tpu.utils.metrics import LatencyTimer


def test_empty_percentile_is_nan():
    t = LatencyTimer()
    assert math.isnan(t.percentile(0.5))
    assert all(math.isnan(v) for v in t.percentiles((0.0, 0.5, 1.0)))


def test_single_sample_every_quantile():
    t = LatencyTimer()
    t.record(0.25)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert t.percentile(q) == 0.25


def test_q_one_is_max_and_q_zero_is_min():
    t = LatencyTimer(cap=16)
    for v in (5.0, 1.0, 3.0, 2.0):
        t.record(v)
    assert t.percentile(0.0) == 1.0
    # q=1.0 indexes past the end without the clamp; must be the max.
    assert t.percentile(1.0) == 5.0


def test_ring_wraparound_past_cap_keeps_recent_samples():
    cap = 8
    t = LatencyTimer(cap=cap)
    for i in range(30):                       # 30 > 3 * cap
        t.record(float(i))
    assert len(t._samples) == cap
    # Ring semantics: only the newest `cap` samples survive, so the
    # minimum percentile can never reach the overwritten early values.
    assert t.percentile(0.0) >= 30 - cap
    assert t.percentile(1.0) == 29.0


def test_percentiles_one_snapshot_many_quantiles():
    t = LatencyTimer(cap=64)
    for i in range(50):
        t.record(float(i))
    p50, p95, p99 = t.percentiles((0.5, 0.95, 0.99))
    assert p50 == 25.0 and p95 == 47.0 and p99 == 49.0


def test_concurrent_record_and_percentile_smoke():
    """Writers hammer record() while readers take percentiles: no
    exception, no deadlock, and the ring stays bounded."""
    t = LatencyTimer(cap=128)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        try:
            while not stop.is_set():
                t.record(i * 1e-6)
                i += 1
        except Exception as e:                # noqa: BLE001
            errs.append(e)

    def reader():
        try:
            while not stop.is_set():
                t.percentiles((0.5, 0.95, 0.99))
        except Exception as e:                # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(2)] \
        + [threading.Thread(target=reader) for _ in range(2)]
    for th in threads:
        th.start()
    timer = threading.Timer(0.5, stop.set)
    timer.start()
    for th in threads:
        th.join(timeout=10)
    timer.cancel()
    assert not errs, errs[:1]
    assert not any(th.is_alive() for th in threads)
    assert len(t._samples) <= 128
    p = t.percentile(0.5)
    assert p == p                             # a real number by now
