"""The distributed runtime's event loop (runtime/node.py _run).

Covers step elision: interval-paced wakeups accumulate timer advance
without stepping while the device-reported timer_margin says no
election/heartbeat can fire, and the work event resumes full service
immediately — plus the replay/publish contract (committed prefix only).
"""
import queue
import time

from raftsql_tpu.config import NO_VOTE, RaftConfig
from raftsql_tpu.runtime.node import CLOSED, RaftNode
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport


def test_threaded_node_elides_idle_steps(tmp_path):
    """An idle threaded
    node with a coarse heartbeat runs far fewer steps than the tick
    interval allows — the device-reported timer_margin parks the loop —
    yet keeps serving when work arrives (the work event)."""
    cfg = RaftConfig(num_groups=1, num_peers=1, tick_interval_s=0.002,
                     election_ticks=60, heartbeat_ticks=25,
                     log_window=32, max_entries_per_msg=4)
    n = RaftNode(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                 data_dir=str(tmp_path / "n1"))
    n.start(threaded=True)
    try:
        deadline = time.monotonic() + 5
        while n.leader_of(0) < 0:
            assert time.monotonic() < deadline, "no self-election"
            time.sleep(0.01)
        n.metrics.ticks = 0
        time.sleep(1.0)
        idle_ticks = n.metrics.ticks
        # 1s / 2ms = 500 loop slots; a leader's margin is the heartbeat
        # countdown (25), so ~20 steps expected.  Allow generous slack
        # for CI scheduling; the pre-elision loop would run ~400+.
        assert idle_ticks <= 120, idle_ticks
        # Snapshot first: the new leader's no-op already counts as a
        # commit, so waiting for >= 1 would pass vacuously.
        base = n.metrics.commits
        n.propose(0, b"SET k v")
        deadline = time.monotonic() + 5
        while n.metrics.commits <= base:
            assert time.monotonic() < deadline, "proposal never committed"
            time.sleep(0.01)
    finally:
        n.stop()


def test_replay_publishes_only_committed_prefix(tmp_path):
    """fail-before/pass-after (found by the process-plane chaos seed
    sweep): a restarted node must NOT publish its appended-but-
    UNCOMMITTED WAL tail to the state machine — a new leader may
    conflict-truncate it, and the phantom apply would diverge this
    replica's SQLite forever (survivors can then never converge).  The
    replaced entry must instead arrive exactly once through the
    ordinary commit path."""
    from raftsql_tpu.runtime.db import _expand_commit_item
    from raftsql_tpu.storage.wal import WAL

    # Hand-crafted WALs: a shared committed entry at index 1; node 1
    # additionally appended "lost-write" at index 2 in term 1 but never
    # committed it, while the term-2 majority (nodes 2, 3) committed a
    # DIFFERENT entry there.
    def make_wal(d, tail_term, tail_sql, term, commit):
        w = WAL(str(d))
        w.append_entry(0, 1, 1, b"SET shared")
        w.append_entry(0, 2, tail_term, tail_sql)
        w.set_hardstate(0, term, NO_VOTE, commit)
        w.sync()
        w.close()

    make_wal(tmp_path / "n1", 1, b"SET lost-write", 1, 1)
    make_wal(tmp_path / "n2", 2, b"SET won-write", 2, 2)
    make_wal(tmp_path / "n3", 2, b"SET won-write", 2, 2)

    cfg = RaftConfig(num_groups=1, num_peers=3, tick_interval_s=0.002,
                     log_window=32, max_entries_per_msg=4)
    hub = LoopbackHub()
    nodes = [RaftNode(i + 1, 3, cfg, LoopbackTransport(hub),
                      data_dir=str(tmp_path / f"n{i + 1}"))
             for i in range(3)]
    published = []
    try:
        for n in nodes:
            n.start(threaded=True)
        deadline = time.monotonic() + 15
        while not any(s == "SET won-write" for (_, _, s) in published):
            assert time.monotonic() < deadline, published
            try:
                item = nodes[0].commit_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None or item is CLOSED:
                continue
            published.extend(_expand_commit_item(item, nodes[0]))
    finally:
        for n in nodes:
            n.stop()
    sqls = [s for (_, _, s) in published]
    assert "SET lost-write" not in sqls, sqls
    assert sqls.count("SET won-write") == 1, sqls
    # The committed prefix itself did replay.
    assert sqls[0] == "SET shared", sqls


def test_forward_reclaimed_when_follower_becomes_leader(tmp_path):
    """A proposal forwarded to a leader that died in the same instant
    must NOT sit in forward-limbo until the retry deadline when the
    proposing follower itself wins the next election: the new leader
    reclaims its own in-flight forwards immediately (envelope dedup
    makes the requeue safe).  Found by the process-plane read nemesis:
    the entry node's PUT stalled for the whole deadline while it was
    the leader that could have committed it."""
    from raftsql_tpu.config import LEADER
    from raftsql_tpu.runtime.db import _expand_commit_item

    cfg = RaftConfig(num_groups=1, num_peers=3, log_window=64,
                     max_entries_per_msg=4, election_ticks=10,
                     heartbeat_ticks=1, tick_interval_s=0.0)
    hub = LoopbackHub()
    nodes = [RaftNode(i + 1, 3, cfg, LoopbackTransport(hub),
                      str(tmp_path / f"n{i + 1}"))
             for i in range(3)]
    try:
        for n in nodes:
            n.start(threaded=False)
        lead = None
        for _ in range(300):
            for n in nodes:
                n.tick()
            lead = next((i for i, n in enumerate(nodes)
                         if n._last_role[0] == LEADER), None)
            if lead is not None and all(
                    n.leader_of(0) == lead for n in nodes):
                break
        assert lead is not None
        fwd = (lead + 1) % 3         # the proposing follower
        other = (lead + 2) % 3
        # Propose at the follower, then kill the leader BEFORE the
        # follower's next tick delivers anywhere useful: the forward
        # targets a dead node and is lost.
        nodes[fwd].propose(0, b"SET k reclaimed")
        from raftsql_tpu.chaos.scenarios import hard_crash_node
        hard_crash_node(nodes[lead])
        dead, nodes[lead] = nodes[lead], None
        # Bias the PROPOSING follower to win the next election (its
        # timers run 2x) — the reclaim-on-become-leader path.
        committed = {}
        for t in range(35):
            for i, n in enumerate(nodes):
                if n is None:
                    continue
                n.tick(timer_inc=2 if i == fwd else 1)
            while True:
                try:
                    item = nodes[fwd].commit_q.get_nowait()
                except queue.Empty:
                    break
                if item is None or item is CLOSED:
                    continue
                for (g, idx, sql) in _expand_commit_item(
                        item, nodes[fwd]):
                    committed[(g, idx)] = sql
            if "SET k reclaimed" in committed.values():
                break
        # Old behavior: the forward sat in limbo until the retry
        # deadline (4 * election_ticks = 40 ticks) — far beyond this
        # window.  With the reclaim, the new leader commits it right
        # after its election.
        assert "SET k reclaimed" in committed.values(), (
            f"forwarded proposal not reclaimed by the new leader "
            f"within 35 ticks (committed: {sorted(committed)})")
    finally:
        for n in nodes:
            if n is not None:
                n.stop()
        if dead is not None:
            dead.stop()


def test_replay_scrubs_duplicate_whose_first_copy_was_compacted(tmp_path):
    """fail-before/pass-after (found by the snapshot-family chaos seed
    sweep, seed 2): the dedup decision is a pure function of the
    committed log PREFIX — but compaction drops that prefix, so a
    restarted node replaying only the retained suffix used to re-apply
    a forward-retry duplicate whose first copy fell below the floor,
    while its live peers (in-memory windows intact) scrubbed it:
    permanent divergence.  The REC_DEDUP baseline persisted at the
    compaction boundary (storage/wal.py) must make replay scrub the
    same duplicates the live peers do."""
    from raftsql_tpu.runtime.envelope import wrap
    from raftsql_tpu.storage.wal import WAL

    DUP_PID = 42

    def make_wal(d, with_baseline):
        # Floor at 2: the duplicate's first copy (applied at index 1)
        # is gone.  The retained suffix holds its re-proposed copy at
        # index 3 plus an ordinary entry at 4; both are committed.
        w = WAL(str(d), native=False)
        w.mark_compact(0, 2, 1)
        if with_baseline:
            assert w.set_dedup(0, 2, [(1, DUP_PID)])
        w.append_entry(0, 3, 1, wrap(b"SET k stale-dup", pid=DUP_PID))
        w.append_entry(0, 4, 1, wrap(b"SET k fresh", pid=77))
        w.set_hardstate(0, 1, NO_VOTE, 4)
        w.sync()
        w.close()

    def replayed_sqls(d):
        cfg = RaftConfig(num_groups=1, num_peers=1,
                         tick_interval_s=0.002, log_window=32,
                         max_entries_per_msg=4)
        n = RaftNode(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                     data_dir=str(d))
        sqls = []
        try:
            n.start(threaded=False)
            while True:                 # replay ends with the sentinel
                item = n.commit_q.get(timeout=5)
                if item is None:
                    break
                sqls.append(item[2])
        finally:
            n.stop()
        return sqls

    make_wal(tmp_path / "bare", with_baseline=False)
    make_wal(tmp_path / "pinned", with_baseline=True)
    # Control: without the baseline the duplicate IS re-published —
    # proving the assertion below bites.
    assert replayed_sqls(tmp_path / "bare") == [
        "SET k stale-dup", "SET k fresh"]
    assert replayed_sqls(tmp_path / "pinned") == ["SET k fresh"]
