"""The distributed runtime's event loop (runtime/node.py _run).

Covers step elision: interval-paced wakeups accumulate timer advance
without stepping while the device-reported timer_margin says no
election/heartbeat can fire, and the work event resumes full service
immediately.
"""
import time

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.runtime.node import RaftNode
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport


def test_threaded_node_elides_idle_steps(tmp_path):
    """An idle threaded
    node with a coarse heartbeat runs far fewer steps than the tick
    interval allows — the device-reported timer_margin parks the loop —
    yet keeps serving when work arrives (the work event)."""
    cfg = RaftConfig(num_groups=1, num_peers=1, tick_interval_s=0.002,
                     election_ticks=60, heartbeat_ticks=25,
                     log_window=32, max_entries_per_msg=4)
    n = RaftNode(1, 1, cfg, LoopbackTransport(LoopbackHub()),
                 data_dir=str(tmp_path / "n1"))
    n.start(threaded=True)
    try:
        deadline = time.monotonic() + 5
        while n.leader_of(0) < 0:
            assert time.monotonic() < deadline, "no self-election"
            time.sleep(0.01)
        n.metrics.ticks = 0
        time.sleep(1.0)
        idle_ticks = n.metrics.ticks
        # 1s / 2ms = 500 loop slots; a leader's margin is the heartbeat
        # countdown (25), so ~20 steps expected.  Allow generous slack
        # for CI scheduling; the pre-elision loop would run ~400+.
        assert idle_ticks <= 120, idle_ticks
        # Snapshot first: the new leader's no-op already counts as a
        # commit, so waiting for >= 1 would pass vacuously.
        base = n.metrics.commits
        n.propose(0, b"SET k v")
        deadline = time.monotonic() + 5
        while n.metrics.commits <= base:
            assert time.monotonic() < deadline, "proposal never committed"
            time.sleep(0.01)
    finally:
        n.stop()
