"""Propose ring (runtime/ring.py) — the multi-worker serving plane.

Covers: SPSC ring framing round-trips (wraparound, zero-copy pop
windows, full-ring backpressure), the request/completion record codecs,
an in-process RingServer↔RingClient round trip over a real fused
RaftDB (PUT ack, GET rows, error propagation, /metrics document), and
the full `--workers N` deployment: real worker OS processes sharing one
engine through the rings, driven over HTTP via SO_REUSEPORT.
"""
import json
import os
import signal
import socket
import subprocess
import sys

import pytest

from raftsql_tpu.runtime.ring import (OP_GET, OP_PUT, ST_ERR, RingClient,
                                      RingServer, SpscRing,
                                      decode_completion, decode_request,
                                      encode_completion, encode_request)


# -- ring framing -----------------------------------------------------------


def test_ring_roundtrip_simple(tmp_path):
    r = SpscRing(str(tmp_path / "a.ring"), size=1 << 16, create=True)
    msgs = [b"hello", b"x" * 1000, b"tail"]
    for m in msgs:
        assert r.push(m)
    with pytest.raises(ValueError):
        r.push(b"")          # empty records are illegal (see push)
    got = []
    while True:
        v = r.pop()
        if v is None:
            break
        got.append(bytes(v))
        r.pop_commit()
    assert got == msgs
    assert r.depth_bytes() == 0


def test_ring_wraparound_many(tmp_path):
    """Thousands of variable-size records through a small ring: every
    byte survives arbitrary wrap positions."""
    import random
    rng = random.Random(7)
    r = SpscRing(str(tmp_path / "w.ring"), size=1 << 12, create=True)
    sent = recv = 0
    pending = []
    for i in range(5000):
        m = bytes([i % 256]) * rng.randrange(0, 200)
        rec = i.to_bytes(4, "little") + m
        while not r.push(rec):
            # Full: drain a few and retry (producer backpressure).
            v = r.pop()
            assert v is not None
            pending.append(bytes(v))
            r.pop_commit()
            recv += 1
        sent += 1
    while True:
        v = r.pop()
        if v is None:
            break
        pending.append(bytes(v))
        r.pop_commit()
        recv += 1
    assert recv == sent == 5000
    for i, rec in enumerate(pending):
        n = int.from_bytes(rec[:4], "little")
        assert n == i
        assert rec[4:] == bytes([i % 256]) * len(rec[4:])


def test_ring_full_backpressure(tmp_path):
    r = SpscRing(str(tmp_path / "f.ring"), size=1 << 12, create=True)
    big = b"z" * 1000
    pushed = 0
    while r.push(big):
        pushed += 1
    assert pushed >= 3                  # most of the capacity usable
    assert not r.push(big)              # full reports, never tears
    v = r.pop()
    assert bytes(v) == big
    r.pop_commit()
    assert r.push(big)                  # space reclaimed after commit


def test_ring_attach_sees_producer(tmp_path):
    """Consumer attaches to the file the producer created — the
    cross-process shape, exercised in-process via two handles."""
    path = str(tmp_path / "x.ring")
    prod = SpscRing(path, size=1 << 14, create=True)
    cons = SpscRing(path)
    assert prod.push(b"one")
    assert prod.push(b"two")
    assert bytes(cons.pop()) == b"one"
    assert bytes(cons.pop()) == b"two"
    cons.pop_commit()
    assert cons.pop() is None
    assert prod.push(b"three")
    assert bytes(cons.pop()) == b"three"


def test_request_completion_codecs():
    rec = encode_request(OP_PUT, 42, 7, 1, 0xDEADBEEF, b"INSERT x")
    assert decode_request(memoryview(rec)) == (OP_PUT, 42, 7, 1,
                                               0xDEADBEEF, 0,
                                               b"INSERT x")
    rec = encode_request(OP_GET, 43, 0, 1, 0, b"SELECT 1",
                         deadline_mono_ms=123456)
    assert decode_request(memoryview(rec)) == (OP_GET, 43, 0, 1, 0,
                                               123456, b"SELECT 1")
    cpl = encode_completion(42, ST_ERR, 3, b"boom")
    assert decode_completion(memoryview(cpl)) == (42, ST_ERR, 3, b"boom")


# -- in-process engine round trip -------------------------------------------


def _mk_rdb(tmp):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.fused import FusedClusterNode, FusedPipe

    cfg = RaftConfig(num_groups=2, num_peers=3, log_window=32,
                     max_entries_per_msg=4, tick_interval_s=0.0)
    node = FusedClusterNode(cfg, os.path.join(tmp, "data"))
    node.start(interval_s=0.0005)
    pipe = FusedPipe(node)

    def smf(g):
        return SQLiteStateMachine(os.path.join(tmp, f"g{g}.db"))

    return RaftDB(smf, pipe, num_groups=2)


def test_ring_server_client_roundtrip(tmp_path):
    rdb = _mk_rdb(str(tmp_path))
    srv = RingServer(rdb, str(tmp_path / "rings"), workers=1)
    srv.start()
    rc = RingClient(str(tmp_path / "rings"), 0)
    try:
        assert rc.propose("CREATE TABLE t (v text)").wait(30) is None
        for i in range(8):
            assert rc.propose(f"INSERT INTO t (v) VALUES ('x{i}')") \
                .wait(30) is None
        rows = rc.query("SELECT count(*) FROM t")
        assert rows.strip() == "|8|"
        # Deterministic apply error comes back as the error ack.
        err = rc.propose("INSERT INTO missing VALUES (1)").wait(30)
        assert err is not None and "missing" in str(err)
        # Non-SELECT through the read path is the 400 class.
        with pytest.raises(ValueError):
            rc.query("DELETE FROM t")
        # The metrics document renders through the ring and carries the
        # serving-plane gauges.
        m = json.loads(rc.render_metrics())
        assert m["ring_workers"] == 1
        assert m["ring_proposed"] >= 9
        assert "ring_depth" in m
        h = json.loads(rc.render_health())
        assert h["ready"] is True
    finally:
        rc.close()
        srv.stop()
        rdb.close()


def test_ring_retry_token_exactly_once(tmp_path):
    """The same retry token through the ring twice applies once — the
    worker plane preserves the engine's exactly-once contract."""
    rdb = _mk_rdb(str(tmp_path))
    srv = RingServer(rdb, str(tmp_path / "rings"), workers=1)
    srv.start()
    rc = RingClient(str(tmp_path / "rings"), 0)
    try:
        assert rc.propose("CREATE TABLE t (v text)").wait(30) is None
        tok = 0x1234ABCD5678
        sql = "INSERT INTO t (v) VALUES ('once')"
        assert rc.propose(sql, token=tok).wait(30) is None
        assert rc.propose(sql, token=tok).wait(30) is None  # retry acks
        assert rc.query("SELECT count(*) FROM t").strip() == "|1|"
    finally:
        rc.close()
        srv.stop()
        rdb.close()


# -- the real multi-worker deployment ---------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_workers_deployment_end_to_end(tmp_path):
    """server/main.py --fused --workers 2: two real worker processes
    over SO_REUSEPORT share one engine through the rings; writes and
    reads flow, /metrics shows the ring plane, SIGTERM exits clean."""
    from raftsql_tpu.api.client import RaftSQLClient

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
         "--workers", "2", "--groups", "2", "--port", str(port),
         "--tick", "0.004"],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    client = RaftSQLClient([port], timeout_s=10)
    try:
        client.wait_healthy(0, deadline_s=90)
        for g in range(2):
            client.put("CREATE TABLE t (v text)", group=g,
                       deadline_s=60)
        for i in range(20):
            client.put(f"INSERT INTO t (v) VALUES ('w{i}')",
                       group=i % 2, deadline_s=30)
        assert client.get("SELECT count(*) FROM t",
                          group=0).strip() == "|10|"
        assert client.get("SELECT count(*) FROM t",
                          group=1).strip() == "|10|"
        status, _, text = client.raw(0, "GET", "/metrics")
        assert status == 200
        m = json.loads(text)
        assert m["ring_workers"] == 2
        assert m["ring_proposed"] >= 22
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        client.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_ring_linear_get_421_redirect_workers_cluster(tmp_path):
    """Ring op 2 (GET) with flags bit 0 (linearizable) through a REAL
    --workers 2 deployment of a DISTRIBUTED 2-node cluster: a linear
    read at the follower's workers crosses the ring, comes back
    ST_NOT_LEADER, surfaces as HTTP 421 + X-Raft-Leader, and the
    hardened client chases the hint to the leader — plus the
    X-Raft-Session watermark echo (session reads) over the same ring.
    """
    from raftsql_tpu.api.client import RaftSQLClient

    peer_ports = [_free_port(), _free_port()]
    http_ports = [_free_port(), _free_port()]
    cluster = ",".join(f"http://127.0.0.1:{p}" for p in peer_ports)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # This test pins the RING read path (421 redirect, session echo,
    # engine-side attribution) — the worker shm fast path would serve
    # these reads before they ever cross the ring, so it stays off
    # here (its own coverage: tests/test_shm.py + serving_smoke
    # --reads).
    env["RAFTSQL_SHM_READS"] = "0"
    procs = []
    for i in (0, 1):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.main",
             "--id", str(i + 1), "--cluster", cluster,
             "--port", str(http_ports[i]), "--workers", "2",
             "--tick", "0.01", "--lease-ticks", "30"],
            cwd=str(tmp_path), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    client = RaftSQLClient(http_ports, timeout_s=10)
    try:
        for i in (0, 1):
            client.wait_healthy(i, deadline_s=120)
        client.put("CREATE TABLE t (v text)", deadline_s=60)
        wm = client.put("INSERT INTO t (v) VALUES ('a')", deadline_s=30)
        assert wm is not None and wm >= 2     # session echo over the ring

        # Find the leader from /healthz (role of group 0).
        lead = None
        deadline = 30
        import time as _t
        t0 = _t.monotonic()
        while lead is None and _t.monotonic() - t0 < deadline:
            for i in (0, 1):
                doc = client.health(i, timeout_s=2.0)
                if doc and doc["groups"]["0"]["role"] == "leader":
                    lead = i
                    break
            _t.sleep(0.1)
        assert lead is not None, "no leader reported via /healthz"
        follower = 1 - lead

        # Raw linear GET pinned at the FOLLOWER's workers: the ring
        # completion must be NOT_LEADER -> 421 + X-Raft-Leader.
        status, hdrs, _ = client.raw(
            follower, "GET", "/", "SELECT count(*) FROM t",
            headers={"X-Consistency": "linear"})
        assert status == 421
        assert hdrs.get("X-Raft-Leader") == str(lead + 1)

        # The hardened client chases the hint and reads linearizably.
        got = client.get("SELECT count(*) FROM t", linear=True,
                         deadline_s=30)
        assert got == "|1|\n", got

        # Session read presenting the PUT's watermark works from the
        # follower too (no leader round).
        got = client.get("SELECT count(*) FROM t", node=follower,
                         consistency="session", session=wm,
                         deadline_s=30)
        assert got == "|1|\n", got

        # The leader's engine attributes the linear read (lease or
        # ReadIndex — never unaccounted).
        _, _, text = client.raw(lead, "GET", "/metrics")
        m = json.loads(text)
        assert m["reads"]["lease"] + m["reads"]["read_index"] >= 1
        _, _, text = client.raw(follower, "GET", "/metrics")
        m = json.loads(text)
        assert m["reads"]["session"] >= 1
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=30) == 0
    finally:
        client.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
