# Build/test harness — parity with the reference Makefile (build, test,
# vet targets; reference Makefile:1-23), adapted to the Python/C++ tree.

PY ?= python
SEED ?= 0

.PHONY: all native native-check native-sanitize test vet bench chaos chaos-membership chaos-procs \
	chaos-mesh chaos-reads chaos-transfer chaos-reshard chaos-quorum chaos-pod chaos-replica \
	chaos-overload trace prom-lint clean

# The mesh families and tests need a multi-device platform; 8 virtual
# CPU devices is the no-hardware testing recipe (tests/conftest.py).
MESH_ENV = JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8"

# "Build" = compile the native C++ components (storage fast path).
all: native

native:
	$(PY) -c "from raftsql_tpu.native.build import load_native_wal; \
	          lib = load_native_wal(); \
	          print('native wal:', 'ok' if lib else 'UNAVAILABLE')"

# Build-check the native GROUP-COMMIT path (wal.cc walplog_* group
# bias): write through per-peer views of one shared native WAL, replay,
# and assert the per-peer split round-trips.  Fails if the toolchain is
# present but the group-commit ABI is broken; degrades to a SKIP where
# no compiler exists (the Python backend covers those hosts).
native-check:
	$(PY) scripts/check_native_gc.py

# Serving smoke (scripts/serving_smoke.py): a --fused --workers 2
# deployment driven by the native loadgen; asserts zero errors and a
# req/s floor.  SMOKE_SECONDS/SMOKE_CLIENTS/SMOKE_MIN_RPS override.
serving-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/serving_smoke.py

# make test captures output like the reference (Makefile:10-15).
test:
	$(PY) -m pytest tests/ -q 2>&1 | tee test.out

# Static analysis stand-in for `go vet`: compile every source file,
# then the raftlint suite (raftsql_tpu/analysis/) — the five classic
# AST rules plus the project-invariant checkers: jit-stability,
# wall-clock/unseeded-random determinism, thread-ownership,
# fail-closed, memory-model.  `python -m raftsql_tpu.analysis --list`
# enumerates the rules; suppress per line with
# `# raftlint: disable=<rule> -- why`.
vet:
	$(PY) -m compileall -q raftsql_tpu tests bench.py __graft_entry__.py \
	      scripts
	$(PY) scripts/vet.py

bench:
	$(PY) bench.py

# Deterministic chaos scenario (raftsql_tpu/chaos/): seeded partitions,
# crashes, fsync/torn-write faults + invariant checking, run TWICE and
# digest-compared to prove the seed reproduces bit-for-bit.
#   make chaos SEED=17
chaos:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --seed $(SEED) --ticks 240 --runs 2

# Sweep one seed through EVERY scenario family of the fault matrix
# (asym partitions, clock skew, wire corruption, ENOSPC, fsync stalls,
# compaction+crash, compaction+InstallSnapshot+crash, real-TCP chaos).
# Deterministic families run twice and must digest-match; all families
# must pass every invariant.  See README "Chaos fault matrix".
#   make chaos-matrix SEED=17
chaos-matrix:
	$(MESH_ENV) $(PY) -m raftsql_tpu.chaos.run \
	  --matrix --seed $(SEED)

# Mesh-skew chaos (runtime/mesh.py MeshClusterNode): the fused skew
# family's schedule on the MESH runtime — per-peer clock drift through
# the shard_map'd step's sharded timer vector, a crash + replay from
# the per-shard WAL dirs, run twice and digest-compared.  Closes the
# old MeshLockstepOnlyError frontier.
#   make chaos-mesh SEED=17
chaos-mesh:
	$(MESH_ENV) $(PY) -m raftsql_tpu.chaos.run \
	  --family mesh_skew --seed $(SEED)

# Membership-churn chaos (raftsql_tpu/membership/): SIGKILL a voter,
# boot a fresh spare, add-learner -> promote (joint consensus) ->
# remove the dead member, under drops + a second crash.  Deterministic:
# runs the seed twice and digest-compares, and every invariant
# (including "no quorum from a removed majority") must hold.
#   make chaos-membership SEED=17
chaos-membership:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --family membership --seed $(SEED)

# Read-plane nemesis (raftsql_tpu/chaos/): lease / ReadIndex /
# session / follower reads racing writes under clock skew, asymmetric
# partitions, leader kills and crashes — the fused family run twice
# and digest-compared, the LEASE-FALSIFICATION sensitivity pair (a
# deliberately mis-sized lease bound under 4x skew MUST be caught by
# the read-linearizability invariant; the same schedule with a correct
# bound must pass), and the process-plane read nemesis over real
# server processes (verdict digests compared).
#   make chaos-reads SEED=17
chaos-reads:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --reads --seed $(SEED)

# Leadership-transfer nemesis (raftsql_tpu/chaos/): graceful transfers
# (core/step.py TimeoutNow kernel, thesis §3.10) racing drops,
# leader-targeted partitions, one-directional cuts, clock skew and
# crash+restart under live acked-PUT load — the fused family run twice
# and digest-compared with a no-availability-loss-during-transfer
# invariant (bounded proposal stall, aborted transfers leave the group
# serving), the BROKEN-KERNEL falsification pair (a kernel that
# abdicates before the target caught up MUST be caught on a directed
# lagging-target schedule; the correct kernel must pass the same
# schedule), and the process-plane POST /transfer nemesis over real
# server processes (verdict digests compared).
#   make chaos-transfer SEED=17
chaos-transfer:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --transfers --seed $(SEED)

# Elastic-keyspace nemesis (raftsql_tpu/reshard/): seeded group
# SPLIT / MERGE / MIGRATE schedules racing partitions, message drops,
# whole-cluster crash+restart, coordinator SIGKILL mid-verb (rebuilt
# from the raft-log journal fold alone) and a disk fault on the
# migrate snapshot ship — under live acked-PUT load, checked by
# NoAckedWriteLost (every acked write readable in exactly one
# post-reshard group, WAL-fold post-mortem after every restart) and
# NoAvailabilityLoss (writes outside the moving range never stall past
# a bound; verbs always resolve).  The family runs twice and is
# digest-compared, then the PREMATURE-FLIP falsification pair: a
# coordinator that flips the router before the destination durably
# applied the copies MUST be caught on a directed copy-starving
# schedule; the correct coordinator must complete the same schedule.
#   make chaos-reshard SEED=17
chaos-reshard:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --reshard --seed $(SEED)

# Quorum-geometry nemesis (raftsql_tpu/chaos/): flexible write /
# election quorums and witness peers under fire.  The witness-cluster
# family (2 full voters + 1 witness, W=E=2) runs twice and is
# digest-compared — the witness must replicate (witness_appends) but
# never publish, with exactly one apply/shard stream fewer than WAL
# streams — then TWO falsification pairs: (a) a non-intersecting
# W=1/E=2 geometry (config-refused without unsafe_quorum_geometry;
# asserted) MUST be caught as divergent committed slots when a
# partitioned pinned leader solo-commits against the majority's
# rewrite, and the same schedule at W=2 must pass; (b) a witness
# wrongly counted toward the LEASE quorum (unsafe_witness_lease) MUST
# be caught as a stale lease read when it grants a prevote inside the
# deposed leader's live lease, and the honest witness must pass the
# same schedule.
#   make chaos-quorum SEED=17
chaos-quorum:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --quorum --seed $(SEED)

# Multi-host pod chaos (raftsql_tpu/chaos/pod.py): a seeded nemesis
# over a REAL 2-process pod (raftsql_tpu/pod/ — dry-run multi-process
# on one box, TcpPodTransport collective, one group shard durable per
# host).  Three incarnations per run: a propose-plane cut at one
# origin, SIGKILL of the NON-coordinator host (pod-wide fail-stop
# abort), SIGKILL of the COORDINATOR, then a fault-free audit
# incarnation — every acked write must survive the merged cross-host
# replay (durability), apply exactly once post-dedup (re-offer retry
# tokens), and every host must fold to the identical state
# (convergence).  Runs the seed TWICE (plan + verdict digests must
# match), then the PREMATURE-ACK falsification pair: acks written
# before any durability plus a scripted crash MUST be caught by the
# durability invariant; honest acks on the same schedule must pass.
#   make chaos-pod SEED=17
chaos-pod:
	$(MESH_ENV) $(PY) -m raftsql_tpu.chaos.run \
	  --pod --seed $(SEED)

# Read-replica tier chaos (raftsql_tpu/chaos/replica.py): a seeded
# nemesis over a fused engine publishing the shm delta stream
# (--replica-listen) and REAL `python -m raftsql_tpu.replica`
# processes subscribed through nemesis-owned TCP proxies — a
# subscription cut + heal, a replica SIGKILL + respawn, and one
# flipped stream bit — under an acked-write workload probing session
# and linear reads at every replica.  StaleReadNever: a 200 answer
# below the mode's bound is the violation, a 421 refusal never is;
# the audit requires exact convergence and the corruption surfacing
# as a CRC failure.  Runs the seed TWICE (plan + verdict digests must
# match), then the UNSAFE-SERVE falsification pair: a replica with
# every fail-closed gate skipped under a never-healed cut MUST be
# caught serving stale; the same schedule with the gates on must
# pass by refusing.
#   make chaos-replica SEED=17
chaos-replica:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --replica --seed $(SEED)

# Overload-control chaos (raftsql_tpu/overload/): a seeded OPEN-LOOP
# nemesis offering ~2x the engine's drain rate — burst windows,
# hot-group skew, device-step deadlines on a fraction of writes,
# slow-fsync stalls, and a mid-overload crash+restart — against the
# bounded admission controller attached exactly as the server attaches
# it.  Invariants: the propose backlog never exceeds the hard cap
# (OVERLOAD-MEMORY, measured against the engine's actual queues every
# tick), every acked write survives the restart replay, goodput clears
# the plan's floor despite the overload, and no group starves.  The
# seed runs TWICE (plan + result digests must match bit-for-bit),
# then the falsification pair: the identical schedule with NO
# admission controller MUST be caught by OVERLOAD-MEMORY, and with
# the bounded controller must pass.
#   make chaos-overload SEED=17
chaos-overload:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --overload --seed $(SEED)

# Process-plane chaos (raftsql_tpu/chaos/proc.py): a seeded nemesis
# over REAL server/main.py OS processes — leader-targeted + random
# SIGKILL, SIGSTOP/SIGCONT stalls, a rolling-restart storm (clean
# SIGTERM + same-port rebinds), env-injected disk faults
# (RAFTSQL_FSIO_FAULTS: ENOSPC on a WAL write + hard process exit at a
# WAL fsync) — under a live acked-PUT workload.  The seed runs TWICE:
# schedule + invariant-verdict digests must match (committed history
# crosses real kernel scheduling, so tick-for-tick replay is out of
# scope on this plane — see README "Process-plane chaos").
#   make chaos-procs SEED=17
chaos-procs:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.chaos.run \
	  --procs --seed $(SEED)

# Metrics lint (scripts/check_prom.py): boot a --fused node per HTTP
# plane (aio + threaded), drive writes, scrape GET /metrics?format=prom
# and the Accept-negotiated path, validate the exposition under a
# strict parser, and check every JSON /metrics field round-trips into
# a Prometheus sample.  --url scrapes a live node instead.
prom-lint:
	JAX_PLATFORMS=cpu $(PY) scripts/check_prom.py

# Observability demo (raftsql_tpu/obs/): run a traced fused cluster and
# emit Chrome trace-event JSON — load trace.json at ui.perfetto.dev or
# chrome://tracing.  The same spans/counters are live on a running
# server at GET /trace and GET /events (enable with --trace).
trace:
	JAX_PLATFORMS=cpu $(PY) -m raftsql_tpu.obs.trace_demo --out trace.json

# AddressSanitizer + UBSan pass over the native WAL stress harness
# (scripts/native_sanitize.py; add --san tsan for the full trio).
# Degrades to SKIP where no g++ exists — those hosts run the Python
# WAL backend.
native-sanitize:
	$(PY) scripts/native_sanitize.py

# ThreadSanitizer pass over the native WAL's locking (SURVEY.md §5.2):
# 4 threads x appends/hardstate/compact/snapshot/sync on one handle.
tsan:
	g++ -O1 -g -std=c++17 -fsanitize=thread -fPIC \
	    -o /tmp/wal_stress_tsan \
	    raftsql_tpu/native/wal_stress.cc raftsql_tpu/native/wal.cc
	rm -rf /tmp/wal_tsan_dir && mkdir -p /tmp/wal_tsan_dir
	/tmp/wal_stress_tsan /tmp/wal_tsan_dir 2000

clean:
	rm -f test.out flight-*.json raftsql_tpu/native/_native_*.so \
	      raftsql_tpu/native/_wal_stress_* raftsql_tpu/native/_http_load
	find . -name __pycache__ -type d -exec rm -rf {} +

# The durable product paths, quick local shapes (one JSON line each).
bench-durable:
	BENCH_CHILD=1 BENCH_PLATFORM=cpu BENCH_CONFIG=durable \
	  BENCH_DURABLE_MODE=fused BENCH_E=32 python bench.py

bench-http:
	BENCH_CHILD=1 BENCH_PLATFORM=cpu BENCH_CONFIG=http \
	  BENCH_HTTP_SECONDS=8 python bench.py
