# Build/test harness — parity with the reference Makefile (build, test,
# vet targets; reference Makefile:1-23), adapted to the Python/C++ tree.

PY ?= python

.PHONY: all native test vet bench clean

# "Build" = compile the native C++ components (storage fast path).
all: native

native:
	$(PY) -c "from raftsql_tpu.native.build import load_native_wal; \
	          lib = load_native_wal(); \
	          print('native wal:', 'ok' if lib else 'UNAVAILABLE')"

# make test captures output like the reference (Makefile:10-15).
test:
	$(PY) -m pytest tests/ -q 2>&1 | tee test.out

# Static analysis stand-in for `go vet`: compile every source file.
vet:
	$(PY) -m compileall -q raftsql_tpu tests bench.py __graft_entry__.py

bench:
	$(PY) bench.py

clean:
	rm -f test.out raftsql_tpu/native/_native_*.so
	find . -name __pycache__ -type d -exec rm -rf {} +
