"""Injectable filesystem layer for the durable write paths.

The reference's only storage-fault story is "trust etcd/wal"; SURVEY.md
§4 and the round-5 advisor findings (crash-window durability bugs that
no test could reach) call for systematic storage fault injection.  This
module is the seam: every durable-path write/fsync in storage/wal.py and
the epoch-commit file in runtime/fused.py flows through the functions
below, which are pass-throughs until a `StorageFaultInjector` is
installed (chaos/ scenarios install one; production never does, so the
cost is one None check per call).

Fault classes (the chaos harness's storage axis):
  * FAILED FSYNC — the Nth fsync matching a rule raises OSError,
    exercising the paths that must fail a tick loudly instead of
    acking unsynced data.  Counters are PER RULE (e.g. per peer WAL
    directory): each peer's fsyncs are sequential even when the fused
    barrier runs them from a worker pool, so rule counters are
    deterministic where a global counter would race.
  * SILENT FSYNC LOSS — from rule op N on, fsync reports success but
    syncs nothing; combined with a crash this models a disk that lied.
  * TORN WRITE / UNSYNCED LOSS — the injector records every write's
    (offset, length) and every file's last really-synced size, so a
    power-loss simulation can truncate files to exactly what a real
    crash could leave: everything synced, plus at most a torn prefix of
    one unsynced record (WAL._repair_tail's job to repair).
  * ENOSPC — the Nth write ATTEMPT matching a rule raises EnospcError
    (errno ENOSPC) BEFORE any byte reaches the file: the WAL record is
    refused whole, so the log tail stays a clean record boundary
    instead of a half-written frame.  The trigger is consumed when it
    fires (an operator freeing disk space), so a crash+restart retry
    of the same record succeeds.
  * FSYNC STALL — the Nth..(N+count-1)th fsyncs matching a rule sleep
    `stall_s` before completing (a saturated disk queue, not a failed
    one): data IS durable afterwards, just late — the tick slows, no
    invariant may break, and the stall count is exported so slow-disk
    incidents are visible in /metrics.
  * PROCESS EXIT AT FSYNC — the Nth fsync matching a rule hard-exits
    the WHOLE PROCESS (os._exit, EXIT_CODE_FSYNC_CRASH) before the
    real fsync runs: the process-plane chaos harness's crash point.
    The written-but-not-yet-synced tail sits in the page cache, the
    tick's ack never happens, and the restarted process must recover
    through WAL tail repair — the "machine died at the worst moment"
    scenario over a REAL server process, not an in-process simulation.

Faults cross the process boundary via RAFTSQL_FSIO_FAULTS: the server
entry point (server/main.py) parses the env spec with
`install_from_env` and installs the rules inside the child before the
node boots, so a nemesis that only controls argv/env can still inject
disk faults into real server processes.  Spec grammar (';'-separated
rules, ':'-separated fields, first field is the path substring):

    raftsql-2:enospc@12            ENOSPC on WAL write attempt #12
    raftsql-2:exit_fsync@9         hard process exit at fsync #9
    raftsql-1:fail_fsync@5         fsync #5 raises FsyncFaultError
    raftsql-3:stall@4x3x50         fsyncs #4..#6 stall 50 ms each
    raftsql-1:enospc@8:stall@2x2x20   clauses compose per rule

The injector also keeps an ordered event log (("write"|"fsync"|
"fsync_dir", path) tuples) so tests can assert durability ORDERING —
e.g. "the data_dir was fsynced after the EPOCHS file was created,
before the epoch was treated as committed".

An ACTIVE injector forces the Python WAL backend (storage/wal.py
_open_active checks `active()`): the C++ fast path does its framing and
fdatasync behind one ctypes call, invisible to this seam.  Chaos
scenarios trade the fast path for full observability; both backends
produce byte-identical files, so what the faults exercise is the real
on-disk format.
"""
from __future__ import annotations

import errno
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple


class FsyncFaultError(OSError):
    """Injected fsync failure (distinguishable from real OS errors)."""


# Exit code of an injected process-exit-at-fsync crash point: the
# nemesis (chaos/proc.py) distinguishes "the scheduled disk crash
# fired" from a real bug in the child by this code.
EXIT_CODE_FSYNC_CRASH = 86


class EnospcError(OSError):
    """Injected disk-full write failure: raised BEFORE the write lands,
    so the refused record never reaches the file and the log tail stays
    a clean record boundary.  Carries errno.ENOSPC like the real one."""

    def __init__(self, msg: str):
        super().__init__(errno.ENOSPC, msg)


class CrashPointError(RuntimeError):
    """Injected mid-write power loss: the write reached the page cache
    (the injector writes through) and the machine died before any
    fsync.  Carries the rule's `tag` so the chaos runner knows which
    peer's record to tear."""

    def __init__(self, msg: str, tag=None):
        super().__init__(msg)
        self.tag = tag


class _FsyncRule:
    """One fault rule: matches paths by substring (`sub` in path + sep,
    so a directory matches its own fsync and its files'), counts the
    fsyncs and writes it sees, fails/skips/crashes at chosen ops."""

    def __init__(self, substring: str, fail_at=(), silent_from=None,
                 crash_write_at=(), tag=None, enospc_write_at=(),
                 stall_at=(), stall_s: float = 0.05, exit_at=()):
        self.substring = substring
        self.fail_at = set(fail_at)
        self.silent_from = silent_from
        self.crash_write_at = set(crash_write_at)
        self.tag = tag
        # ENOSPC triggers fire on the (write_ops + 1)th write ATTEMPT
        # and are consumed when they fire (see module doc).
        self.enospc_write_at = set(enospc_write_at)
        self.stall_at = set(stall_at)
        self.stall_s = stall_s
        # Process-exit crash points: fsync op numbers at which the
        # whole process hard-exits (os._exit, no cleanup).
        self.exit_at = set(exit_at)
        self.ops = 0
        self.write_ops = 0
        self.failures = 0
        self.lost = 0
        self.enospc_hits = 0
        self.stalls = 0

    def matches(self, path: str) -> bool:
        return self.substring in path + os.sep


class StorageFaultInjector:
    """Deterministic storage fault state, shared across all files.

    Thread-safe: the fused runtime fsyncs peers from a worker pool, so
    the write log and rule counters are lock-protected.
    """

    def __init__(self):
        self.rules: List[_FsyncRule] = []
        self.fsync_ops = 0
        self.write_ops = 0
        self.fsync_failures = 0
        self.enospc_hits = 0
        self.fsync_stalls = 0
        self.events: List[Tuple[str, str]] = []
        # path -> (offset before last write, bytes written) for torn-
        # write crash simulation.
        self.last_write: Dict[str, Tuple[int, int]] = {}
        # path -> durable size at last REAL fsync (for unsynced-loss
        # crash simulation; a path absent here was never synced).
        self.synced_size: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add_rule(self, substring: str, fail_at=(),
                 silent_from: Optional[int] = None,
                 crash_write_at=(), tag=None, enospc_write_at=(),
                 stall_at=(), stall_s: float = 0.05,
                 exit_at=()) -> _FsyncRule:
        rule = _FsyncRule(substring, fail_at, silent_from,
                          crash_write_at, tag, enospc_write_at,
                          stall_at, stall_s, exit_at)
        with self._lock:
            self.rules.append(rule)
        return rule

    # -- hooks called by the I/O functions below -----------------------

    def check_write(self, path: str, nbytes: int) -> None:
        """Pre-write gate: raises EnospcError when a rule's next write
        attempt is scheduled to hit disk-full.  Runs BEFORE the caller
        writes anything, so the refused record never lands (the log
        tail cannot be corrupted by a half-written frame).  The trigger
        is consumed so a post-restart retry of the same record
        succeeds — the disk-was-freed recovery story."""
        with self._lock:
            for rule in self.rules:
                if not rule.matches(path):
                    continue
                attempt = rule.write_ops + 1
                if attempt in rule.enospc_write_at:
                    rule.enospc_write_at.discard(attempt)
                    rule.enospc_hits += 1
                    self.enospc_hits += 1
                    raise EnospcError(
                        f"injected ENOSPC (write attempt {attempt} of "
                        f"rule {rule.substring!r}) on {path}")

    def on_write(self, path: str, offset: int, nbytes: int) -> None:
        """Record one (already page-cache-visible) write; raises
        CrashPointError AFTER recording when a rule's write counter
        hits a crash point — the caller's write reached the file, the
        fsync never will."""
        with self._lock:
            self.write_ops += 1
            self.events.append(("write", path))
            self.last_write[path] = (offset, nbytes)
            for rule in self.rules:
                if not rule.matches(path):
                    continue
                rule.write_ops += 1
                if rule.write_ops in rule.crash_write_at:
                    raise CrashPointError(
                        f"injected mid-write power loss (write op "
                        f"{rule.write_ops} of rule {rule.substring!r}) "
                        f"on {path}", tag=rule.tag)

    def on_fsync(self, path: str, size: int, kind: str = "fsync") -> bool:
        """Count one fsync; returns False when the sync must be
        silently skipped; raises FsyncFaultError for a failed one.
        Stall rules sleep OUTSIDE the lock (a stalled disk must slow
        this fsync, not serialize every other peer's)."""
        stall_for = 0.0
        with self._lock:
            self.fsync_ops += 1
            self.events.append((kind, path))
            silent = False
            for rule in self.rules:
                if not rule.matches(path):
                    continue
                rule.ops += 1
                if rule.ops in rule.exit_at:
                    # Crash point: the machine dies AT the fsync — the
                    # record is in the page cache, the barrier never
                    # completes, nothing after this line runs.  stderr
                    # is best-effort (the nemesis reads the exit code).
                    try:
                        sys.stderr.write(
                            f"fsio: injected process exit at fsync "
                            f"{rule.ops} of rule {rule.substring!r} "
                            f"on {path}\n")
                        sys.stderr.flush()
                    finally:
                        os._exit(EXIT_CODE_FSYNC_CRASH)
                if rule.ops in rule.fail_at:
                    rule.failures += 1
                    self.fsync_failures += 1
                    raise FsyncFaultError(
                        f"injected fsync failure (op {rule.ops} of rule "
                        f"{rule.substring!r}) on {path}")
                if rule.ops in rule.stall_at:
                    rule.stalls += 1
                    self.fsync_stalls += 1
                    stall_for = max(stall_for, rule.stall_s)
                if rule.silent_from is not None \
                        and rule.ops >= rule.silent_from:
                    rule.lost += 1
                    silent = True
            if not silent and kind == "fsync":
                self.synced_size[path] = size
        if stall_for > 0.0:
            time.sleep(stall_for)
        return not silent

    # -- crash simulation ----------------------------------------------

    def tear_last_write(self, path: str,
                        keep_fraction: float = 0.5) -> bool:
        """Truncate `path` mid-way through its last recorded write —
        the torn-record shape a power loss leaves.  Never cuts below
        the last really-synced size (durable bytes cannot tear), and
        never extends the file (the write may still sit in a userspace
        buffer a simulated process kill already discarded).  Returns
        True when something was actually torn."""
        rec = self.last_write.get(path)
        if rec is None or not os.path.isfile(path):
            return False
        offset, nbytes = rec
        keep = offset + max(0, min(nbytes - 1,
                                   int(nbytes * keep_fraction)))
        keep = max(keep, self.synced_size.get(path, 0))
        if keep >= os.path.getsize(path):
            return False
        with open(path, "r+b") as f:
            f.truncate(keep)
        return True

    def drop_unsynced(self, path: str) -> bool:
        """Truncate `path` back to its last REALLY-synced size (0 when
        never synced) — what a power loss leaves on disk.  Returns True
        when bytes were dropped."""
        size = self.synced_size.get(path, 0)
        if not os.path.isfile(path) or os.path.getsize(path) <= size:
            return False
        with open(path, "r+b") as f:
            f.truncate(size)
        return True

    def tracked_paths(self) -> List[str]:
        with self._lock:
            return sorted(set(self.last_write) | set(self.synced_size))


_injector: Optional[StorageFaultInjector] = None


def install(inj: StorageFaultInjector) -> StorageFaultInjector:
    global _injector
    _injector = inj
    return inj


def uninstall() -> None:
    global _injector
    _injector = None


def active() -> bool:
    return _injector is not None


def injector() -> Optional[StorageFaultInjector]:
    return _injector


# -- env-injected faults (the process boundary) ------------------------

def parse_env_spec(spec: str) -> List[dict]:
    """Parse a RAFTSQL_FSIO_FAULTS value into add_rule kwargs dicts.

    Grammar (module doc): rules ';'-separated, fields ':'-separated,
    first field the path substring, then `clause@args` clauses with
    'x'-separated integer args.  Raises ValueError on anything
    malformed — a server booted with a broken fault spec must fail
    loudly, not run chaos with silently-dropped faults."""
    rules = []
    for rule_s in spec.split(";"):
        rule_s = rule_s.strip()
        if not rule_s:
            continue
        fields = rule_s.split(":")
        if len(fields) < 2 or not fields[0]:
            raise ValueError(f"fsio spec rule needs 'substring:clause', "
                             f"got {rule_s!r}")
        kw: dict = {"substring": fields[0]}
        for clause in fields[1:]:
            name, at, args_s = clause.partition("@")
            if at != "@":
                raise ValueError(f"fsio clause needs 'name@args', "
                                 f"got {clause!r}")
            args = [int(a) for a in args_s.split("x")]
            if name == "enospc" and len(args) == 1:
                kw.setdefault("enospc_write_at", []).append(args[0])
            elif name == "fail_fsync" and len(args) == 1:
                kw.setdefault("fail_at", []).append(args[0])
            elif name == "exit_fsync" and len(args) == 1:
                kw.setdefault("exit_at", []).append(args[0])
            elif name == "stall" and len(args) == 3:
                k, count, ms = args
                kw.setdefault("stall_at", []).extend(
                    range(k, k + count))
                kw["stall_s"] = ms / 1000.0
            else:
                raise ValueError(f"unknown fsio clause {clause!r}")
        rules.append(kw)
    return rules


def install_from_env(spec: Optional[str] = None) \
        -> Optional[StorageFaultInjector]:
    """Install an injector from a RAFTSQL_FSIO_FAULTS-style spec (reads
    the env var when `spec` is None).  Returns the installed injector,
    or None when the spec is absent/empty.  This is the server entry
    point's storage-fault seam: the nemesis sets the env var, the child
    installs the rules before its first WAL byte."""
    if spec is None:
        spec = os.environ.get("RAFTSQL_FSIO_FAULTS", "")
    rules = parse_env_spec(spec)
    if not rules:
        return None
    inj = StorageFaultInjector()
    for kw in rules:
        inj.add_rule(**kw)
    return install(inj)


class installed:
    """Context manager: `with fsio.installed(inj): ...` — uninstalls on
    exit even when the scenario raises (tests must never leak an
    injector into the next test's WAL traffic)."""

    def __init__(self, inj: StorageFaultInjector):
        self.inj = inj

    def __enter__(self) -> StorageFaultInjector:
        return install(self.inj)

    def __exit__(self, *exc) -> None:
        uninstall()


# -- the I/O seam ------------------------------------------------------

def write(f, data: bytes) -> None:
    """File write, recorded for torn-write simulation.

    Under an injector the write goes THROUGH to the file before the
    crash-point check runs — page-cache semantics: a process kill keeps
    what was written, a power loss keeps at most a torn prefix of it
    (the injector's tear/drop helpers cut it back to what a real crash
    could leave).  An ENOSPC rule fires BEFORE any byte lands (see
    StorageFaultInjector.check_write): the caller's record is refused
    whole and the file tail is untouched."""
    inj = _injector
    if inj is None:
        f.write(data)
        return
    path = getattr(f, "name", "")
    inj.check_write(path, len(data))     # may raise EnospcError
    offset = f.tell()
    f.write(data)
    f.flush()
    inj.on_write(path, offset, len(data))


def fsync_file(f) -> None:
    """flush + fsync an open file object through the fault layer."""
    f.flush()
    inj = _injector
    if inj is not None:
        if not inj.on_fsync(getattr(f, "name", ""), f.tell()):
            return                       # silent loss: report success
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """fsync a directory fd (dirent durability) through the fault layer."""
    inj = _injector
    if inj is not None:
        if not inj.on_fsync(path, 0, kind="fsync_dir"):
            return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
