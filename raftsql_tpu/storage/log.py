"""Host-side payload log: entry (term, bytes) per (group, index).

The device log (core/state.py) stores only the last-W entry *terms* in a
ring; the bytes of each proposal (SQL text) — and the full term history,
which the device ring forgets once an index slides out of the window —
live here, mirroring device log positions 1:1.  This splits the
reference's `raft.MemoryStorage` (reference raft.go:129, 229) into its two
real roles: ordering metadata (device) and bytes (host).

The full term history is what lets the leader's HOST build catch-up
AppendEntries for followers that have fallen more than W entries behind —
positions the device can no longer describe (runtime/node.py catch-up
path; the reference gets the same from MemoryStorage.Term, which etcd's
sendAppend consults before falling back to a snapshot).

Like MemoryStorage, growth is unbounded and never compacted — a documented
limitation shared with the reference; snapshots are the eventual fix for
both (reference db.go:27-29 declares the same).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


class PayloadLog:
    """1-based, truncate-on-conflict (term, bytes) log for G groups.

    After `compact(g, upto, term)`, entries at or below `upto` are
    dropped; `start(g)` reports the floor and `term_of(g, start)` still
    resolves (the boundary term is retained) so AppendEntries prev-term
    checks at the compaction edge work."""

    def __init__(self, num_groups: int):
        self._logs: List[List[Tuple[int, bytes]]] = [
            [] for _ in range(num_groups)]
        self._start: List[int] = [0] * num_groups
        self._start_term: List[int] = [0] * num_groups
        # One lock: readers (publish, catch-up, send) race the compactor,
        # and a torn (_start, _logs) read would mis-align indexes.
        self._mu = __import__("threading").RLock()

    def length(self, group: int) -> int:
        with self._mu:
            return self._start[group] + len(self._logs[group])

    def start(self, group: int) -> int:
        with self._mu:
            return self._start[group]

    def set_start(self, group: int, start: int, start_term: int) -> None:
        """Initialize the compaction floor on restart (from a WAL
        snapshot marker).  Only valid on an empty group log."""
        with self._mu:
            assert not self._logs[group]
            self._start[group] = start
            self._start_term[group] = start_term

    def reset(self, group: int, start: int, start_term: int) -> None:
        """Discard the group's entire log and restart it at `start` (the
        receiver side of InstallSnapshot: history before the snapshot is
        gone, and any suffix predating it may conflict)."""
        with self._mu:
            self._logs[group].clear()
            self._start[group] = start
            self._start_term[group] = start_term

    def compact(self, group: int, upto: int, boundary_term: int) -> None:
        """Drop entries <= upto (must be <= length)."""
        with self._mu:
            s = self._start[group]
            if upto <= s:
                return
            del self._logs[group][: upto - s]
            self._start[group] = upto
            self._start_term[group] = boundary_term

    def get(self, group: int, index: int) -> bytes:
        with self._mu:
            return self._logs[group][index - 1 - self._start[group]][1]

    def term_of(self, group: int, index: int) -> int:
        """Term of entry `index`; term_of(0) == 0 (the log-start
        sentinel), term_of(start) == the retained boundary term."""
        with self._mu:
            if index == 0:
                return 0
            s = self._start[group]
            if index == s:
                return self._start_term[group]
            # A negative list index would silently wrap to the tail.
            assert index > s, f"term_of below compaction floor ({index})"
            return self._logs[group][index - 1 - s][0]

    def try_term_of(self, group: int, index: int) -> Optional[int]:
        """term_of with a floor check instead of an assert: None when
        `index` sits at/below a concurrently advancing compaction floor
        or beyond the log — for client-thread callers (ReadIndex) that
        race the compactor and must degrade to a retry, not an
        AssertionError (or a wrapped negative index under python -O)."""
        with self._mu:
            if index == 0:
                return 0
            s = self._start[group]
            if index == s:
                return self._start_term[group]
            if index < s or index > s + len(self._logs[group]):
                return None
            return self._logs[group][index - 1 - s][0]

    def try_tail_with_terms(self, group: int, start: int, n: int):
        """Atomic (prev_term, [(term, payload)...]) for entries
        [start, start+n) — None if `start` has been compacted away.
        The single lock hold makes check + boundary-term + slice one
        consistent read against the concurrent compactor."""
        with self._mu:
            s0 = self._start[group]
            if start <= s0:
                return None
            if start - 1 == 0:
                prev_term = 0
            elif start - 1 == s0:
                prev_term = self._start_term[group]
            else:
                prev_term = self._logs[group][start - 2 - s0][0]
            rel = start - 1 - s0
            return prev_term, list(self._logs[group][rel: rel + n])

    def slice(self, group: int, start: int, n: int) -> List[bytes]:
        """Entry payloads [start, start+n), 1-based."""
        with self._mu:
            s = start - 1 - self._start[group]
            assert s >= 0, "slice below compaction floor"
            return [d for (_, d) in self._logs[group][s: s + n]]

    def try_slice(self, group: int, start: int, n: int
                  ) -> Optional[List[bytes]]:
        """Like slice, but None when [start, start+n) dips below the
        compaction floor — the floor moves concurrently (compactor
        thread), so check-then-slice must be one atomic operation."""
        with self._mu:
            s = start - 1 - self._start[group]
            if s < 0:
                return None
            return [d for (_, d) in self._logs[group][s: s + n]]

    def slice_with_terms(self, group: int, start: int, n: int
                         ) -> List[Tuple[int, bytes]]:
        with self._mu:
            s = start - 1 - self._start[group]
            assert s >= 0, "slice below compaction floor"
            return list(self._logs[group][s: s + n])

    def put(self, group: int, start: int, payloads: Sequence[bytes],
            terms: Sequence[int], new_len: Optional[int] = None) -> None:
        """Write (term, payload) at [start, start+len), extending or
        overwriting; then truncate to new_len if given (the
        conflict-truncation mirror of the device-side append in
        core/step.py Phase 4)."""
        with self._mu:
            self._put_locked(group, start, payloads, terms, new_len)

    def put_ranges(self, items) -> None:
        """Batched `put`: one lock acquisition for an iterable of
        (group, start, payloads, terms, new_len) tuples — the fused
        runtime writes O(groups) ranges per tick and the per-call lock
        round trip was a measurable slice of its WAL phase."""
        with self._mu:
            for (group, start, payloads, terms, new_len) in items:
                self._put_locked(group, start, payloads, terms, new_len)

    def _put_locked(self, group: int, start: int, payloads, terms,
                    new_len: Optional[int]) -> None:
        log = self._logs[group]
        off = self._start[group]
        if start - 1 - off == len(log):
            # Pure tail append — the leader/follower hot path (the
            # per-entry positioned loop below was the single largest
            # Python cost of the durable WAL phase at saturation).
            log.extend(zip(terms, payloads))
        else:
            for i, (term, data) in enumerate(zip(terms, payloads)):
                pos = start - 1 + i - off
                if pos < 0:
                    continue   # below the compaction floor: immutable
                if pos < len(log):
                    log[pos] = (term, data)
                elif pos == len(log):
                    log.append((term, data))
                else:
                    raise ValueError(
                        f"payload gap: group {group} idx "
                        f"{pos + 1 + off} > len {len(log) + off}")
        if new_len is not None and new_len - off < len(log):
            del log[max(new_len - off, 0):]
