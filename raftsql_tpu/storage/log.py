"""Host-side payload log: entry (term, bytes) per (group, index).

The device log (core/state.py) stores only the last-W entry *terms* in a
ring; the bytes of each proposal (SQL text) — and the full term history,
which the device ring forgets once an index slides out of the window —
live here, mirroring device log positions 1:1.  This splits the
reference's `raft.MemoryStorage` (reference raft.go:129, 229) into its two
real roles: ordering metadata (device) and bytes (host).

The full term history is what lets the leader's HOST build catch-up
AppendEntries for followers that have fallen more than W entries behind —
positions the device can no longer describe (runtime/node.py catch-up
path; the reference gets the same from MemoryStorage.Term, which etcd's
sendAppend consults before falling back to a snapshot).

Like MemoryStorage, growth is unbounded and never compacted — a documented
limitation shared with the reference; snapshots are the eventual fix for
both (reference db.go:27-29 declares the same).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


class PayloadLog:
    """1-based, truncate-on-conflict (term, bytes) log for G groups."""

    def __init__(self, num_groups: int):
        self._logs: List[List[Tuple[int, bytes]]] = [
            [] for _ in range(num_groups)]

    def length(self, group: int) -> int:
        return len(self._logs[group])

    def get(self, group: int, index: int) -> bytes:
        return self._logs[group][index - 1][1]

    def term_of(self, group: int, index: int) -> int:
        """Term of entry `index`; term_of(0) == 0 (the log-start sentinel)."""
        if index == 0:
            return 0
        return self._logs[group][index - 1][0]

    def slice(self, group: int, start: int, n: int) -> List[bytes]:
        """Entry payloads [start, start+n), 1-based."""
        return [d for (_, d) in self._logs[group][start - 1: start - 1 + n]]

    def slice_with_terms(self, group: int, start: int, n: int
                         ) -> List[Tuple[int, bytes]]:
        return list(self._logs[group][start - 1: start - 1 + n])

    def put(self, group: int, start: int, payloads: Sequence[bytes],
            terms: Sequence[int], new_len: Optional[int] = None) -> None:
        """Write (term, payload) at [start, start+len), extending or
        overwriting; then truncate to new_len if given (the
        conflict-truncation mirror of the device-side append in
        core/step.py Phase 4)."""
        log = self._logs[group]
        for i, (term, data) in enumerate(zip(terms, payloads)):
            pos = start - 1 + i
            if pos < len(log):
                log[pos] = (term, data)
            elif pos == len(log):
                log.append((term, data))
            else:
                raise ValueError(
                    f"payload gap: group {group} idx {pos + 1} > "
                    f"len {len(log)}")
        if new_len is not None and new_len < len(log):
            del log[new_len:]
