"""Host-side payload log: entry bytes per (group, index).

The device log (core/state.py) stores only entry *terms*; the bytes of
each proposal (SQL text) live here, mirroring device log positions 1:1.
This splits the reference's `raft.MemoryStorage` (reference raft.go:129,
229) into its two real roles: ordering metadata (device) and bytes (host).

Like MemoryStorage, growth is unbounded and never compacted — a documented
limitation shared with the reference; snapshots are the eventual fix for
both (reference db.go:27-29 declares the same).
"""
from __future__ import annotations

from typing import Dict, List, Optional


class PayloadLog:
    """1-based, truncate-on-conflict byte log for G groups."""

    def __init__(self, num_groups: int):
        self._logs: List[List[bytes]] = [[] for _ in range(num_groups)]

    def length(self, group: int) -> int:
        return len(self._logs[group])

    def get(self, group: int, index: int) -> bytes:
        return self._logs[group][index - 1]

    def slice(self, group: int, start: int, n: int) -> List[bytes]:
        """Entries [start, start+n), 1-based."""
        return self._logs[group][start - 1: start - 1 + n]

    def put(self, group: int, start: int, payloads: List[bytes],
            new_len: Optional[int] = None) -> None:
        """Write payloads at [start, start+len), extending/overwriting; then
        truncate to new_len if given (the conflict-truncation mirror of the
        device-side append in core/step.py Phase 4)."""
        log = self._logs[group]
        for i, data in enumerate(payloads):
            pos = start - 1 + i
            if pos < len(log):
                log[pos] = data
            elif pos == len(log):
                log.append(data)
            else:
                raise ValueError(
                    f"payload gap: group {group} idx {pos + 1} > "
                    f"len {len(log)}")
        if new_len is not None and new_len < len(log):
            del log[new_len:]

    def append(self, group: int, payloads: List[bytes]) -> int:
        """Append at the tail; returns the new length."""
        self._logs[group].extend(payloads)
        return len(self._logs[group])
