"""Host-side payload log: entry (term, bytes) per (group, index).

The device log (core/state.py) stores only the last-W entry *terms* in a
ring; the bytes of each proposal (SQL text) — and the full term history,
which the device ring forgets once an index slides out of the window —
live here, mirroring device log positions 1:1.  This splits the
reference's `raft.MemoryStorage` (reference raft.go:129, 229) into its two
real roles: ordering metadata (device) and bytes (host).

The full term history is what lets the leader's HOST build catch-up
AppendEntries for followers that have fallen more than W entries behind —
positions the device can no longer describe (runtime/node.py catch-up
path; the reference gets the same from MemoryStorage.Term, which etcd's
sendAppend consults before falling back to a snapshot).

Storage layout is COLUMNAR: parallel per-group term and payload lists,
not a list of (term, bytes) tuples.  The hot paths — publish slicing
payloads for every committed range, and the durable tick appending a
batch per active group — then cost one C-level list slice/extend each,
with no per-entry tuple construction (measured: the tuple layout's
put/slice pair was a double-digit share of the fused durable tick).

Like MemoryStorage, growth is unbounded unless compacted (`compact`, fed
by state-machine snapshots — runtime/db.py / runtime/fused.py); parity
deployments never compact, same documented limitation as the reference
(db.go:27-29).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


class PayloadLog:
    """1-based, truncate-on-conflict (term, bytes) log for G groups.

    After `compact(g, upto, term)`, entries at or below `upto` are
    dropped; `start(g)` reports the floor and `term_of(g, start)` still
    resolves (the boundary term is retained) so AppendEntries prev-term
    checks at the compaction edge work."""

    def __init__(self, num_groups: int):
        self._terms: List[List[int]] = [[] for _ in range(num_groups)]
        self._datas: List[List[bytes]] = [[] for _ in range(num_groups)]
        self._start: List[int] = [0] * num_groups
        self._start_term: List[int] = [0] * num_groups
        # One lock: readers (publish, catch-up, send) race the compactor,
        # and a torn (_start, lists) read would mis-align indexes.
        self._mu = __import__("threading").RLock()

    def length(self, group: int) -> int:
        with self._mu:
            return self._start[group] + len(self._datas[group])

    def start(self, group: int) -> int:
        with self._mu:
            return self._start[group]

    def set_start(self, group: int, start: int, start_term: int) -> None:
        """Initialize the compaction floor on restart (from a WAL
        snapshot marker).  Only valid on an empty group log."""
        with self._mu:
            assert not self._datas[group]
            self._start[group] = start
            self._start_term[group] = start_term

    def reset(self, group: int, start: int, start_term: int) -> None:
        """Discard the group's entire log and restart it at `start` (the
        receiver side of InstallSnapshot: history before the snapshot is
        gone, and any suffix predating it may conflict)."""
        with self._mu:
            self._terms[group].clear()
            self._datas[group].clear()
            self._start[group] = start
            self._start_term[group] = start_term

    def compact(self, group: int, upto: int, boundary_term: int) -> None:
        """Drop entries <= upto (must be <= length)."""
        with self._mu:
            s = self._start[group]
            if upto <= s:
                return
            del self._terms[group][: upto - s]
            del self._datas[group][: upto - s]
            self._start[group] = upto
            self._start_term[group] = boundary_term

    def get(self, group: int, index: int) -> bytes:
        with self._mu:
            return self._datas[group][index - 1 - self._start[group]]

    def term_of(self, group: int, index: int) -> int:
        """Term of entry `index`; term_of(0) == 0 (the log-start
        sentinel), term_of(start) == the retained boundary term."""
        with self._mu:
            if index == 0:
                return 0
            s = self._start[group]
            if index == s:
                return self._start_term[group]
            # A negative list index would silently wrap to the tail.
            assert index > s, f"term_of below compaction floor ({index})"
            return self._terms[group][index - 1 - s]

    def try_term_of(self, group: int, index: int) -> Optional[int]:
        """term_of with a floor check instead of an assert: None when
        `index` sits at/below a concurrently advancing compaction floor
        or beyond the log — for client-thread callers (ReadIndex) that
        race the compactor and must degrade to a retry, not an
        AssertionError (or a wrapped negative index under python -O)."""
        with self._mu:
            if index == 0:
                return 0
            s = self._start[group]
            if index == s:
                return self._start_term[group]
            if index < s or index > s + len(self._terms[group]):
                return None
            return self._terms[group][index - 1 - s]

    def try_tail_with_terms(self, group: int, start: int, n: int):
        """Atomic (prev_term, [(term, payload)...]) for entries
        [start, start+n) — None if `start` has been compacted away.
        The single lock hold makes check + boundary-term + slice one
        consistent read against the concurrent compactor."""
        with self._mu:
            s0 = self._start[group]
            if start <= s0:
                return None
            if start - 1 == 0:
                prev_term = 0
            elif start - 1 == s0:
                prev_term = self._start_term[group]
            else:
                prev_term = self._terms[group][start - 2 - s0]
            rel = start - 1 - s0
            return prev_term, list(zip(self._terms[group][rel: rel + n],
                                       self._datas[group][rel: rel + n]))

    def slice(self, group: int, start: int, n: int) -> List[bytes]:
        """Entry payloads [start, start+n), 1-based — one C-level list
        slice, the publish hot path."""
        with self._mu:
            s = start - 1 - self._start[group]
            assert s >= 0, "slice below compaction floor"
            return self._datas[group][s: s + n]

    def try_slice(self, group: int, start: int, n: int
                  ) -> Optional[List[bytes]]:
        """Like slice, but None when [start, start+n) dips below the
        compaction floor — the floor moves concurrently (compactor
        thread), so check-then-slice must be one atomic operation."""
        with self._mu:
            s = start - 1 - self._start[group]
            if s < 0:
                return None
            return self._datas[group][s: s + n]

    def slice_columns(self, group: int, start: int, n: int
                      ) -> Tuple[List[int], List[bytes]]:
        """(terms, payloads) for [start, start+n) as two C-level list
        slices — the mirror hot path (runtime/fused.py); a tuple-zipping
        variant of this accessor was the second-largest per-entry cost
        of the durable tick."""
        with self._mu:
            s = start - 1 - self._start[group]
            assert s >= 0, "slice below compaction floor"
            return (self._terms[group][s: s + n],
                    self._datas[group][s: s + n])

    def put(self, group: int, start: int, payloads: Sequence[bytes],
            terms: Sequence[int], new_len: Optional[int] = None) -> None:
        """Write (term, payload) at [start, start+len), extending or
        overwriting; then truncate to new_len if given (the
        conflict-truncation mirror of the device-side append in
        core/step.py Phase 4)."""
        with self._mu:
            self._put_locked(group, start, payloads, terms, new_len)

    def put_ranges(self, items) -> None:
        """Batched `put`: one lock acquisition for an iterable of
        (group, start, payloads, terms, new_len) tuples — the fused
        runtime writes O(groups) ranges per tick and the per-call lock
        round trip was a measurable slice of its WAL phase."""
        with self._mu:
            for (group, start, payloads, terms, new_len) in items:
                self._put_locked(group, start, payloads, terms, new_len)

    def _put_locked(self, group: int, start: int, payloads, terms,
                    new_len: Optional[int]) -> None:
        tl, dl = self._terms[group], self._datas[group]
        off = self._start[group]
        rel = start - 1 - off
        # The parallel lists corrupt silently if they ever diverge (the
        # old tuple layout couldn't): refuse mismatched inputs here.
        assert len(terms) == len(payloads), (len(terms), len(payloads))
        if rel == len(dl):
            # Pure tail append — the leader/follower hot path: two
            # C-level extends, zero per-entry Python.
            tl.extend(terms)
            dl.extend(payloads)
        else:
            n = len(payloads)
            if rel >= 0 and rel + n <= len(dl):
                # In-place overwrite (conflict suffix replacement).
                tl[rel: rel + n] = terms
                dl[rel: rel + n] = payloads
            else:
                for i in range(n):
                    pos = rel + i
                    if pos < 0:
                        continue   # below the compaction floor: immutable
                    if pos < len(dl):
                        tl[pos] = terms[i]
                        dl[pos] = payloads[i]
                    elif pos == len(dl):
                        tl.append(terms[i])
                        dl.append(payloads[i])
                    else:
                        raise ValueError(
                            f"payload gap: group {group} idx "
                            f"{pos + 1 + off} > len {len(dl) + off}")
        if new_len is not None and new_len - off < len(dl):
            del tl[max(new_len - off, 0):]
            del dl[max(new_len - off, 0):]


class NativePayloadLog:
    """ctypes-backed PayloadLog (native/wal.cc `Plog`): same surface,
    entry bytes live in C++.  Paired with WAL.append_ranges_uniform and
    storage.wal.wal_mirror_all, the fused runtime's payload plane does
    no per-entry Python at all on the write side; reads (publish,
    replay, catch-up) come back as one blob + lens and split into bytes
    objects only where a consumer needs them."""

    def __init__(self, num_groups: int, lib):
        import ctypes
        self._c = ctypes
        self._lib = lib
        self._h = lib.plog_new(num_groups)
        self._G = num_groups

    @property
    def handle(self):
        return self._h

    def close(self) -> None:
        if self._h:
            self._lib.plog_free(self._h)
            self._h = None

    def length(self, group: int) -> int:
        return int(self._lib.plog_length(self._h, group))

    def start(self, group: int) -> int:
        return int(self._lib.plog_start(self._h, group))

    def set_start(self, group: int, start: int, start_term: int) -> None:
        rc = self._lib.plog_set_start(self._h, group, start, start_term)
        if rc != 0:
            raise RuntimeError("set_start on non-empty group")

    def term_of(self, group: int, index: int) -> int:
        t = int(self._lib.plog_term_of(self._h, group, index))
        if t == (1 << 64) - 1:      # explicit: survives python -O
            raise IndexError(f"term_of out of range (g{group} "
                             f"idx {index})")
        return t

    def try_term_of(self, group: int, index: int) -> Optional[int]:
        t = int(self._lib.plog_term_of(self._h, group, index))
        return None if t == (1 << 64) - 1 else t

    def compact(self, group: int, upto: int, boundary_term: int) -> None:
        rc = self._lib.plog_compact(self._h, group, upto, boundary_term)
        if rc != 0:
            raise RuntimeError(f"compact past tail (g{group} "
                               f"upto {upto})")

    def put(self, group: int, start: int, payloads: Sequence[bytes],
            terms: Sequence[int], new_len: Optional[int] = None) -> None:
        import numpy as np
        c = self._c
        n = len(payloads)
        blob = b"".join(payloads)
        lens = np.fromiter(map(len, payloads), np.uint32, n)
        ta = np.asarray(terms, np.uint64)
        rc = self._lib.plog_put_range(
            self._h, group, start, n,
            ta.ctypes.data_as(c.POINTER(c.c_uint64)), blob,
            lens.ctypes.data_as(c.POINTER(c.c_uint32)),
            -1 if new_len is None else new_len)
        if rc != 0:
            raise ValueError(f"payload gap: group {group} at {start}")

    def put_ranges(self, items) -> None:
        for (group, start, payloads, terms, new_len) in items:
            self.put(group, start, payloads, terms, new_len)

    def _read(self, group: int, start: int, n: int, want_terms: bool):
        import numpy as np
        c = self._c
        total = int(self._lib.plog_range_bytes(self._h, group, start, n))
        if total == (1 << 64) - 1:
            return None
        blob = c.create_string_buffer(total)
        lens = np.zeros(n, np.uint32)
        terms = np.zeros(n, np.uint64) if want_terms else None
        rc = self._lib.plog_read_range(
            self._h, group, start, n,
            c.cast(blob, c.POINTER(c.c_uint8)),
            lens.ctypes.data_as(c.POINTER(c.c_uint32)),
            terms.ctypes.data_as(c.POINTER(c.c_uint64))
            if want_terms else None)
        if rc != 0:
            return None
        raw = blob.raw
        out, off = [], 0
        for ln in lens.tolist():
            out.append(raw[off: off + ln])
            off += ln
        return (out, terms.tolist()) if want_terms else out

    def slice(self, group: int, start: int, n: int) -> List[bytes]:
        got = self._read(group, start, n, want_terms=False)
        if got is None:             # explicit: survives python -O
            raise RuntimeError("slice below compaction floor")
        return got

    def try_slice(self, group: int, start: int, n: int
                  ) -> Optional[List[bytes]]:
        return self._read(group, start, n, want_terms=False)

    def read_groups(self, groups, starts, counts):
        """Batched multi-range read: [(payloads...)] per range, in TWO
        ctypes calls total — the publish hot path reads every ready
        group of a tick at once (per-range ctypes calls cost more than
        the payloads themselves)."""
        import numpy as np
        c = self._c
        n_ranges = len(groups)
        ga = np.asarray(groups, np.uint32)
        sa = np.asarray(starts, np.uint64)
        ca = np.asarray(counts, np.uint32)
        gp = ga.ctypes.data_as(c.POINTER(c.c_uint32))
        sp = sa.ctypes.data_as(c.POINTER(c.c_uint64))
        cp = ca.ctypes.data_as(c.POINTER(c.c_uint32))
        total = int(self._lib.plog_ranges_bytes(self._h, n_ranges,
                                                gp, sp, cp))
        if total == (1 << 64) - 1:  # explicit: survives python -O
            raise RuntimeError("read_groups: range below compaction "
                               "floor or past tail")
        blob = c.create_string_buffer(total)
        n_entries = int(ca.sum())
        lens = np.zeros(n_entries, np.uint32)
        rc = self._lib.plog_read_groups(
            self._h, n_ranges, gp, sp, cp,
            c.cast(blob, c.POINTER(c.c_uint8)),
            lens.ctypes.data_as(c.POINTER(c.c_uint32)))
        if rc != 0:                 # explicit: survives python -O
            raise RuntimeError("read_groups raced a truncation")
        raw = blob.raw
        out, off, li = [], 0, 0
        lens_l = lens.tolist()
        for cnt in ca.tolist():
            datas = []
            for _ in range(cnt):
                ln = lens_l[li]
                datas.append(raw[off: off + ln])
                off += ln
                li += 1
            out.append(datas)
        return out

    def slice_columns(self, group: int, start: int, n: int
                      ) -> Tuple[List[int], List[bytes]]:
        got = self._read(group, start, n, want_terms=True)
        if got is None:             # explicit: survives python -O
            raise RuntimeError("slice below compaction floor")
        datas, terms = got
        return terms, datas
