"""Durable write-ahead log, multi-group, host-side.

Replaces the reference's vendored `etcd/wal` (reference raft.go:33-34,
99-134): an append-only record log that persists raft entries and hard
state *before* peer messages are sent or commits published (the durability
ordering invariant, reference raft.go:227-235), and is fully replayed on
restart (reference raft.go:122-134).

Differences from etcd/wal, by design:
  - One WAL serves ALL raft groups of a node; records carry a group id, so
    a single fsync batches the tick's appends across every group — the
    group-commit analog of batching consensus math on device.
  - Records are fixed-layout little-endian structs (struct-of-arrays
    friendly, shared with the C++ fast path in native/wal.cc, loaded via
    storage.native_wal when built).

Record layout:  u32 crc32(body) | u32 body_len | body
  body := u8 type | fields
  type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
  type 2 HARDSTATE: u32 group | u64 term | i64 vote | u64 commit

Replay semantics match raft's log-matching property: a later ENTRY record
at an index <= the current length with the SAME term is an idempotent
overwrite (a re-accepted duplicate append — same index+term implies same
entry), while a DIFFERENT term is a genuine conflict and truncates the
suffix from that index before appending (core/step.py Phase 4).  Truncating
on same-term overlap would silently drop durably-acked suffix entries when
a stale duplicate append covering only a prefix is re-accepted.  The last
HARDSTATE per group wins.  A torn tail (bad CRC / short read) is dropped,
like etcd's repair path.
"""
from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_HDR = struct.Struct("<II")          # crc, body_len
_ENTRY = struct.Struct("<BIQQ")      # type, group, index, term
_HARD = struct.Struct("<BIQqQ")      # type, group, term, vote, commit

REC_ENTRY = 1
REC_HARDSTATE = 2

WAL_FILE = "wal-0.log"


@dataclass
class HardState:
    term: int = 0
    vote: int = -1
    commit: int = 0


@dataclass
class GroupLog:
    """Replayed per-group state: 1-based entries plus last hard state."""
    hard: HardState = field(default_factory=HardState)
    entries: List[Tuple[int, bytes]] = field(default_factory=list)  # (term, data)

    @property
    def log_len(self) -> int:
        return len(self.entries)


def wal_exists(dirname: str) -> bool:
    return os.path.isfile(os.path.join(dirname, WAL_FILE))


class WAL:
    """Append-only multi-group WAL with batched fsync.

    Usage per tick (the reference's Ready handling, raft.go:227-235):
        wal.begin()
        wal.append_entry(...); wal.set_hardstate(...)
        wal.sync()              # durable point — only now send/publish
    """

    def __init__(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        self.path = os.path.join(dirname, WAL_FILE)
        self._f = open(self.path, "ab")
        self._pending = False

    # -- write path ------------------------------------------------------

    def _write(self, body: bytes) -> None:
        self._f.write(_HDR.pack(zlib.crc32(body), len(body)))
        self._f.write(body)
        self._pending = True

    def append_entry(self, group: int, index: int, term: int,
                     data: bytes) -> None:
        self._write(_ENTRY.pack(REC_ENTRY, group, index, term) + data)

    def set_hardstate(self, group: int, term: int, vote: int,
                      commit: int) -> None:
        self._write(_HARD.pack(REC_HARDSTATE, group, term, vote, commit))

    def sync(self) -> None:
        if self._pending:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._pending = False

    def close(self) -> None:
        self.sync()
        self._f.close()

    # -- replay ----------------------------------------------------------

    @staticmethod
    def replay(dirname: str) -> Dict[int, GroupLog]:
        """Read the WAL back into per-group logs; tolerate a torn tail."""
        groups: Dict[int, GroupLog] = {}
        path = os.path.join(dirname, WAL_FILE)
        if not os.path.isfile(path):
            return groups
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        while off + _HDR.size <= len(blob):
            crc, blen = _HDR.unpack_from(blob, off)
            body = blob[off + _HDR.size: off + _HDR.size + blen]
            if len(body) != blen or zlib.crc32(body) != crc:
                break               # torn tail — drop the rest
            off += _HDR.size + blen
            rtype = body[0]
            if rtype == REC_ENTRY:
                _, group, index, term = _ENTRY.unpack_from(body)
                data = body[_ENTRY.size:]
                gl = groups.setdefault(group, GroupLog())
                if 1 <= index <= len(gl.entries):
                    if gl.entries[index - 1][0] == term:
                        gl.entries[index - 1] = (term, data)
                    else:                            # conflict truncation
                        del gl.entries[index - 1:]
                        gl.entries.append((term, data))
                elif index == len(gl.entries) + 1:
                    gl.entries.append((term, data))
                # else: a gap would mean WAL corruption; skip the record.
            elif rtype == REC_HARDSTATE:
                _, group, term, vote, commit = _HARD.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                gl.hard = HardState(term=term, vote=vote, commit=commit)
        return groups
