"""Durable write-ahead log, multi-group, host-side, segmented.

Replaces the reference's vendored `etcd/wal` (reference raft.go:33-34,
99-134): an append-only record log that persists raft entries and hard
state *before* peer messages are sent or commits published (the durability
ordering invariant, reference raft.go:227-235), and is fully replayed on
restart (reference raft.go:122-134).

Differences from etcd/wal, by design:
  - One WAL serves ALL raft groups of a node; records carry a group id, so
    a single fsync batches the tick's appends across every group — the
    group-commit analog of batching consensus math on device.
  - Records are fixed-layout little-endian structs (struct-of-arrays
    friendly, shared with the C++ fast path in native/wal.cc, loaded via
    storage.native_wal when built).

Segmentation (the same shape as etcd/wal's segment directory, which the
reference opens at raft.go:99-117): the log is a directory of bounded
files `wal-<seq>.log`; appends go to the highest sequence ("active")
segment, a segment that exceeds `segment_bytes` is closed at the next
sync boundary and a fresh one opened.  Compaction never rewrites live
data: it appends per-group COMPACT floor markers to the active segment,
then unlinks whole closed segments whose every record is superseded —
O(appended markers + unlink), not O(log).  Replay concatenates segments
in sequence order, so the byte format within each segment is exactly the
single-file format (the C++ fast path is unchanged per segment).

Record layout:  u32 crc32(body) | u32 body_len | body
  body := u8 type | fields
  type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
  type 2 HARDSTATE: u32 group | u64 term | i64 vote | u64 commit
  type 3 SNAPSHOT:  u32 group | u64 index | u64 term
  type 4 COMPACT:   u32 group | u64 index | u64 term
  type 5 RANGE:     u32 group | u64 start | u64 term | u32 count
                    | u32 lens[count] | bytes payloads (concatenated)

RANGE is the batched form the fused tick writes: one record per
(group, start, term) run of consecutive same-term entries at
start .. start+count-1, with the 8-byte frame + 21-byte entry header
amortized across the run (per-entry framing tripled the durable tick's
fsync bytes at G=10k).  Replay expands a RANGE to exactly the entry
sequence its per-entry form would have produced.

Replay semantics match raft's log-matching property: a later ENTRY record
at an index <= the current length with the SAME term is an idempotent
overwrite (a re-accepted duplicate append — same index+term implies same
entry), while a DIFFERENT term is a genuine conflict and truncates the
suffix from that index before appending (core/step.py Phase 4).  Truncating
on same-term overlap would silently drop durably-acked suffix entries when
a stale duplicate append covering only a prefix is re-accepted.  The last
HARDSTATE per group wins.  SNAPSHOT (an InstallSnapshot boundary) drops
the covered prefix AND the retained suffix — the installed state's
history may conflict with it; COMPACT (a local compaction floor) drops
only the covered prefix.  A torn record (bad CRC / short read) drops
everything from that point on — only the active segment's tail can
legitimately be torn.
"""
from __future__ import annotations

import os
import re
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_HDR = struct.Struct("<II")          # crc, body_len
_ENTRY = struct.Struct("<BIQQ")      # type, group, index, term
_HARD = struct.Struct("<BIQqQ")      # type, group, term, vote, commit
_SNAP = struct.Struct("<BIQQ")       # type, group, index, term (also COMPACT)
_RANGE = struct.Struct("<BIQQI")     # type, group, start, term, count
_EPOCH = struct.Struct("<BBQ")       # type, kind (0 BEGIN / 1 END), no
_CONFREC = struct.Struct("<BIQBQQQ")  # type, group, index, kind,
#                                       voters, joint, learners (u64
#                                       slot bitmasks — membership/)
_DEDUPHDR = struct.Struct("<BIQI")   # type, group, floor_index, count
_DEDUPPAIR = struct.Struct("<QQ")    # applied index, proposal id

REC_ENTRY = 1
REC_HARDSTATE = 2
REC_SNAPSHOT = 3        # install boundary: entries <= index AND the
#                         retained suffix dropped (conflicting history)
REC_COMPACT = 4         # compaction floor: entries <= index dropped,
#                         retained suffix kept
REC_RANGE = 5           # batched same-term entry run (see module doc)
REC_EPOCH = 6           # multi-step dispatch frame marker (see
                        # runtime/fused.py steps_per_dispatch): kind 0 =
                        # BEGIN, 1 = END, + the dispatch's epoch number.
                        # Replay ignores these; repair_epochs() uses
                        # BEGIN markers to atomically drop an
                        # uncommitted dispatch after a crash.
REC_CONF = 7            # applied membership configuration baseline
                        # (raftsql_tpu/membership/): written when a
                        # committed conf-change entry APPLIES, carrying
                        # the entry's log index + the full config
                        # (kind, voter/joint/learner u64 bitmasks).
                        # Replay keeps the last one per group; restart
                        # recovery seeds the active config from it and
                        # re-applies any conf ENTRIES committed above
                        # it — so the active config survives even after
                        # compaction unlinks the entries that built it.
REC_DEDUP = 8           # forward-retry dedup baseline (set_dedup): the
                        # group's (applied_index, proposal_id) window
                        # pairs at or below a compaction/install floor.
                        # The dedup decision is a pure function of the
                        # committed log PREFIX (runtime/envelope.py) —
                        # compaction drops that prefix, so without this
                        # record a restarted node replays only the
                        # retained suffix and re-applies a forward-retry
                        # duplicate whose first copy fell below the
                        # floor while live peers scrub it (divergence).
                        # Replay keeps the highest-floor record per
                        # group; boot restores it into the DedupWindow
                        # BEFORE publishing the retained suffix.

_SEG_RE = re.compile(r"^wal-(\d+)\.log$")
# Single source of truth for the default lives in config (the CLI and
# RaftConfig share it).
from raftsql_tpu.config import \
    WAL_SEGMENT_BYTES_DEFAULT as DEFAULT_SEGMENT_BYTES  # noqa: E402
from raftsql_tpu.storage import fsio  # noqa: E402


def _segment_paths(dirname: str) -> List[Tuple[int, str]]:
    """[(seq, abspath)] of existing segments, sequence order."""
    out = []
    try:
        names = os.listdir(dirname)
    except FileNotFoundError:
        return []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(dirname, n)))
    out.sort()
    return out


def _fsync_dir(dirname: str) -> None:
    fsio.fsync_dir(dirname)


@dataclass
class HardState:
    term: int = 0
    vote: int = -1
    commit: int = 0


@dataclass
class GroupLog:
    """Replayed per-group state: entries (start+1 ... start+len, 1-based)
    plus last hard state.  `start` > 0 after WAL compaction — the prefix
    up to `start` is covered by the state-machine snapshot; `start_term`
    is the boundary entry's term."""
    hard: HardState = field(default_factory=HardState)
    entries: List[Tuple[int, bytes]] = field(default_factory=list)  # (term, data)
    start: int = 0
    start_term: int = 0
    # Last applied-membership baseline (REC_CONF), or None:
    # (entry_index, kind, voters_mask, joint_mask, learners_mask).
    conf: Optional[Tuple[int, int, int, int, int]] = None
    # Highest-floor dedup baseline (REC_DEDUP), or None:
    # (floor_index, [(applied_index, proposal_id), ...] FIFO order).
    dedup: Optional[Tuple[int, List[Tuple[int, int]]]] = None

    @property
    def log_len(self) -> int:
        return self.start + len(self.entries)


@dataclass
class _SegStats:
    """What a closed segment contains, for deletability decisions:
    per-group max index referenced by ENTRY/SNAPSHOT/COMPACT records, and
    the set of groups with HARDSTATE records."""
    max_idx: Dict[int, int] = field(default_factory=dict)
    hs: Set[int] = field(default_factory=set)

    def bump(self, group: int, index: int) -> None:
        if index > self.max_idx.get(group, -1):
            self.max_idx[group] = index

    def groups(self) -> Set[int]:
        return set(self.max_idx) | self.hs


def split_uniform_runs(start: int, terms) -> List[Tuple[int, int, int]]:
    """(start, count, term) uniform-term runs covering positions
    start..start+len(terms)-1 — the shape RANGE records require.
    Mirrored batches cross terms only at elections, so the common case
    is ONE run; the boundary scan is vectorized, no per-entry Python."""
    import numpy as np
    n = len(terms)
    if n == 0:
        return []
    ta = np.asarray(terms)
    bnd = np.flatnonzero(np.diff(ta))
    if not bnd.size:
        return [(start, n, int(ta[0]))]
    edges = [0] + (bnd + 1).tolist() + [n]
    return [(start + a, b - a, int(ta[a]))
            for a, b in zip(edges[:-1], edges[1:])]


def wal_mirror_all(wals, plogs, peers, srcs, groups, starts, counts,
                   new_lens) -> bool:
    """Cluster-wide follower mirror in ONE native call
    (walplog_mirror_all): phase A stages every source range (the
    read-all-before-write-all contract that makes same-tick source
    truncation safe), phase B writes each destination peer's WAL ENTRY
    records + payload-log range + truncation.  Returns False when the
    native path is unavailable on any peer (caller falls back).

    Destination WALs may be group-commit views (GroupCommitWAL below):
    their `group_bias` flattens the record's group id into the shared
    multiplexed stream, applied on the WAL side only."""
    if not wals:
        return True
    lib = wals[0]._lib
    if lib is None or not hasattr(lib, "walplog_mirror_all"):
        return False
    if any(w._lib is None for w in wals) \
            or any(not hasattr(p, "handle") for p in plogs):
        return False
    import ctypes

    import numpy as np
    n = len(peers)
    if n == 0:
        return True
    P = len(wals)
    wh = (ctypes.c_void_p * P)(*[w._h for w in wals])
    ph = (ctypes.c_void_p * P)(*[p.handle for p in plogs])
    biases = np.asarray([getattr(w, "group_bias", 0) for w in wals],
                        np.uint32)
    pa = np.asarray(peers, np.uint32)
    sa = np.asarray(srcs, np.uint32)
    ga = np.asarray(groups, np.uint32)
    ia = np.asarray(starts, np.uint64)
    ca = np.asarray(counts, np.uint32)
    na = np.asarray(new_lens, np.int64)
    per_bytes = np.zeros(P, np.uint64)
    rc = lib.walplog_mirror_all(
        wh, ph, n,
        pa.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        sa.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ia.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ca.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        na.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        per_bytes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        biases.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    if rc != 0:
        raise ValueError("walplog_mirror_all: source range unavailable")
    for i in range(n):
        c = int(ca[i])
        if c:
            w = wals[int(pa[i])]
            w._active_stats.bump(
                int(ga[i]) + int(biases[int(pa[i])]), int(ia[i]) + c - 1)
    for p in range(P):
        b = int(per_bytes[p])
        if b:
            wals[p]._pending = True
            wals[p]._bytes += b
    return True


def wal_exists(dirname: str) -> bool:
    return bool(_segment_paths(dirname))


class WAL:
    """Append-only segmented multi-group WAL with batched fsync.

    Usage per tick (the reference's Ready handling, raft.go:227-235):
        wal.append_entry(...); wal.set_hardstate(...)
        wal.sync()              # durable point — only now send/publish

    The write path prefers the C++ fast path (native/wal.cc — framing,
    CRC, buffered write, fdatasync behind one ctypes call) and falls back
    to pure Python; both produce byte-identical files, and `replay` reads
    either.  `native=None` auto-detects; True/False force.

    NOT thread-safe: callers serialize all writes, sync, and compact (the
    node holds its _wal_lock across every call).
    """

    def __init__(self, dirname: str, native: Optional[bool] = None,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        os.makedirs(dirname, exist_ok=True)
        self.dirname = dirname
        self.segment_bytes = segment_bytes
        segs = _segment_paths(dirname)
        self._seq = segs[-1][0] if segs else 0
        self.path = os.path.join(dirname, f"wal-{self._seq}.log")
        self._native_pref = native
        self._lib = None
        self._h = None
        self._f = None
        self._pending = False
        self.last_sync_s = 0.0
        # Observability hook (raftsql_tpu/obs/spans.py SpanTracer, or
        # anything with note_event): wired by the owning runtime's
        # enable_tracing so every durable barrier lands on the host
        # trace timeline.  None (default) costs one attribute test.
        self.obs = None
        # A crash can tear the active segment's tail.  Appending AFTER
        # torn bytes would hide every later record from replay (it stops
        # at the first bad CRC) — durably-acked writes would vanish on the
        # next restart.  Truncate to the last whole record before opening
        # for append (etcd's repair path does the same).
        self._bytes = self._repair_tail(self.path)
        # Active-segment stats accumulate as we write; closed segments
        # written before this process are scanned lazily (compact()).
        self._active_stats = _SegStats()
        self._closed_stats: Dict[str, _SegStats] = {}
        self._marker_floor: Dict[int, int] = {}
        # Latest applied-membership baseline per group (set_conf),
        # re-asserted into the active segment when compaction unlinks
        # the segment that held it — same survival contract as hard
        # states.  Seeded by the owning runtime after replay (set_conf
        # is idempotent), not by this handle.
        self._conf_latest: Dict[int, Tuple[int, int, int, int, int]] = {}
        # Latest dedup baseline per group (set_dedup), kept as the
        # packed record body so compaction's re-assert is a plain
        # re-append — same survival contract as _conf_latest.
        self._dedup_latest: Dict[int, bytes] = {}
        self._open_active()

    @staticmethod
    def _repair_tail(path: str) -> int:
        """Truncate `path` to its longest valid record prefix; returns
        the resulting size (0 for a missing file)."""
        if not os.path.isfile(path):
            return 0
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        while off + _HDR.size <= len(blob):
            crc, blen = _HDR.unpack_from(blob, off)
            body = blob[off + _HDR.size: off + _HDR.size + blen]
            if len(body) != blen or zlib.crc32(body) != crc:
                break
            off += _HDR.size + blen
        if off < len(blob):
            with open(path, "r+b") as f:
                f.truncate(off)
                f.flush()
                os.fsync(f.fileno())
        return off

    def _open_active(self) -> None:
        # An active storage-fault injector (chaos scenarios) forces the
        # Python backend: the C++ fast path frames and fdatasyncs behind
        # one ctypes call, invisible to the fsio seam.  Both backends
        # write byte-identical files.
        if self._native_pref is not False and not fsio.active():
            from raftsql_tpu.native.build import load_native_wal
            lib = load_native_wal()
            if lib is not None:
                h = lib.wal_open(self.path.encode())
                if h:
                    self._lib, self._h = lib, h
            if self._native_pref is True and self._lib is None:
                raise RuntimeError("native WAL requested but unavailable")
        self._f = None if self._lib else open(self.path, "ab")

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    # -- write path ------------------------------------------------------

    def _write(self, body: bytes) -> None:
        # One write per record (not header-then-body): the fsio seam
        # records it whole, so a simulated torn write tears a RECORD —
        # the shape a real power loss leaves.  A write failure (ENOSPC
        # through fsio.check_write) raises BEFORE any byte lands and
        # BEFORE _pending/_bytes advance, so the refused record leaves
        # the file tail at a clean record boundary and the in-memory
        # bookkeeping matched to it — the caller surfaces the error
        # (the runtimes treat it as fatal, like a failed fsync) and a
        # restart replays a consistent log.
        fsio.write(self._f, _HDR.pack(zlib.crc32(body), len(body)) + body)
        self._pending = True
        self._bytes += _HDR.size + len(body)

    def append_entry(self, group: int, index: int, term: int,
                     data: bytes) -> None:
        self._active_stats.bump(group, index)
        if self._lib is not None:
            self._lib.wal_append_entry(self._h, group, index, term,
                                       data, len(data))
            self._pending = True
            self._bytes += _HDR.size + _ENTRY.size + len(data)
            return
        self._write(_ENTRY.pack(REC_ENTRY, group, index, term) + data)

    def append_entries(self, groups, indexes, terms, datas) -> None:
        """Batched append — one native call for a whole tick's records.

        Callers (the tick's WAL phase) emit per-group ranges with
        ascending indexes, but the stats pass below does not rely on
        that — it computes each run's true max."""
        if self._lib is None:
            for g, i, t, d in zip(groups, indexes, terms, datas):
                self.append_entry(g, i, t, d)
            return
        import ctypes

        import numpy as np
        n = len(groups)
        if n == 0:
            return
        blob = b"".join(datas)
        # numpy list→array conversion marshals the parallel arrays ~5x
        # faster than ctypes (c_uint32 * n)(*list) star-unpacking.
        ga = np.asarray(groups, np.uint32)
        ia = np.asarray(indexes, np.uint64)
        ta = np.asarray(terms, np.uint64)
        # Segment stats (per-group max index) per contiguous RUN, not per
        # record: maximum.reduceat computes each run's true max whatever
        # the intra-run order (no reliance on the ascending-batch
        # contract), and bump()'s compare arbitrates across runs of the
        # same group.  The per-record dict pass this replaces was ~8% of
        # the WAL phase.
        ends = np.nonzero(np.diff(ga))[0]
        run_starts = np.concatenate(([0], ends + 1))
        run_max = np.maximum.reduceat(ia, run_starts)
        bump = self._active_stats.bump
        for s, m in zip(run_starts.tolist(), run_max.tolist()):
            bump(int(ga[s]), int(m))
        la = np.fromiter(map(len, datas), np.uint32, n)
        self._lib.wal_append_entries(
            self._h, n,
            ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ia.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ta.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            blob,
            la.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        self._pending = True
        self._bytes += n * (_HDR.size + _ENTRY.size) + len(blob)

    def append_ranges(self, groups, starts, counts, terms, datas) -> None:
        """Batched RANGE append: one type-5 record per (group, start,
        term, count) run of consecutive same-term entries.  `datas` is
        the flat per-entry payload list, ranges in order, `sum(counts)`
        entries total.  Equivalent on replay to appending each entry,
        at ~1/4 the framed bytes for small payloads (the durable tick's
        fsync is bandwidth-bound).
        """
        if any(c == 0 for c in counts):
            # Empty runs write nothing: a zero-count record would bump
            # segment stats at start-1 for a group that may have no
            # durable floor, permanently blocking segment deletion.
            keep = [i for i, c in enumerate(counts) if c]
            groups = [groups[i] for i in keep]
            starts = [starts[i] for i in keep]
            terms = [terms[i] for i in keep]
            counts = [c for c in counts if c]
        n = len(groups)
        if n == 0:
            return
        import numpy as np
        la = np.fromiter(map(len, datas), np.uint32, len(datas))
        bump = self._active_stats.bump
        for g, s, c in zip(groups, starts, counts):
            bump(int(g), int(s) + int(c) - 1)
        if self._lib is not None:
            import ctypes
            ga = np.asarray(groups, np.uint32)
            sa = np.asarray(starts, np.uint64)
            ta = np.asarray(terms, np.uint64)
            ca = np.asarray(counts, np.uint32)
            blob = b"".join(datas)
            self._lib.wal_append_ranges(
                self._h, n,
                ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                sa.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ta.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ca.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                blob,
                la.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            self._pending = True
            self._bytes += (n * (_HDR.size + _RANGE.size)
                            + 4 * len(datas) + len(blob))
            return
        pos = 0
        lens = la.tobytes()      # little-endian u32, matches the format
        for g, s, c, t in zip(groups, starts, counts, terms):
            body = (_RANGE.pack(REC_RANGE, g, s, t, c)
                    + lens[4 * pos: 4 * (pos + c)]
                    + b"".join(datas[pos: pos + c]))
            pos += c
            self._write(body)

    def append_ranges_uniform(self, plog, groups, starts, counts, terms,
                              blob: bytes, lens,
                              group_bias: int = 0) -> bool:
        """Combined native write (walplog_put_uniform): for each range
        (group, start, count, term) write ONE WAL RANGE record AND the
        native payload-log range, all in one C call — zero per-entry
        Python.  `blob` concatenates every range's payload bytes in
        order; `lens` is per-entry.  Returns False when the native
        combined path is unavailable (caller falls back to
        append_entries + plog.put_ranges).  `group_bias` offsets the
        WAL records' group ids only (the group-commit multiplexed
        layout); the payload log is indexed by the raw group."""
        if self._lib is None or plog is None \
                or not hasattr(self._lib, "walplog_put_uniform"):
            return False
        import ctypes

        import numpy as np
        n_ranges = len(groups)
        if n_ranges == 0:
            return True
        ga = np.asarray(groups, np.uint32)
        sa = np.asarray(starts, np.uint64)
        ca = np.asarray(counts, np.uint32)
        ta = np.asarray(terms, np.uint64)
        la = np.asarray(lens, np.uint32)
        rc = self._lib.walplog_put_uniform(
            self._h, plog.handle, n_ranges,
            ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            sa.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ca.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ta.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            blob,
            la.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            group_bias)
        if rc != 0:
            raise ValueError("walplog_put_uniform: payload gap")
        bump = self._active_stats.bump
        live = 0
        for g, s, c in zip(ga.tolist(), sa.tolist(), ca.tolist()):
            if c:             # native side skips empty runs entirely
                bump(g + group_bias, s + c - 1)
                live += 1
        self._pending = True
        # One RANGE record per non-empty run (native writes type-5 —
        # keep _bytes matched to the file so rotation fires where
        # segment_bytes intends).
        self._bytes += live * (_HDR.size + _RANGE.size) \
            + 4 * int(ca.sum()) + len(blob)
        return True

    def set_hardstate(self, group: int, term: int, vote: int,
                      commit: int) -> None:
        self._active_stats.hs.add(group)
        if self._lib is not None:
            self._lib.wal_set_hardstate(self._h, group, term, vote, commit)
            self._pending = True
            self._bytes += _HDR.size + _HARD.size
            return
        self._write(_HARD.pack(REC_HARDSTATE, group, term, vote, commit))

    def set_hardstates(self, groups, terms, votes, commits) -> None:
        """Batched hard-state records from parallel arrays — one native
        call for the whole tick (under saturation EVERY group's commit
        advances per tick, and a per-group ctypes round trip was ~40% of
        the durable WAL phase)."""
        n = len(groups)
        if n == 0:
            return
        if self._lib is None:
            for g, t, v, c in zip(groups, terms, votes, commits):
                self.set_hardstate(int(g), int(t), int(v), int(c))
            return
        import ctypes

        import numpy as np
        ga = np.ascontiguousarray(groups, np.uint32)
        self._active_stats.hs.update(ga.tolist())
        ta = np.ascontiguousarray(terms, np.uint64)
        va = np.ascontiguousarray(votes, np.int64)
        ca = np.ascontiguousarray(commits, np.uint64)
        self._lib.wal_set_hardstates(
            self._h, n,
            ga.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ta.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            va.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ca.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        self._pending = True
        self._bytes += n * (_HDR.size + _HARD.size)

    def set_snapshot(self, group: int, index: int, term: int) -> None:
        """InstallSnapshot boundary marker: on replay, entries of `group`
        at or below `index` AND the retained suffix are dropped — the
        installed state's history supersedes the whole local log."""
        self._active_stats.bump(group, index)
        if self._lib is not None:
            self._lib.wal_set_snapshot(self._h, group, index, term)
            self._pending = True
            self._bytes += _HDR.size + _SNAP.size
            return
        self._write(_SNAP.pack(REC_SNAPSHOT, group, index, term))

    def set_conf(self, group: int, index: int, kind: int, voters: int,
                 joint: int, learners: int) -> bool:
        """Applied-membership baseline record (REC_CONF): the conf
        entry at `index` has been APPLIED — replay's last-wins baseline
        seeds the active config even after compaction drops the entry.

        Durability ride-along: the record lands before the NEXT sync
        barrier; a crash before it replays the same conf from the still
        -committed log entry, so no extra fsync is needed here.  The
        native C fast path has no conf writer — returns False there
        (recovery then depends on the retained entries; the membership
        runtimes force the Python backend via their chaos/fsio posture,
        and document the native gap)."""
        if self._lib is not None:
            return False
        self._conf_latest[group] = (index, kind, voters, joint, learners)
        self._active_stats.hs.add(group)   # re-assert like a hard state
        self._write(_CONFREC.pack(REC_CONF, group, index, kind,
                                  voters, joint, learners))
        return True

    def set_dedup(self, group: int, floor: int,
                  pairs: List[Tuple[int, int]]) -> bool:
        """Dedup-window baseline record (REC_DEDUP): `pairs` is the
        group's forward-retry window at or below `floor` (the new
        compaction/install boundary), FIFO order.  Replay keeps the
        highest-floor record; node boot restores it into the in-memory
        window before publishing the retained suffix, so a restart
        scrubs the same forward-retry duplicates its live peers do.

        Durability ride-along like set_conf: the caller's compaction /
        install barrier syncs it.  The native C fast path has no dedup
        writer — returns False there (the chaos/fsio posture forces the
        Python backend wherever this invariant is exercised; native
        deployments keep the pre-record behavior and the documented
        gap)."""
        if self._lib is not None:
            return False
        body = b"".join(
            [_DEDUPHDR.pack(REC_DEDUP, group, floor, len(pairs))]
            + [_DEDUPPAIR.pack(i, p) for (i, p) in pairs])
        self._dedup_latest[group] = body
        self._active_stats.hs.add(group)   # re-assert like a hard state
        self._write(body)
        return True

    def epoch_mark(self, no: int, end: bool) -> None:
        """Multi-step dispatch frame marker (REC_EPOCH): BEGIN before
        the dispatch's first record, END after its last (including the
        hard states).  Replay ignores them; repair_epochs() drops a
        trailing dispatch whose epoch was never cluster-committed."""
        if self._lib is not None and hasattr(self._lib, "wal_epoch"):
            self._lib.wal_epoch(self._h, no, 1 if end else 0)
            self._pending = True
            self._bytes += _HDR.size + _EPOCH.size
            return
        self._write(_EPOCH.pack(REC_EPOCH, 1 if end else 0, no))

    @staticmethod
    def repair_epochs(dirname: str, committed: int) -> bool:
        """Atomically drop an UNCOMMITTED multi-step dispatch: truncate
        this WAL at the first EPOCH-BEGIN marker whose number exceeds
        `committed` (the cluster's epoch-commit fsync is the
        linearization point; see runtime/fused.py) and unlink any later
        segments.  Runs BEFORE replay/open.  Returns True if anything
        was dropped.

        Within one dispatch peers exchange messages that are not yet
        individually durable; the per-peer fsync barrier is not atomic,
        so a crash mid-barrier can leave peer A's WAL holding effects
        of a message peer B never persisted.  Dropping the whole
        uncommitted dispatch on EVERY peer restores the all-or-nothing
        view — nothing was published (publish follows the epoch-commit
        fsync), so no client observed it."""
        cut: Optional[Tuple[str, int]] = None
        paths = _segment_paths(dirname)
        for pi, (seq, path) in enumerate(paths):
            with open(path, "rb") as f:
                blob = f.read()
            off = 0
            while off + _HDR.size <= len(blob):
                crc, blen = _HDR.unpack_from(blob, off)
                body = blob[off + _HDR.size: off + _HDR.size + blen]
                if len(body) != blen or zlib.crc32(body) != crc:
                    break                    # torn — _repair_tail's job
                if body[0] == REC_EPOCH:
                    _, kind, no = _EPOCH.unpack_from(body)
                    if kind == 0 and no > committed:
                        cut = (pi, off)
                        break
                off += _HDR.size + blen
            if cut is not None:
                break
        if cut is None:
            return False
        pi, off = cut
        with open(paths[pi][1], "r+b") as f:
            f.truncate(off)
            f.flush()
            os.fsync(f.fileno())
        for _, path in paths[pi + 1:]:
            os.unlink(path)
        return True

    def _write_compact_rec(self, group: int, index: int, term: int) -> None:
        self._active_stats.bump(group, index)
        if self._lib is not None:
            self._lib.wal_set_compact(self._h, group, index, term)
            self._pending = True
            self._bytes += _HDR.size + _SNAP.size
            return
        self._write(_SNAP.pack(REC_COMPACT, group, index, term))

    def mark_compact(self, group: int, index: int, term: int) -> None:
        """Compaction floor marker: on replay, entries of `group` at or
        below `index` are dropped; the suffix survives.  Idempotent per
        floor (re-marking an already-marked floor is skipped)."""
        if index <= self._marker_floor.get(group, 0):
            return
        self._marker_floor[group] = index
        self._write_compact_rec(group, index, term)

    def sync(self) -> None:
        """Durable barrier.  May stall (slow disk — the fsio seam's
        stall rules model it): that is latency, never corruption — the
        caller's tick simply takes longer and every invariant must hold
        across it.  `last_sync_s` exposes the most recent barrier's
        wall time so a stalling disk is observable without a profiler."""
        if not self._pending:
            return
        import time as _t
        t0 = _t.monotonic()
        if self._lib is not None:
            if self._lib.wal_sync(self._h) != 0:
                raise OSError("native WAL sync failed")
        else:
            fsio.fsync_file(self._f)
        self.last_sync_s = _t.monotonic() - t0
        if self.obs is not None:
            self.obs.note_event("wal.fsync", dur_s=self.last_sync_s,
                                dir=self.dirname)
        self._pending = False
        if self._bytes >= self.segment_bytes:
            self._rotate()

    def _rotate(self) -> None:
        """Close the active segment and start wal-<seq+1>.log.  Only ever
        called at a sync boundary, so every closed segment is a complete,
        durable record stream."""
        self._close_handle()
        self._closed_stats[self.path] = self._active_stats
        self._active_stats = _SegStats()
        self._seq += 1
        self.path = os.path.join(self.dirname, f"wal-{self._seq}.log")
        self._bytes = 0
        self._open_active()
        _fsync_dir(self.dirname)

    def _close_handle(self) -> None:
        if self._lib is not None:
            lib, self._lib = self._lib, None
            rc = lib.wal_close(self._h)
            self._h = None
            if rc != 0:
                raise OSError("native WAL close failed (unsynced records "
                              "may be lost)")
            return
        if self._f is not None:
            f, self._f = self._f, None
            fsio.fsync_file(f)
            f.close()

    def close(self) -> None:
        if self._lib is None and self._f is None:
            return
        self._close_handle()
        self._pending = False

    # -- compaction ------------------------------------------------------

    def _stats_for(self, path: str) -> _SegStats:
        """Stats of a closed (immutable) segment, scanned once."""
        st = self._closed_stats.get(path)
        if st is not None:
            return st
        st = _SegStats()
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        while off + _HDR.size <= len(blob):
            crc, blen = _HDR.unpack_from(blob, off)
            body = blob[off + _HDR.size: off + _HDR.size + blen]
            if len(body) != blen or zlib.crc32(body) != crc:
                break
            off += _HDR.size + blen
            rtype = body[0]
            if rtype == REC_ENTRY:
                _, group, index, _t = _ENTRY.unpack_from(body)
                st.bump(group, index)
            elif rtype == REC_RANGE:
                _, group, start, _t, count = _RANGE.unpack_from(body)
                st.bump(group, start + count - 1)
            elif rtype == REC_HARDSTATE:
                st.hs.add(_HARD.unpack_from(body)[1])
            elif rtype == REC_CONF:
                # Same survival contract as a hard state: the group's
                # baseline must be re-asserted before this segment may
                # be unlinked (compact()'s _conf_latest re-write).
                st.hs.add(_CONFREC.unpack_from(body)[1])
            elif rtype == REC_DEDUP:
                # Baseline survival contract, like REC_CONF above.
                st.hs.add(_DEDUPHDR.unpack_from(body)[1])
            elif rtype in (REC_SNAPSHOT, REC_COMPACT):
                _, group, index, _t = _SNAP.unpack_from(body)
                st.bump(group, index)
        self._closed_stats[path] = st
        return st

    def compact(self, floors: Dict[int, Tuple[int, int]],
                hard: Dict[int, Tuple[int, int, int]]) -> int:
        """Advance compaction floors and drop fully-superseded segments.

        floors: {group: (floor_index, floor_term)} — the durable
          snapshot-covered boundary per group (every group with a nonzero
          payload-log start, not just newly compacted ones).
        hard: {group: (term, vote, commit)} — current hard states, used
          to re-assert state for groups whose only hardstate records live
          in a segment being deleted.

        Appends COMPACT markers for advanced floors, then walks closed
        segments oldest-first and unlinks each whose every entry/marker
        is at or below its group's floor (hardstate-only groups are
        re-asserted into the active segment first).  Stops at the first
        non-deletable segment to keep the segment sequence contiguous.
        Never rewrites live data; cost is O(markers + unlinked files).

        Returns the number of deleted segments.
        """
        wrote = False
        for g, (idx, term) in sorted(floors.items()):
            if idx > self._marker_floor.get(g, 0):
                self.mark_compact(g, idx, term)
                wrote = True
        if wrote:
            self.sync()

        # Find the longest deletable prefix run first, then re-assert the
        # UNION of its groups once and fsync once — a long run of small
        # segments must not cost one fsync each (the caller holds the
        # node's WAL lock across this).
        run: List[str] = []
        affected: Set[int] = set()
        for seq, path in _segment_paths(self.dirname):
            if path == self.path:
                break                   # never delete the active segment
            st = self._stats_for(path)
            ok = all(
                g in floors and idx <= floors[g][0]
                for g, idx in st.max_idx.items()
            ) and all(g in hard for g in st.hs - set(st.max_idx))
            if not ok:
                break
            run.append(path)
            affected |= st.groups()
        if not run:
            return 0
        # Re-assert everything the doomed segments contributed, into the
        # active segment, durably, BEFORE the unlinks: hard states
        # (last-wins, and `hard` is current so appending it last is
        # correct) and floor markers (replay must re-learn start).
        for g in sorted(affected):
            if g in hard:
                self.set_hardstate(g, *hard[g])
            if g in floors:
                self._write_compact_rec(g, *floors[g])
            conf = self._conf_latest.get(g)
            if conf is not None and self._lib is None:
                # The membership baseline must survive the unlink too:
                # the conf ENTRY that built it may live only in the
                # doomed segments.
                self._write(_CONFREC.pack(REC_CONF, g, *conf))
            dd = self._dedup_latest.get(g)
            if dd is not None and self._lib is None:
                # Likewise the dedup baseline: the doomed segments may
                # hold the only record scrubbing a compacted-away
                # forward-retry duplicate.
                self._write(dd)
        self.sync()
        for path in run:
            os.unlink(path)
            self._closed_stats.pop(path, None)
        _fsync_dir(self.dirname)
        return len(run)

    @staticmethod
    def rewrite(dirname: str, groups: Dict[int, GroupLog]) -> None:
        """Atomically replace the WAL contents with a compacted image.

        Writes the image as a NEW top segment (seq = max + 1), fsyncs it
        into place, then unlinks all older segments.  A crash at any
        point leaves a correct replay: before the rename the old segments
        are intact; after it, replaying old segments then the image
        yields exactly the image (SNAPSHOT markers + full retained tails
        + final hard states supersede the prefix).  The caller must hold
        the WAL quiescent (no concurrent appends) and reopen its handle
        afterwards.

        The live engine compacts with `compact` (markers + segment
        drops); this full rewrite remains for offline tooling and tests.
        """
        segs = _segment_paths(dirname)
        new_seq = (segs[-1][0] + 1) if segs else 0
        path = os.path.join(dirname, f"wal-{new_seq}.log")
        tmp = path + ".rewrite"
        w = WAL.__new__(WAL)                      # bare python-backend WAL
        w._lib = w._h = None
        w.path = tmp
        w._f = open(tmp, "wb")
        w._pending = False
        w._bytes = 0
        w._active_stats = _SegStats()
        for g, gl in sorted(groups.items()):
            if gl.start:
                w.set_snapshot(g, gl.start, gl.start_term)
            for i, (term, data) in enumerate(gl.entries):
                w.append_entry(g, gl.start + 1 + i, term, data)
            w.set_hardstate(g, gl.hard.term, gl.hard.vote, gl.hard.commit)
        w._f.flush()
        os.fsync(w._f.fileno())
        w._f.close()
        os.replace(tmp, path)
        _fsync_dir(dirname)
        for seq, old in segs:
            os.unlink(old)
        if segs:
            _fsync_dir(dirname)

    # -- replay ----------------------------------------------------------

    @staticmethod
    def replay(dirname: str) -> Dict[int, GroupLog]:
        """Read all segments back into per-group logs, sequence order.

        A torn record drops everything after it — including later
        segments: only the active segment's tail can be torn by a crash,
        so a tear mid-sequence means real corruption and the safe replay
        is the longest clean prefix."""
        groups: Dict[int, GroupLog] = {}
        for seq, path in _segment_paths(dirname):
            with open(path, "rb") as f:
                blob = f.read()
            if not WAL._replay_blob(blob, groups):
                break
        return groups

    @staticmethod
    def _replay_entry(groups: Dict[int, GroupLog], group: int, index: int,
                      term: int, data: bytes) -> None:
        """Apply one replayed entry (ENTRY record, or one position of a
        RANGE record) under the log-matching semantics in the module
        doc: same-term overwrite is idempotent, different-term truncates
        the suffix, below-floor is skipped."""
        gl = groups.setdefault(group, GroupLog())
        pos = index - gl.start               # 1-based within entries
        if pos < 1:
            return                           # below compaction floor
        if pos <= len(gl.entries):
            if gl.entries[pos - 1][0] == term:
                gl.entries[pos - 1] = (term, data)
            else:                            # conflict truncation
                del gl.entries[pos - 1:]
                gl.entries.append((term, data))
        elif pos == len(gl.entries) + 1:
            gl.entries.append((term, data))
        else:
            # Forward gap: the missing prefix lived in segments
            # compaction unlinked (its COMPACT marker replays later,
            # from a retained segment — it will confirm this floor and
            # supply start_term).  Record-level corruption cannot
            # produce a gap: appends are sequential within a segment
            # and a torn record stops replay entirely.
            gl.entries.clear()
            gl.start, gl.start_term = index - 1, 0
            gl.entries.append((term, data))

    @staticmethod
    def _replay_blob(blob: bytes, groups: Dict[int, GroupLog]) -> bool:
        """Apply one segment's records; False on a torn record."""
        off = 0
        while off + _HDR.size <= len(blob):
            crc, blen = _HDR.unpack_from(blob, off)
            body = blob[off + _HDR.size: off + _HDR.size + blen]
            if len(body) != blen or zlib.crc32(body) != crc:
                return False        # torn — drop the rest
            off += _HDR.size + blen
            rtype = body[0]
            if rtype == REC_ENTRY:
                _, group, index, term = _ENTRY.unpack_from(body)
                WAL._replay_entry(groups, group, index, term,
                                  body[_ENTRY.size:])
            elif rtype == REC_RANGE:
                _, group, start, term, count = _RANGE.unpack_from(body)
                doff = _RANGE.size + 4 * count
                pos = doff
                for i in range(count):
                    (ln,) = struct.unpack_from(
                        "<I", body, _RANGE.size + 4 * i)
                    WAL._replay_entry(groups, group, start + i, term,
                                      body[pos: pos + ln])
                    pos += ln
            elif rtype == REC_HARDSTATE:
                _, group, term, vote, commit = _HARD.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                gl.hard = HardState(term=term, vote=vote, commit=commit)
            elif rtype == REC_SNAPSHOT:
                _, group, index, term = _SNAP.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                # Leads a rewritten WAL (no entries yet), or marks a live
                # InstallSnapshot mid-stream: drop the covered prefix —
                # AND any retained suffix, which predates the snapshot
                # and may conflict with the installed state's history.
                if index > gl.start:
                    gl.entries.clear()
                    gl.start, gl.start_term = index, term
            elif rtype == REC_COMPACT:
                _, group, index, term = _SNAP.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                # Local compaction floor: the covered prefix goes, the
                # retained suffix SURVIVES (unlike REC_SNAPSHOT).
                if index > gl.start:
                    drop = min(index - gl.start, len(gl.entries))
                    del gl.entries[:drop]
                    gl.start, gl.start_term = index, term
                elif index == gl.start and gl.start_term == 0:
                    # Confirms an implicit floor inferred from a forward
                    # entry gap (see ENTRY handling above).
                    gl.start_term = term
            elif rtype == REC_CONF:
                _, group, index, kind, voters, joint, learners = \
                    _CONFREC.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                # Last-wins applied-config baseline; conf entries
                # committed above it re-apply on top during restore
                # (runtime membership wiring).
                if gl.conf is None or index >= gl.conf[0]:
                    gl.conf = (index, kind, voters, joint, learners)
            elif rtype == REC_DEDUP:
                _, group, floor, count = _DEDUPHDR.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                # Highest-floor-wins dedup baseline (a later compaction
                # supersedes an earlier one; pairs are FIFO-ordered).
                if gl.dedup is None or floor >= gl.dedup[0]:
                    off2 = _DEDUPHDR.size
                    gl.dedup = (floor, [
                        _DEDUPPAIR.unpack_from(
                            body, off2 + k * _DEDUPPAIR.size)
                        for k in range(count)])
        return True


# ---------------------------------------------------------------------------
# WAL group commit (PR 7): one physical log — one append stream, one
# fsync — for ALL P peers of a co-located cluster.


class GroupCommitWAL:
    """Multiplex P peers' logical WALs into ONE physical segmented log.

    The fused runtime's durable barrier was P fsyncs in flight (one per
    peer directory) per tick; on one data directory those target the
    same device, so the barrier pays P journal commits for one tick's
    worth of records.  This layout coalesces them: every peer's records
    land in one shared `WAL` (same record formats, same segmentation,
    same repair/compaction machinery) keyed by the FLAT group id
    `peer * G + g`, and the tick's barrier is ONE write+fsync covering
    every peer — a group commit whose batch is whatever the tick wrote.
    Durability semantics are unchanged: sync() returning still means
    every peer's records of the tick are on disk (they are in the same
    file, so trivially so), and the batch window is the tick itself —
    it adapts to load because a saturated tick simply carries more
    records into the same single commit.

    `view(peer)` returns the per-peer facade the host plane writes
    through (the WAL write surface with the peer's `group_bias` applied
    on the way in); `replay/exists/repair_epochs` are the matching
    whole-directory forms, with `split_replay` giving the per-peer
    slice the host plane's restore path consumes.

    Observability: `group_commits` counts actual fsyncs, `batch_hist`
    maps peers-per-commit → count (the bench's group-commit histogram),
    and the owning runtime exports both via /metrics
    (`wal_group_commits`).
    """

    def __init__(self, dirname: str, num_peers: int, num_groups: int,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        import threading
        self.num_peers = num_peers
        self.num_groups = num_groups
        self.base = WAL(dirname, segment_bytes=segment_bytes)
        self._mu = threading.Lock()
        self._dirty: Set[int] = set()
        self._open_views = 0
        self._epoch_last: Optional[Tuple[int, bool]] = None
        self._floors: Dict[int, Tuple[int, int]] = {}
        self._hard: Dict[int, Tuple[int, int, int]] = {}
        self.group_commits = 0
        self.batch_hist: Dict[int, int] = {}
        self._views = [WALGroupView(self, p) for p in range(num_peers)]

    # -- per-peer facades ------------------------------------------------

    def view(self, peer: int) -> "WALGroupView":
        self._open_views += 1
        return self._views[peer]

    # -- whole-directory forms -------------------------------------------

    @staticmethod
    def exists(dirname: str) -> bool:
        return wal_exists(dirname)

    @staticmethod
    def replay_flat(dirname: str) -> Dict[int, GroupLog]:
        return WAL.replay(dirname)

    @staticmethod
    def split_replay(flat: Dict[int, GroupLog], peer: int,
                     num_groups: int) -> Dict[int, GroupLog]:
        lo, hi = peer * num_groups, (peer + 1) * num_groups
        return {fg - lo: gl for fg, gl in flat.items() if lo <= fg < hi}

    @staticmethod
    def repair_epochs(dirname: str, committed: int) -> bool:
        return WAL.repair_epochs(dirname, committed)

    # -- shared write machinery (called by the views) --------------------

    def note_write(self, peer: int) -> None:
        self._dirty.add(peer)

    def epoch_mark(self, no: int, end: bool) -> None:
        """One BEGIN/END frame per dispatch for the WHOLE shared stream
        (the host plane asks per peer; duplicates carry no information
        here because all peers' records share the file).  The dedupe
        check holds the lock across the write so a racing parallel
        worker can never slip a record ahead of the BEGIN it relies
        on."""
        with self._mu:
            key = (no, end)
            if self._epoch_last == key:
                return
            self._epoch_last = key
            self.base.epoch_mark(no, end)

    def sync(self) -> None:
        """The group commit: first caller flushes + fsyncs EVERYTHING
        every peer wrote since the last barrier; the other peers'
        sync() calls find nothing pending and return — P calls, one
        fsync."""
        with self._mu:
            if not self.base._pending:
                return
            batch = len(self._dirty) or 1
            self._dirty.clear()
            self.base.sync()
            self.group_commits += 1
            self.batch_hist[batch] = self.batch_hist.get(batch, 0) + 1

    def compact_view(self, bias: int, floors, hard) -> int:
        """Per-view compaction: floors/hard merge into the cluster-wide
        flat dicts (segment deletability needs EVERY peer's floors —
        one peer's view alone could never prove a shared segment
        fully superseded)."""
        with self._mu:
            self._floors.update(
                {g + bias: v for g, v in floors.items()})
            self._hard.update({g + bias: v for g, v in hard.items()})
            return self.base.compact(dict(self._floors),
                                     dict(self._hard))

    def close_view(self) -> None:
        with self._mu:
            self._open_views -= 1
            if self._open_views <= 0:
                self.base.close()


class WALGroupView:
    """One peer's write surface over a GroupCommitWAL: the WAL API the
    host plane uses, with `group_bias` flattening this peer's group ids
    into the shared stream.  NOT constructed directly — GroupCommitWAL
    hands them out."""

    def __init__(self, owner: GroupCommitWAL, peer: int):
        self._owner = owner
        self.peer = peer
        self.group_bias = peer * owner.num_groups

    # Shared-state delegation: the native mirror path (wal_mirror_all)
    # talks to `_lib`/`_h` and writes `_pending`/`_bytes`/stat bumps —
    # all live on the one shared base WAL.
    @property
    def _lib(self):
        return self._owner.base._lib

    @property
    def _h(self):
        return self._owner.base._h

    @property
    def _f(self):
        return self._owner.base._f

    @property
    def _active_stats(self):
        return self._owner.base._active_stats

    @property
    def _pending(self):
        return self._owner.base._pending

    @_pending.setter
    def _pending(self, v) -> None:
        self._owner.base._pending = v
        if v:
            self._owner.note_write(self.peer)

    @property
    def _bytes(self):
        return self._owner.base._bytes

    @_bytes.setter
    def _bytes(self, v) -> None:
        self._owner.base._bytes = v

    @property
    def obs(self):
        return self._owner.base.obs

    @obs.setter
    def obs(self, tracer) -> None:
        self._owner.base.obs = tracer

    @property
    def last_sync_s(self) -> float:
        return self._owner.base.last_sync_s

    # -- biased write surface --------------------------------------------

    def _touch(self) -> None:
        self._owner.note_write(self.peer)

    def append_entry(self, group, index, term, data) -> None:
        self._touch()
        self._owner.base.append_entry(group + self.group_bias, index,
                                      term, data)

    def append_entries(self, groups, indexes, terms, datas) -> None:
        self._touch()
        self._owner.base.append_entries(
            [g + self.group_bias for g in groups], indexes, terms, datas)

    def append_ranges(self, groups, starts, counts, terms,
                      datas) -> None:
        self._touch()
        self._owner.base.append_ranges(
            [int(g) + self.group_bias for g in groups], starts, counts,
            terms, datas)

    def append_ranges_uniform(self, plog, groups, starts, counts, terms,
                              blob, lens) -> bool:
        self._touch()
        return self._owner.base.append_ranges_uniform(
            plog, groups, starts, counts, terms, blob, lens,
            group_bias=self.group_bias)

    def set_hardstate(self, group, term, vote, commit) -> None:
        self._touch()
        self._owner.base.set_hardstate(group + self.group_bias, term,
                                       vote, commit)

    def set_hardstates(self, groups, terms, votes, commits) -> None:
        import numpy as np
        self._touch()
        ga = np.asarray(groups, np.int64) + self.group_bias
        self._owner.base.set_hardstates(ga, terms, votes, commits)

    def set_snapshot(self, group, index, term) -> None:
        self._touch()
        self._owner.base.set_snapshot(group + self.group_bias, index,
                                      term)

    def set_conf(self, group, index, kind, voters, joint,
                 learners) -> bool:
        self._touch()
        return self._owner.base.set_conf(group + self.group_bias, index,
                                         kind, voters, joint, learners)

    def mark_compact(self, group, index, term) -> None:
        self._owner.base.mark_compact(group + self.group_bias, index,
                                      term)

    def epoch_mark(self, no: int, end: bool) -> None:
        self._owner.epoch_mark(no, end)

    def sync(self) -> None:
        self._owner.sync()

    def compact(self, floors, hard) -> int:
        return self._owner.compact_view(self.group_bias, floors, hard)

    def close(self) -> None:
        self._owner.close_view()
