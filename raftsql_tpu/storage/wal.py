"""Durable write-ahead log, multi-group, host-side.

Replaces the reference's vendored `etcd/wal` (reference raft.go:33-34,
99-134): an append-only record log that persists raft entries and hard
state *before* peer messages are sent or commits published (the durability
ordering invariant, reference raft.go:227-235), and is fully replayed on
restart (reference raft.go:122-134).

Differences from etcd/wal, by design:
  - One WAL serves ALL raft groups of a node; records carry a group id, so
    a single fsync batches the tick's appends across every group — the
    group-commit analog of batching consensus math on device.
  - Records are fixed-layout little-endian structs (struct-of-arrays
    friendly, shared with the C++ fast path in native/wal.cc, loaded via
    storage.native_wal when built).

Record layout:  u32 crc32(body) | u32 body_len | body
  body := u8 type | fields
  type 1 ENTRY:     u32 group | u64 index | u64 term | bytes data
  type 2 HARDSTATE: u32 group | u64 term | i64 vote | u64 commit

Replay semantics match raft's log-matching property: a later ENTRY record
at an index <= the current length with the SAME term is an idempotent
overwrite (a re-accepted duplicate append — same index+term implies same
entry), while a DIFFERENT term is a genuine conflict and truncates the
suffix from that index before appending (core/step.py Phase 4).  Truncating
on same-term overlap would silently drop durably-acked suffix entries when
a stale duplicate append covering only a prefix is re-accepted.  The last
HARDSTATE per group wins.  A torn tail (bad CRC / short read) is dropped,
like etcd's repair path.
"""
from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_HDR = struct.Struct("<II")          # crc, body_len
_ENTRY = struct.Struct("<BIQQ")      # type, group, index, term
_HARD = struct.Struct("<BIQqQ")      # type, group, term, vote, commit
_SNAP = struct.Struct("<BIQQ")       # type, group, index, term

REC_ENTRY = 1
REC_HARDSTATE = 2
REC_SNAPSHOT = 3        # compaction boundary: entries <= index dropped,
#                         term = term of the boundary entry (so AppendEntries
#                         prev-term checks at the boundary still resolve)

WAL_FILE = "wal-0.log"


@dataclass
class HardState:
    term: int = 0
    vote: int = -1
    commit: int = 0


@dataclass
class GroupLog:
    """Replayed per-group state: entries (start+1 ... start+len, 1-based)
    plus last hard state.  `start` > 0 after WAL compaction — the prefix
    up to `start` is covered by the state-machine snapshot; `start_term`
    is the boundary entry's term."""
    hard: HardState = field(default_factory=HardState)
    entries: List[Tuple[int, bytes]] = field(default_factory=list)  # (term, data)
    start: int = 0
    start_term: int = 0

    @property
    def log_len(self) -> int:
        return self.start + len(self.entries)


def wal_exists(dirname: str) -> bool:
    return os.path.isfile(os.path.join(dirname, WAL_FILE))


class WAL:
    """Append-only multi-group WAL with batched fsync.

    Usage per tick (the reference's Ready handling, raft.go:227-235):
        wal.append_entry(...); wal.set_hardstate(...)
        wal.sync()              # durable point — only now send/publish

    The write path prefers the C++ fast path (native/wal.cc — framing,
    CRC, buffered write, fdatasync behind one ctypes call) and falls back
    to pure Python; both produce byte-identical files, and `replay` reads
    either.  `native=None` auto-detects; True/False force.
    """

    def __init__(self, dirname: str, native: Optional[bool] = None):
        os.makedirs(dirname, exist_ok=True)
        self.path = os.path.join(dirname, WAL_FILE)
        self._lib = None
        self._h = None
        if native is not False:
            from raftsql_tpu.native.build import load_native_wal
            lib = load_native_wal()
            if lib is not None:
                h = lib.wal_open(self.path.encode())
                if h:
                    self._lib, self._h = lib, h
            if native is True and self._lib is None:
                raise RuntimeError("native WAL requested but unavailable")
        self._f = None if self._lib else open(self.path, "ab")
        self._pending = False

    @property
    def is_native(self) -> bool:
        return self._lib is not None

    # -- write path ------------------------------------------------------

    def _write(self, body: bytes) -> None:
        self._f.write(_HDR.pack(zlib.crc32(body), len(body)))
        self._f.write(body)
        self._pending = True

    def append_entry(self, group: int, index: int, term: int,
                     data: bytes) -> None:
        if self._lib is not None:
            self._lib.wal_append_entry(self._h, group, index, term,
                                       data, len(data))
            self._pending = True
            return
        self._write(_ENTRY.pack(REC_ENTRY, group, index, term) + data)

    def append_entries(self, groups, indexes, terms, datas) -> None:
        """Batched append — one native call for a whole tick's records."""
        if self._lib is None:
            for g, i, t, d in zip(groups, indexes, terms, datas):
                self.append_entry(g, i, t, d)
            return
        import ctypes
        n = len(groups)
        if n == 0:
            return
        blob = b"".join(datas)
        self._lib.wal_append_entries(
            self._h, n,
            (ctypes.c_uint32 * n)(*groups),
            (ctypes.c_uint64 * n)(*indexes),
            (ctypes.c_uint64 * n)(*terms),
            blob,
            (ctypes.c_uint32 * n)(*[len(d) for d in datas]))
        self._pending = True

    def set_hardstate(self, group: int, term: int, vote: int,
                      commit: int) -> None:
        if self._lib is not None:
            self._lib.wal_set_hardstate(self._h, group, term, vote, commit)
            self._pending = True
            return
        self._write(_HARD.pack(REC_HARDSTATE, group, term, vote, commit))

    def set_snapshot(self, group: int, index: int, term: int) -> None:
        """Snapshot/compaction boundary marker: on replay, entries of
        `group` at or below `index` are dropped and the log starts there
        (with the boundary entry's term preserved)."""
        if self._lib is not None:
            self._lib.wal_set_snapshot(self._h, group, index, term)
            self._pending = True
            return
        self._write(_SNAP.pack(REC_SNAPSHOT, group, index, term))

    def sync(self) -> None:
        if not self._pending:
            return
        if self._lib is not None:
            if self._lib.wal_sync(self._h) != 0:
                raise OSError("native WAL sync failed")
        else:
            self._f.flush()
            os.fsync(self._f.fileno())
        self._pending = False

    def close(self) -> None:
        if self._lib is not None:
            lib, self._lib = self._lib, None
            rc = lib.wal_close(self._h)
            self._h = None
            if rc != 0:
                raise OSError("native WAL close failed (unsynced records "
                              "may be lost)")
            return
        if self._f is not None:
            self.sync()
            self._f.close()

    # -- compaction ------------------------------------------------------

    @staticmethod
    def rewrite(dirname: str, groups: Dict[int, GroupLog]) -> None:
        """Atomically replace the WAL with a compacted image.

        `groups` is the desired post-compaction state: per group, a
        snapshot boundary (start, start_term), the retained entry tail,
        and the current hard state.  Written to a temp file, fsynced, then
        renamed over the live WAL — a crash at any point leaves either the
        old or the new WAL intact.  The caller must hold the WAL quiescent
        (no concurrent appends) and reopen its handle afterwards.
        """
        path = os.path.join(dirname, WAL_FILE)
        tmp = path + ".rewrite"
        w = WAL.__new__(WAL)                      # bare python-backend WAL
        w._lib = w._h = None
        w.path = tmp
        w._f = open(tmp, "wb")
        w._pending = False
        for g, gl in sorted(groups.items()):
            if gl.start:
                w.set_snapshot(g, gl.start, gl.start_term)
            for i, (term, data) in enumerate(gl.entries):
                w.append_entry(g, gl.start + 1 + i, term, data)
            w.set_hardstate(g, gl.hard.term, gl.hard.vote, gl.hard.commit)
        w.sync()
        w.close()
        os.replace(tmp, path)
        # Durability of the rename itself.
        dirfd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    # -- replay ----------------------------------------------------------

    @staticmethod
    def replay(dirname: str) -> Dict[int, GroupLog]:
        """Read the WAL back into per-group logs; tolerate a torn tail."""
        groups: Dict[int, GroupLog] = {}
        path = os.path.join(dirname, WAL_FILE)
        if not os.path.isfile(path):
            return groups
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        while off + _HDR.size <= len(blob):
            crc, blen = _HDR.unpack_from(blob, off)
            body = blob[off + _HDR.size: off + _HDR.size + blen]
            if len(body) != blen or zlib.crc32(body) != crc:
                break               # torn tail — drop the rest
            off += _HDR.size + blen
            rtype = body[0]
            if rtype == REC_ENTRY:
                _, group, index, term = _ENTRY.unpack_from(body)
                data = body[_ENTRY.size:]
                gl = groups.setdefault(group, GroupLog())
                pos = index - gl.start           # 1-based within entries
                if pos < 1:
                    continue                     # below compaction floor
                if pos <= len(gl.entries):
                    if gl.entries[pos - 1][0] == term:
                        gl.entries[pos - 1] = (term, data)
                    else:                        # conflict truncation
                        del gl.entries[pos - 1:]
                        gl.entries.append((term, data))
                elif pos == len(gl.entries) + 1:
                    gl.entries.append((term, data))
                # else: a gap would mean WAL corruption; skip the record.
            elif rtype == REC_HARDSTATE:
                _, group, term, vote, commit = _HARD.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                gl.hard = HardState(term=term, vote=vote, commit=commit)
            elif rtype == REC_SNAPSHOT:
                _, group, index, term = _SNAP.unpack_from(body)
                gl = groups.setdefault(group, GroupLog())
                # Leads a rewritten WAL (no entries yet), or marks a live
                # InstallSnapshot mid-stream: drop the covered prefix —
                # AND any retained suffix, which predates the snapshot
                # and may conflict with the installed state's history.
                if index > gl.start:
                    gl.entries.clear()
                    gl.start, gl.start_term = index, term
        return groups
