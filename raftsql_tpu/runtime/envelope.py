"""Proposal envelopes: exactly-once apply under forward-retry.

The reference forwards proposals to the leader via etcd/raft's MsgProp
routing and simply loses them if the leader is down — the client's PUT
hangs forever.  This runtime retries forwarding (runtime/node.py), which
upgrades delivery to at-least-once; the envelope downgrades apply back to
exactly-once:

  - every proposal is wrapped with a random 64-bit id before entering the
    log:  0x01 | u64 id | payload;
  - at publish time each node tracks the last `window` ids per group and
    drops re-occurrences.  The dedup decision is a pure function of the
    committed log prefix, so every replica (and every replay) makes the
    same decision — replicas stay identical.

Deliberately proposing the same SQL text twice still applies twice (two
proposals, two ids) — preserving the reference's duplicate-query FIFO
semantics (reference db.go:70-75).  No-op/conf entries are empty and not
enveloped (reference skips them at publish, raft.go:84-87).
"""
from __future__ import annotations

import secrets
import struct
import threading
from collections import deque
from typing import Optional, Tuple

_MAGIC = 0x01
_HDR = struct.Struct("<BQ")


def new_id() -> int:
    return secrets.randbits(64)


def wrap(payload: bytes, pid: Optional[int] = None) -> bytes:
    return _HDR.pack(_MAGIC, new_id() if pid is None else pid) + payload


def unwrap(data: bytes) -> Tuple[Optional[int], bytes]:
    """Returns (proposal id, payload); id is None for bare entries."""
    if len(data) >= _HDR.size and data[0] == _MAGIC:
        _, pid = _HDR.unpack_from(data)
        return pid, data[_HDR.size:]
    return None, data


class DedupWindow:
    """Sliding window of recently applied proposal ids for one group.

    Entries carry the log index they were applied at so the window can be
    SNAPSHOTTED consistently: a state transfer at applied index A must
    ship exactly the ids applied at or below A — shipping the live window
    (which may run ahead of the state machine's applied point) would make
    the receiver skip entries whose effects its installed state does not
    contain (runtime/node.py InstallSnapshot path).

    Thread-safe: `seen` advances on the commit CONSUMER thread (the
    publish phase ships raw entries; unwrap/dedup runs off the tick
    thread — runtime/db.py), while `pairs_upto` (snapshot send) and
    `restore` (snapshot install) run on the tick thread."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._fifo: deque = deque()          # (idx, pid), idx ascending
        self._set: set = set()
        self._mu = threading.Lock()

    def seen(self, pid: int, idx: int = 0) -> bool:
        """Check-and-insert; True if pid was already applied recently."""
        with self._mu:
            if pid in self._set:
                return True
            self._set.add(pid)
            self._fifo.append((idx, pid))
            if len(self._fifo) > self._cap:
                self._set.discard(self._fifo.popleft()[1])
            return False

    def pairs_upto(self, idx: int) -> list:
        """(idx, pid) pairs applied at or below `idx`, FIFO order."""
        with self._mu:
            return [(i, p) for (i, p) in self._fifo if i <= idx]

    def restore(self, pairs) -> None:
        """Replace the window contents (InstallSnapshot receiver side)."""
        with self._mu:
            self._fifo = deque(pairs)
            self._set = {p for (_, p) in self._fifo}
            while len(self._fifo) > self._cap:
                self._set.discard(self._fifo.popleft()[1])


# Snapshot-blob framing: the node wraps the state machine's opaque blob
# with the dedup window so exactly-once survives a full state transfer.
_SNAP_MAGIC = 0x02
_SNAP_HDR = struct.Struct("<BI")
_SNAP_PAIR = struct.Struct("<QQ")


def wrap_snapshot(pairs, sm_blob: bytes) -> bytes:
    out = [_SNAP_HDR.pack(_SNAP_MAGIC, len(pairs))]
    for i, p in pairs:
        out.append(_SNAP_PAIR.pack(i, p))
    out.append(sm_blob)
    return b"".join(out)


def unwrap_snapshot(blob: bytes):
    """Returns (pairs or None, sm_blob).  Blobs without the magic are
    treated as bare state-machine blobs (window untouched)."""
    if len(blob) >= _SNAP_HDR.size and blob[0] == _SNAP_MAGIC:
        _, n = _SNAP_HDR.unpack_from(blob)
        off = _SNAP_HDR.size
        need = off + n * _SNAP_PAIR.size
        if len(blob) >= need:
            pairs = [_SNAP_PAIR.unpack_from(blob, off + k * _SNAP_PAIR.size)
                     for k in range(n)]
            return pairs, blob[need:]
    return None, blob


# Membership-over-snapshot framing (raftsql_tpu/membership/): an
# InstallSnapshot transfer SKIPS the log, so a receiver restored by one
# would miss any conf-change entries inside the skipped range and keep
# a stale voter configuration.  The sender therefore wraps the (already
# dedup-wrapped) transfer blob with the ACTIVE config at the snapshot
# point; receivers without the magic byte see a bare blob (framing is
# optional, like the dedup wrapper above).
#   0x04 | u64 conf_index | u32 conf_len | conf_entry_bytes | inner
_CONF_MAGIC = 0x04
_CONF_HDR = struct.Struct("<BQI")


def wrap_snapshot_conf(conf_index: int, conf_entry: bytes,
                       inner: bytes) -> bytes:
    return _CONF_HDR.pack(_CONF_MAGIC, conf_index,
                          len(conf_entry)) + conf_entry + inner


def unwrap_snapshot_conf(blob: bytes):
    """Returns ((conf_index, conf_entry) or None, inner_blob)."""
    if len(blob) >= _CONF_HDR.size and blob[0] == _CONF_MAGIC:
        _, idx, n = _CONF_HDR.unpack_from(blob)
        off = _CONF_HDR.size
        if len(blob) >= off + n:
            return (idx, blob[off:off + n]), blob[off + n:]
    return None, blob
