"""Proposal envelopes: exactly-once apply under forward-retry.

The reference forwards proposals to the leader via etcd/raft's MsgProp
routing and simply loses them if the leader is down — the client's PUT
hangs forever.  This runtime retries forwarding (runtime/node.py), which
upgrades delivery to at-least-once; the envelope downgrades apply back to
exactly-once:

  - every proposal is wrapped with a random 64-bit id before entering the
    log:  0x01 | u64 id | payload;
  - at publish time each node tracks the last `window` ids per group and
    drops re-occurrences.  The dedup decision is a pure function of the
    committed log prefix, so every replica (and every replay) makes the
    same decision — replicas stay identical.

Deliberately proposing the same SQL text twice still applies twice (two
proposals, two ids) — preserving the reference's duplicate-query FIFO
semantics (reference db.go:70-75).  No-op/conf entries are empty and not
enveloped (reference skips them at publish, raft.go:84-87).
"""
from __future__ import annotations

import secrets
import struct
from collections import deque
from typing import Optional, Tuple

_MAGIC = 0x01
_HDR = struct.Struct("<BQ")


def new_id() -> int:
    return secrets.randbits(64)


def wrap(payload: bytes, pid: Optional[int] = None) -> bytes:
    return _HDR.pack(_MAGIC, new_id() if pid is None else pid) + payload


def unwrap(data: bytes) -> Tuple[Optional[int], bytes]:
    """Returns (proposal id, payload); id is None for bare entries."""
    if len(data) >= _HDR.size and data[0] == _MAGIC:
        _, pid = _HDR.unpack_from(data)
        return pid, data[_HDR.size:]
    return None, data


class DedupWindow:
    """Sliding window of recently applied proposal ids for one group."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._fifo: deque = deque()
        self._set: set = set()

    def seen(self, pid: int) -> bool:
        """Check-and-insert; True if pid was already applied recently."""
        if pid in self._set:
            return True
        self._set.add(pid)
        self._fifo.append(pid)
        if len(self._fifo) > self._cap:
            self._set.discard(self._fifo.popleft())
        return False
