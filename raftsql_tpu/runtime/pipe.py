"""RaftPipe — the propose/commit/error facade (QuorumBackend seam).

The reference's 17-line `raftPipe` (reference raftpipe.go:3-17) bundles
{ProposeC, CommitC, ErrorC}: everything above consensus sees "strings in,
totally ordered strings out".  SURVEY.md §1 marks this as THE seam where
the TPU backend plugs in; here it is the same triple, batched with group
ids, backed by a RaftNode.

close() mirrors the reference contract (raftpipe.go:14-17): stop accepting
proposals, shut the node down, and return the terminal error (None on a
clean shutdown).
"""
from __future__ import annotations

from typing import Optional

from raftsql_tpu.runtime.node import RaftNode


class RaftPipe:
    def __init__(self, node: RaftNode):
        self.node = node
        # Items: (group, index, sql) per-entry (replay), or the RAW
        # batch form (group, base_idx, [bytes, ...]) from the live
        # publish phase (one put per group per tick; entries still
        # enveloped — unwrap/dedup/decode happens on the CONSUMER
        # thread); None = replay-done sentinel, CLOSED = stream end.
        # Consumers normalize via runtime.db._expand_commit_item(item,
        # node).
        self.commit_q = node.commit_q

    @classmethod
    def create(cls, node_id: int, num_nodes: int, cfg, transport,
               data_dir: str) -> "RaftPipe":
        node = RaftNode(node_id, num_nodes, cfg, transport, data_dir)
        pipe = cls(node)
        node.start()
        return pipe

    def propose(self, group: int, payload: bytes,
                pid: Optional[int] = None) -> None:
        self.node.propose(group, payload, pid)

    @property
    def error(self) -> Optional[Exception]:
        return self.node.error

    def close(self) -> Optional[Exception]:
        self.node.stop()
        return self.node.error
