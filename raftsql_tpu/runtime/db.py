"""RaftDB — apply-side state machine driver with ack routing.

Re-design of the reference's `raftdb` (reference db.go:13-167), batched
over groups:

  - consumes the commit stream and applies each committed command to the
    group's state machine in commit order (db.go:45-57);
  - routes per-proposal acks back to waiting clients by *query identity*:
    a FIFO of callbacks per (group, query); duplicate identical queries
    queue multiple callbacks and the first commit acks the head — the
    reference's exact quirk, preserved (db.go:63-76, 112-118, SURVEY.md
    §2d.3).  Commits originating from replay or other nodes have no
    callback and are skipped (db.go:64-69);
  - write/read split: Propose rejects SELECT, Query requires SELECT
    (db.go:98-110, 123-126);
  - local non-linearizable reads (db.go:128-130);
  - on consensus error, every pending ack receives the error and the DB
    shuts down (db.go:83-95);
  - the constructor consumes the replay stream synchronously until the
    `None` sentinel before returning, so the state machine is caught up to
    the WAL before serving (db.go:40, SURVEY.md §3.1 handshake), then a
    reader thread consumes live commits (db.go:41).

The optional commit listener mirrors every applied commit (and the replay
sentinel) to tests — the reference's `commitListenerC` observability hook
(db.go:19, 48-50, 59-61), which its restart tests depend on.
"""
from __future__ import annotations

import json
import logging
import queue
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Dict, Optional, Tuple

from raftsql_tpu.models.base import StateMachine
from raftsql_tpu.models.sqlite_sm import is_select
from raftsql_tpu.overload import (Overloaded, deadline_steps,
                                  zero_metrics_doc)
from raftsql_tpu.runtime.envelope import unwrap
from raftsql_tpu.transport.codec import is_conf_entry
from raftsql_tpu.runtime.node import (CLOSED, RAW_BATCH, RAW_MANY,
                                      RAW_PLAIN)
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.utils.metrics import LatencyTimer

log = logging.getLogger("raftsql_tpu.db")


def iter_plain_entries(base, datas):
    """Yield (index, decoded_command) for each non-empty entry of one
    plain-payload sub-batch (entries at base+1..).  Lives next to
    _expand_commit_item so the plain wire contract (index base,
    empty-entry skip, utf-8 payloads) has exactly one owner; hot
    consumers (the durable benchmark's drain) use this instead of
    building per-entry (group, index, str) tuples."""
    idx = base
    for d in datas:
        idx += 1
        if d:
            yield idx, d.decode("utf-8")


def iter_plain_batches(item):
    """Yield (group, base_idx, [raw_bytes, ...]) sub-batches of a
    plain-payload commit item — one batch for RAW_PLAIN, the whole
    tick's batches for RAW_MANY.  Same single-owner rationale as
    iter_plain_entries; payloads follow the plain contract (no
    envelopes, empty bytes = no-op entries the consumer skips)."""
    if item[0] is RAW_PLAIN:
        yield item[1], item[2], item[3]
    elif item[0] is RAW_MANY:
        yield from item[1]


def _expand_commit_item(item, node=None, dups=None):
    """Normalize a commit_q item to per-entry (group, index, sql) tuples.

    `dups` (optional list) collects (group, index, sql) for committed
    entries the dedup window SKIPPED — a client-retried or
    forward-retried duplicate that already applied.  The caller must
    still ACK those by query identity (the retry's client is waiting on
    this very commit; without the ack a PUT retried across a crash
    would hang forever even though its first copy applied).

    Four forms, discriminated explicitly:
      - (RAW_BATCH, group, base_idx, [raw_bytes, ...]) — the live
        publish phase's tagged batch (entries at base_idx+1..): one
        queue put per group per tick, with the per-entry envelope
        unwrap / dedup / utf-8 decode done HERE, on the consumer
        thread, off the tick's critical path (`node.dedup_for(g)`
        supplies the per-group DedupWindow — forward-retried
        duplicates apply exactly once);
      - (RAW_PLAIN, group, base_idx, [raw_bytes, ...]) — same shape,
        but payloads are PLAIN (never enveloped): only producers whose
        proposals bypass the wrap/forward path may emit it (the
        fused/mesh runtimes, which route proposals on the host).
        Tagging wrapped payloads RAW_PLAIN would apply entries with
        envelope header bytes prepended;
      - (RAW_MANY, [(group, base_idx, [raw_bytes, ...]), ...]) — a
        whole fused tick's RAW_PLAIN batches in one queue item (same
        plain-payload contract);
      - (group, index, sql_str) — WAL replay per-entry items (the
        nil-sentinel counting protocol must stay item-accurate there);
      - (group, [(index, sql), ...]) — decoded per-group batches (older
        producers/tests).
    """
    if item[0] is RAW_BATCH:
        _, g, base, datas = item
        dedup = node.dedup_for(g) if node is not None else None
        out = []
        for off, data in enumerate(datas):
            if not data or is_conf_entry(data):
                continue                    # no-op/conf entry
            pid, payload = unwrap(data)
            if pid is not None and dedup is not None \
                    and dedup.seen(pid, base + 1 + off):
                if dups is not None:        # retry duplicate: ack, no apply
                    dups.append((g, base + 1 + off,
                                 payload.decode("utf-8")))
                continue
            out.append((g, base + 1 + off, payload.decode("utf-8")))
        return out
    if item[0] is RAW_PLAIN:
        _, g, base, datas = item
        return [(g, base + 1 + off, data.decode("utf-8"))
                for off, data in enumerate(datas)
                if data and not is_conf_entry(data)]
    if item[0] is RAW_MANY:
        return [(g, base + 1 + off, data.decode("utf-8"))
                for (g, base, datas) in item[1]
                for off, data in enumerate(datas)
                if data and not is_conf_entry(data)]
    if len(item) == 2:
        g = item[0]
        return [(g, i, s) for (i, s) in item[1]]
    if len(item) == 3 and isinstance(item[2], str):
        return [item]
    raise TypeError(f"unrecognized commit_q item shape: {item!r:.120}")


class NotLeaderError(Exception):
    """A linearizable read hit a non-leader; retry at `leader` (1-based
    node id, 0 = unknown)."""

    def __init__(self, group: int, leader: int):
        super().__init__(
            f"group {group}: not the leader"
            + (f"; leader is node {leader}" if leader > 0 else ""))
        self.group = group
        self.leader = leader


class ReadTimeout(TimeoutError):
    """A read could not be served within the request timeout — a TYPED,
    RETRYABLE condition (quorum unreachable mid-ReadIndex round, apply
    lagging the read point, a session watermark not yet replicated, or
    leadership lost mid-round without a forward hint).  Subclasses
    TimeoutError so both HTTP planes keep answering 503 Service
    Unavailable (retry-at-will), never a 400; `phase` names which wait
    ran out, so a client log pinpoints the stall."""

    def __init__(self, group: int, phase: str, detail: str):
        super().__init__(f"group {group}: {detail}")
        self.group = group
        self.phase = phase


class AckFuture:
    """The reference's buffered `chan error` (db.go:107): one result,
    delivered once, awaited by one client."""

    def __init__(self):
        self._evt = threading.Event()
        self._err: Optional[Exception] = None
        self._cb = None
        self._cb_mu = threading.Lock()
        self.created = time.monotonic()

    def set(self, err: Optional[Exception]) -> None:
        self._err = err
        self._evt.set()
        with self._cb_mu:
            cb, self._cb = self._cb, None
        if cb is not None:
            cb(err)

    def wait(self, timeout: Optional[float] = None) -> Optional[Exception]:
        if not self._evt.wait(timeout):
            raise TimeoutError("proposal not committed in time")
        return self._err

    def add_done_callback(self, cb) -> None:
        """Deliver the result to `cb(err)` instead of (or in addition
        to) a blocking wait() — the async API plane's bridge.  At most
        one callback; runs on the resolver's thread (the commit
        consumer), or immediately here if already resolved.  Called
        exactly once."""
        with self._cb_mu:
            if not self._evt.is_set():
                self._cb = cb
                return
        cb(self._err)


class RaftDB:
    def __init__(self, sm_factory: Callable[[int], StateMachine],
                 pipe: RaftPipe, num_groups: int = 1,
                 listener=None, resume: bool = False,
                 compact_every: int = 0, compact_keep: int = 1024):
        """resume=True enables snapshot-resume (SURVEY.md §5.4
        improvement): state machines that persist applied_index (see
        SQLiteStateMachine resume mode) skip re-apply of already-applied
        replayed entries, and — when compact_every > 0 — the WAL prefix
        covered by every group's snapshot is compacted away after every
        `compact_every` applies (retaining `compact_keep` entries for
        follower catch-up).  Default off: reference delete-and-replay
        parity (db.go:27-29)."""
        self.pipe = pipe
        self.num_groups = num_groups
        self.listener = listener            # queue-like or None
        self.resume = resume
        self._compact_every = compact_every if resume else 0
        self._compact_keep = compact_keep
        self._applies_since_compact = 0
        # Witness replica (config.py quorum geometry): this node votes,
        # appends and fsyncs — but owns no SQLite shard.  The real
        # sm_factory is never invoked, so no shard file or directory is
        # ever created; committed payloads are discarded at apply time
        # (they are already durable in the WAL, which is all a witness
        # owes the cluster) and every read is refused up front.
        self.witness_self = bool(getattr(pipe.node, "witness_self",
                                         False))
        if self.witness_self:
            from raftsql_tpu.models.witness import WitnessStateMachine
            sm_factory = WitnessStateMachine
        self._sms: Dict[int, StateMachine] = {
            g: sm_factory(g) for g in range(num_groups)}
        if not any(getattr(sm, "has_durable_snapshot", False)
                   for sm in self._sms.values()):
            # All floors would be 0 (volatile applied indexes must not
            # gate WAL compaction) — a guaranteed no-op; don't take
            # _wal_lock for it every compact_every applies.
            self._compact_every = 0
        if resume:
            # Full state transfer for followers beyond the compaction
            # floor (InstallSnapshot) is only sound when re-apply is
            # snapshot-aware, so it rides the resume flag.
            pipe.node.snapshot_provider = self._snapshot_of
            pipe.node.snapshot_installer = self._install_snapshot
        self._mu = threading.Lock()
        self._q2cb: Dict[Tuple[int, str], deque] = defaultdict(deque)  # raftlint: guarded-by=_mu
        self._failed: Optional[Exception] = None
        self._closed = False
        self.latency = LatencyTimer()   # propose→ack, the p50 north star
        # Serving-plane gauge hook (runtime/ring.py RingServer): a
        # callable whose dict is merged into metrics() — ring depth,
        # proposed/completed counts of the multi-worker deployment.
        self.serving_metrics = None
        # Shared-memory snapshot publisher (runtime/shm.py), attached
        # by RingServer when the worker read fast path is on: every
        # applied run is mirrored into the worker-mapped snapshot log
        # (publish_deltas), snapshot installs republish the group's
        # base image.  None keeps the apply path untouched.
        self.shm = None
        # Read-replica stream server (raftsql_tpu/replica/), attached
        # by the server's --replica-listen flag: the shm publisher's
        # tee framed onto TCP for remote replicas.  None keeps the
        # engine inert; metrics() still exports the zeroed `replica`
        # section so the series exist from boot (scripts/check_prom.py
        # requires them).
        self.replica_plane = None
        # Placement controller (raftsql_tpu/placement/), attached by
        # the server's --placement flag; None keeps metrics() and
        # flight bundles unchanged.
        self.placement = None
        # Reshard plane (raftsql_tpu/reshard/plane.py), attached by the
        # server's --reshard flag: the elastic-keyspace coordinator +
        # keymap router.  None keeps /kv, /healthz and metrics()
        # unchanged (the plane compiles in but stays idle).
        self.reshard = None
        # propose→commit (stamped when the committed entry reaches the
        # apply consumer — commit + publish, before apply): the
        # histogram /metrics exports as propose_commit_p50/p95/p99_ms.
        self.latency_commit = LatencyTimer()

        # Synchronous replay consumption (db.go:40): apply until the
        # sentinel so reads see the replayed state before we return.
        self._read_commits(replay=True)
        self._reader = threading.Thread(target=self._read_commits,
                                        daemon=True, name="raftdb-reader")
        self._reader.start()

    # ------------------------------------------------------------------

    def _node_tracer(self):
        """The engine's span tracer, or None (tracing may be enabled
        after construction — resolve per use, it is one getattr)."""
        return getattr(getattr(self.pipe, "node", None), "tracer", None)

    def _ack_one(self, group: int, query: str, err,
                 commit_ts: Optional[float] = None) -> None:
        if self.listener is not None:
            self.listener.put((group, query))
        tracer = self._node_tracer()
        if tracer is not None:
            tracer.note_ack(group, query)
        # Per-group traffic accounting (utils/metrics.py GroupTraffic):
        # the ack leg — proposes/commits are stamped in the host plane.
        traffic = getattr(self.pipe.node, "traffic", None)
        if traffic is not None:
            traffic.add_ack(group)
        with self._mu:
            cbs = self._q2cb.get((group, query))
            if not cbs:
                return                  # replayed or proposed elsewhere
            cb = cbs.popleft()
            if not cbs:
                del self._q2cb[(group, query)]
        cb.set(err)
        self.latency.record(time.monotonic() - cb.created)
        if commit_ts is not None:
            # commit_ts is when this run was drained off the commit
            # queue — the commit observation point, before apply.
            self.latency_commit.record(commit_ts - cb.created)

    def _apply_run(self, run) -> None:
        """Apply a drained run of commits with GROUP COMMIT: entries are
        batched per state machine and applied in one durable transaction
        each (models apply_batch; per-item fallback otherwise), then
        acks/listeners fire in original commit order.  In resume mode
        the state machine itself skips entries at or below its durable
        applied index (atomically under its own lock, racing snapshot
        installs safely) and returns None — so skipped-but-committed
        entries still resolve their acks."""
        commit_ts = time.monotonic()    # commit observation point
        per_g: Dict[int, list] = defaultdict(list)
        for (group, index, query) in run:
            per_g[group].append((query, index))
        errs: Dict[int, list] = {}
        for group, items in per_g.items():
            sm = self._sms[group]
            batch_fn = getattr(sm, "apply_batch", None)
            if batch_fn is not None:
                errs[group] = batch_fn(items)
            else:
                errs[group] = [sm.apply(qy, ix) for (qy, ix) in items]
        if self.shm is not None:
            # Mirror the applied run into the worker-mapped snapshot
            # log BEFORE acks fire: a client whose PUT just acked may
            # immediately session-read at a worker, and the worker's
            # replica must be able to reach that watermark.  Statements
            # that errored are published too — workers re-apply them
            # under the same SAVEPOINT semantics, so replica state
            # stays bit-identical to the engine's.
            try:
                self.shm.publish_deltas(per_g)
            except Exception:                           # noqa: BLE001
                log.exception("shm delta publish failed; disabling")
                self.shm = None
        tracer = self._node_tracer()
        pos = {g: 0 for g in per_g}
        for (group, index, query) in run:
            err = errs[group][pos[group]]
            pos[group] += 1
            if tracer is not None:
                tracer.note_apply(group, index)
            self._ack_one(group, query, err, commit_ts=commit_ts)
        for _ in run:
            self._maybe_compact()

    def _read_commits(self, replay: bool = False) -> None:
        q = self.pipe.commit_q
        while True:
            item = q.get()
            if item is None:
                if self.listener is not None:
                    self.listener.put(None)
                if replay:
                    return
                continue
            if item is CLOSED:
                break
            # Greedy drain (live loop only): everything already queued
            # joins this item's group-committed batch.  The replay pass
            # must stay strictly item-at-a-time — draining could swallow
            # live entries beyond the nil sentinel it returns at.
            # Items arrive per-entry (group, index, sql) from replay, or
            # as per-group RAW batches (group, base_idx, [bytes, ...])
            # from the live publish phase (runtime/node.py) — expanded
            # (unwrap/dedup/decode) HERE so the tick thread pays one
            # queue put per group and none of the per-entry Python.
            dups: list = []
            run = _expand_commit_item(item, self.pipe.node, dups)
            stop = False
            if not replay:
                while len(run) < 256:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        # Preserve the sentinel's position in the
                        # listener protocol relative to this run.
                        self._apply_run(run)
                        run = []
                        if self.listener is not None:
                            self.listener.put(None)
                        continue
                    if nxt is CLOSED:
                        stop = True
                        break
                    run.extend(_expand_commit_item(nxt, self.pipe.node,
                                                   dups))
            if run:
                self._apply_run(run)
            for (group, index, query) in dups:
                # A committed RETRY duplicate: its first copy applied
                # (this run or earlier), so the retrying client's PUT
                # succeeded — ack success without re-applying.
                self._ack_one(group, query, None,
                              commit_ts=time.monotonic())
            if stop:
                break

        # Stream closed: clean shutdown or error teardown (db.go:83-95).
        err = self.pipe.error
        if err is not None:
            with self._mu:
                pending = [cb for cbs in self._q2cb.values() for cb in cbs]
                self._q2cb.clear()
                self._failed = err
            for cb in pending:
                cb.set(err)

    # ------------------------------------------------------------------

    def _snapshot_of(self, group: int):
        sm = self._sms[group]
        fn = getattr(sm, "serialize_with_index", None)
        if fn is None:
            return None
        idx, blob = fn()
        return (idx, blob) if idx > 0 else None

    # Grace before failing acks orphaned by a snapshot install: commits
    # ABOVE the snapshot still publish normally and must keep their acks.
    SNAPSHOT_ACK_GRACE_S = 5.0

    def _install_snapshot(self, group: int, index: int,
                          blob: bytes) -> None:
        self._sms[group].install(blob, index)
        if self.shm is not None:
            # A state transfer skipped the delta stream: workers must
            # rebuild their replica from the installed image, so the
            # group's base is republished into the snapshot log.
            try:
                self.shm.publish_base(group, blob, index)
            except Exception:                           # noqa: BLE001
                log.exception("shm base publish failed; disabling")
                self.shm = None
        # A state transfer SKIPS the log: proposals whose commits sit
        # INSIDE the snapshot are never published here, so their acks
        # would wait forever (the reference never snapshots and inherits
        # the hang only for lost proposals).  But a pending ack may also
        # belong to a commit ABOVE the snapshot — about to stream in and
        # ack normally — and the two are indistinguishable by (group,
        # query) key.  So: snapshot the exact callbacks pending NOW, give
        # the post-install catch-up a grace window to drain them, and
        # fail only the leftovers with a retriable error.  Hazard,
        # documented: a flushed write may in fact be inside the installed
        # state — a client retrying a non-idempotent statement should
        # verify first (same duplicate exposure as the reference's
        # content-keyed FIFO, db.go:112-118).
        with self._mu:
            stale = [(k, cb) for k, cbs in self._q2cb.items()
                     if k[0] == group for cb in cbs]
        if not stale:
            return
        err = RuntimeError(
            f"group {group}: pending proposal superseded by snapshot "
            f"install at index {index}; state may include the write — "
            "verify before retrying")

        def flush():
            victims = []
            with self._mu:
                for k, cb in stale:
                    cbs = self._q2cb.get(k)
                    if cbs and cb in cbs:
                        cbs.remove(cb)
                        if not cbs:
                            self._q2cb.pop(k, None)
                        victims.append(cb)
            for cb in victims:
                cb.set(err)

        t = threading.Timer(self.SNAPSHOT_ACK_GRACE_S, flush)
        t.daemon = True
        t.start()

    def _maybe_compact(self) -> None:
        if not self._compact_every:
            return
        self._applies_since_compact += 1
        if self._applies_since_compact < self._compact_every:
            return
        self._applies_since_compact = 0
        # Volatile applied indexes (has_durable_snapshot unset/False) are
        # floored at 0: compacting the WAL against state lost on restart
        # would be silent data loss (models/base.py contract).
        applied = {g: (sm.applied_index()
                       if getattr(sm, "has_durable_snapshot", False) else 0)
                   for g, sm in self._sms.items()}
        self.pipe.node.compact(applied, keep=self._compact_keep)

    def propose(self, query: str, group: int = 0,
                token: Optional[int] = None,
                deadline_ms: Optional[float] = None) -> AckFuture:
        """Submit a write; the future resolves after commit + local apply
        (the reference's blocking-PUT contract, httpapi.go:45-49).

        `token` (a client retry token, X-Raft-Retry-Token) pins the
        proposal's envelope id: a client re-sending the same logical
        PUT — after a timeout, a dropped connection, or a crashed
        leader — passes the same token and the publish-time dedup
        window applies whichever copies commit exactly once (the
        duplicate's commit still ACKS, it just doesn't re-apply).

        `deadline_ms` (remaining client budget, X-Raft-Deadline-Ms) is
        converted ONCE here from wall budget to a device-step deadline
        (raftsql_tpu/overload/ discipline) and carried with the queue
        entry, so work already expired at staging time is shed before
        WAL/fsync cost is paid.  Raises `Overloaded` (HTTP 429) when an
        attached admission controller refuses the enqueue; no-op when
        no overload plane is attached."""
        fut = AckFuture()
        if is_select(query):
            fut.set(ValueError("expected non-SELECT"))
            return fut
        if not 0 <= group < self.num_groups:
            fut.set(ValueError(f"group {group} out of range "
                               f"[0, {self.num_groups})"))
            return fut
        node = self.pipe.node
        dstep = None
        if deadline_ms is not None \
                and getattr(node, "overload", None) is not None:
            dstep = deadline_steps(node._device_steps, deadline_ms,
                                   node.cfg.tick_interval_s)
        with self._mu:
            if self._failed is not None:
                fut.set(self._failed)
                return fut
            if self._closed:
                fut.set(RuntimeError("db is closed"))
                return fut
            self._q2cb[(group, query)].append(fut)
        try:
            if dstep is not None:
                self.pipe.propose(group, query.encode("utf-8"), token,
                                  deadline_step=dstep)
            else:
                self.pipe.propose(group, query.encode("utf-8"), token)
        except Overloaded:
            # Refused at the admission edge: nothing was enqueued, so
            # the ack callback must not linger in _q2cb.
            self.abandon(query, group, fut)
            raise
        return fut

    def abandon(self, query: str, group: int, fut: AckFuture) -> None:
        """Deregister a timed-out proposal's callback so it cannot leak in
        `_q2cb` forever (the proposal itself may still commit later; its
        apply is unaffected — only the ack is orphaned)."""
        with self._mu:
            cbs = self._q2cb.get((group, query))
            if cbs is None:
                return
            try:
                cbs.remove(fut)
            except ValueError:
                return
            if not cbs:
                del self._q2cb[(group, query)]

    def pending_for(self, group: int) -> int:
        """Acks still outstanding for `group` — the reshard drain gate:
        a frozen slot's verb may not copy rows until every write that
        was in flight at freeze time either acked or errored."""
        with self._mu:
            return sum(len(d) for (g, _q), d in self._q2cb.items()
                       if g == group)

    def watermark(self, group: int = 0) -> int:
        """This replica's applied index for `group` — the session
        watermark echoed as X-Raft-Session on both HTTP planes.  A
        client that carries the largest watermark it has seen and
        presents it on `mode="session"` reads gets read-your-writes
        and monotonic reads from ANY replica."""
        return int(self._sms[group].applied_index())

    def _wait_applied(self, group: int, target: int, deadline: float,
                      tick: float, phase: str) -> None:
        """Block until the local apply reaches `target` (bounded)."""
        while self._sms[group].applied_index() < target:
            if self._failed is not None:
                raise self._failed
            now = time.monotonic()
            if now > deadline:
                raise ReadTimeout(
                    group, phase,
                    f"apply (at {self._sms[group].applied_index()}) "
                    f"did not reach read point {target} in time")
            time.sleep(min(tick, max(deadline - now, 0.0005)))

    def query(self, query: str, group: int = 0,
              linear: bool = False, timeout: float = 10.0,
              mode: Optional[str] = None, watermark: int = 0,
              deadline_ms: Optional[float] = None,
              brownout: bool = False,
              info: Optional[dict] = None) -> str:
        """Read path, five consistency modes (README read-modes table):

          - "local" (default): the reference's stale local read —
            never touches consensus (db.go:123-130);
          - "session": local read AFTER the replica's apply reaches the
            client-provided `watermark` (X-Raft-Session echo from a
            previous write/read) — read-your-writes + monotonic reads
            at any replica;
          - "follower": local read at the replicated read-index
            watermark — this node's CURRENT commit index — so the
            answer reflects everything this replica knows committed at
            request arrival (fresher than local, no leader round);
          - "linear" (or linear=True): LINEARIZABLE.  Served from the
            leader LEASE when one covers now + max_clock_skew (no
            quorum round, config.lease_ticks), degrading to the
            ReadIndex quorum round (raft §6.4), degrading to
            NotLeaderError (421 + leader hint) off-leader — each
            degradation explicit, never a silent stale read.

        Bounded: every wait raises typed, retryable ReadTimeout (503)
        within `timeout`; leadership lost mid-round surfaces
        NotLeaderError on the next poll, never an unbounded spin."""
        if not is_select(query):
            raise ValueError("expected SELECT")
        if self.witness_self:
            # Refuse up front: a witness applies nothing, so any wait
            # on its applied index would just spin to ReadTimeout.
            raise ValueError(
                "witness replica serves no reads (it owns no shard); "
                "route the query to a full voter")
        if not 0 <= group < self.num_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.num_groups})")
        if mode is None:
            mode = "linear" if linear else "local"
        node = self.pipe.node
        m = getattr(node, "metrics", None)
        tick = node.cfg.tick_interval_s or 0.001
        if deadline_ms is not None:
            # The client's end-to-end budget bounds every wait below;
            # a tighter server-side timeout still wins.
            timeout = min(timeout, max(float(deadline_ms) / 1000.0, 0.0))
        deadline = time.monotonic() + timeout
        if info is not None:
            info["served"] = mode
        if mode == "local":
            if m is not None:
                m.reads_local += 1
        elif mode == "session":
            if m is not None:
                m.reads_session += 1
            if watermark > 0:
                self._wait_applied(group, watermark, deadline, tick,
                                   "session")
        elif mode == "follower":
            if m is not None:
                m.reads_follower += 1
            wm_fn = getattr(node, "commit_watermark", None)
            target = wm_fn(group) if wm_fn is not None \
                else max(watermark, 0)
            self._wait_applied(group, target, deadline, tick, "follower")
        elif mode == "linear":
            self._linear_wait(node, group, deadline, tick,
                              brownout=brownout, info=info)
        else:
            raise ValueError(f"unknown read mode {mode!r}")
        return self._sms[group].query(query)

    def _linear_wait(self, node, group: int, deadline: float,
                     tick: float, brownout: bool = False,
                     info: Optional[dict] = None) -> None:
        """The linearizable read protocol: lease fast path, then the
        ReadIndex round, each wait bounded by `deadline`.

        Brownout ladder (raftsql_tpu/overload/): when an attached
        governor reports sustained queue pressure, the ReadIndex
        fallback is withheld — the lease fast path still serves full
        linearizability for free, but a lease miss refuses (429) unless
        the client opted in via `brownout=True` (X-Raft-Brownout:
        allow), in which case the read degrades to a session read at
        this replica's current applied point and `info["served"]`
        names the mode actually served.  Never a silent downgrade."""
        m = getattr(node, "metrics", None)
        lease_fn = getattr(node, "lease_read", None)
        lease_on = node.cfg.lease_ticks > 0 and lease_fn is not None
        if lease_on:
            target = lease_fn(group)
            if target is not None:
                if m is not None:
                    m.reads_lease += 1
                self._wait_applied(group, target, deadline, tick,
                                   "lease_apply")
                return
            # Lease unavailable (expired / not leader / precondition
            # pending): degrade to the full quorum round.
            if m is not None:
                m.lease_degrades += 1
        ov = getattr(node, "overload", None)
        if ov is not None:
            path = ov.brownout_read_path(brownout)  # may raise Overloaded
            if path == "session":
                # Opted-in degradation: serve at whatever this replica
                # has applied, skipping the quorum round entirely.
                if info is not None:
                    info["served"] = "session"
                return
        if m is not None:
            m.reads_read_index += 1
        join_fn = getattr(node, "read_join", None)
        if join_fn is not None:
            # Batched ReadIndex (runtime/node.py): join the group's
            # shared per-tick round and sleep on its event — N
            # concurrent readers cost one quorum round per tick, and
            # nobody poll-spins at tick cadence.
            while True:
                b = join_fn(group)
                if b is None:
                    raise NotLeaderError(group,
                                         node.leader_of(group) + 1)
                # A spurious wake on a still-pending batch must keep
                # waiting on the SAME batch — re-joining would bump its
                # count again and double-count this reader in
                # reads_read_index_batched and the batch-size histogram.
                while not b.status:
                    if time.monotonic() > deadline:
                        raise ReadTimeout(
                            group, "confirm",
                            "leadership not re-confirmed "
                            "(no quorum reachable?)")
                    b.evt.wait(max(deadline - time.monotonic(), 0.0))
                if b.status == "ok":
                    self._wait_applied(group, b.target, deadline,
                                       tick, "apply")
                    return
                if time.monotonic() > deadline:
                    raise ReadTimeout(
                        group, "confirm",
                        "leadership not re-confirmed "
                        "(no quorum reachable?)")
                # "not_leader": re-join — once the role cache reflects
                # the loss, join returns None and the typed redirect
                # surfaces.
        while True:
            got = node.read_index(group)
            if got is None:
                raise NotLeaderError(group, node.leader_of(group) + 1)
            if got != ():
                break
            # Leader without a committed current-term entry yet
            # (raft §6.4 precondition) — its no-op is in flight.
            if time.monotonic() > deadline:
                raise ReadTimeout(group, "read_index",
                                  "no current-term commit yet")
            time.sleep(tick)
        target, reg = got
        while not node.read_ready(group, reg):
            # Leadership lost mid-round: surface the typed redirect on
            # the NEXT poll — the round can never confirm and spinning
            # it out to the deadline would stall the client for
            # nothing (the leader hint names where to retry).
            if node.read_index(group) is None:
                raise NotLeaderError(group, node.leader_of(group) + 1)
            if time.monotonic() > deadline:
                raise ReadTimeout(
                    group, "confirm",
                    "leadership not re-confirmed "
                    "(no quorum reachable?)")
            time.sleep(tick)
        self._wait_applied(group, target, deadline, tick, "apply")

    def metrics(self) -> dict:
        def ms(v):
            return round(v * 1e3, 3) if v == v else None   # NaN -> null

        m = self.pipe.node.metrics.snapshot()
        # propose→commit (stamped at the commit observation point,
        # before apply) and propose→ack (after apply, the full
        # blocking-PUT latency the client sees).
        c50, c95, c99 = self.latency_commit.percentiles(
            (0.5, 0.95, 0.99))
        m["propose_commit_p50_ms"] = ms(c50)
        m["propose_commit_p95_ms"] = ms(c95)
        m["propose_commit_p99_ms"] = ms(c99)
        a50, a99 = self.latency.percentiles((0.5, 0.99))
        m["propose_ack_p50_ms"] = ms(a50)
        m["propose_ack_p99_ms"] = ms(a99)
        # Membership observability (raftsql_tpu/membership/): active
        # voter/learner slot totals across groups + applied conf-change
        # count.  Engines without a manager report the static shape.
        node = self.pipe.node
        mm = getattr(node, "membership", None)
        if mm is not None:
            v, l = mm.counts()
        else:
            v, l = node.cfg.num_peers * node.cfg.num_groups, 0
        m["members_voters"] = v
        m["members_learners"] = l
        # Quorum geometry (config.py flexible quorums + witnesses):
        # the per-phase thresholds this deployment runs under and the
        # provisioned witness count — static per config, exported so an
        # operator can read the geometry off any node's /metrics.
        cfg = node.cfg
        m["quorum"] = {
            "write_size": cfg.write_size,
            "election_size": cfg.election_size,
            "witnesses": len(cfg.witness_set),
        }
        # Telemetry plane (PR 8, default on): per-phase tick wall-time
        # histograms and the per-group traffic table with its top-K
        # hot-groups rows — the feed the placement controller consumes.
        prof = getattr(node, "prof", None)
        if prof is not None:
            m["phase_profile"] = prof.snapshot()
        traffic = getattr(node, "traffic", None)
        if traffic is not None:
            xg = getattr(node, "transferring_groups", None)
            m["group_traffic"] = traffic.doc(
                leader_of=getattr(node, "leader_of", None),
                shard_of=getattr(node, "_group_shard_of", None),
                transferring=xg() if callable(xg) else None)
        # Placement controller (raftsql_tpu/placement/): balance gauges
        # + issue counters, when a controller is attached.
        if self.placement is not None:
            m["placement"] = self.placement.metrics_doc()
        # Reshard plane (raftsql_tpu/reshard/): verb counters, per-verb
        # duration histogram, mapping epoch + active-verb gauge.
        if self.reshard is not None:
            m["reshard"] = self.reshard.metrics_doc()
        # Read-replica tier (raftsql_tpu/replica/): stream-server
        # counters when --replica-listen attached a plane; zeros
        # otherwise, so the raftsql_replica_* series exist from boot
        # on every deployment (scripts/check_prom.py requires them).
        if self.replica_plane is not None:
            m["replica"] = self.replica_plane.metrics_doc()
        else:
            m["replica"] = {"subscribers": 0, "deltas_tx": 0,
                            "bases_tx": 0, "resyncs": 0,
                            "refusals": 0, "lag_ms": 0}
        # Overload plane (raftsql_tpu/overload/): admission, per-phase
        # shed, and brownout counters when a controller is attached;
        # zeros otherwise so the raftsql_overload_* series exist from
        # boot on every deployment (scripts/check_prom.py requires
        # them), same contract as the replica section above.
        ovc = getattr(node, "overload", None)
        m["overload"] = (ovc.metrics_doc() if ovc is not None
                         else zero_metrics_doc())
        gcw = getattr(node, "_gcwal", None)
        if gcw is not None:
            # Group-commit batch histogram: peers coalesced per fsync
            # -> count (how well the one-fsync-per-tick lever engages).
            m["wal_gc_batch_hist"] = {
                str(k): v for k, v in sorted(gcw.batch_hist.items())}
        if self.serving_metrics is not None:
            try:
                m.update(self.serving_metrics())
            except Exception:                           # noqa: BLE001
                pass        # a gauge must never break the scrape
        return m

    def render_metrics(self) -> str:
        return json.dumps(self.metrics(), sort_keys=True) + "\n"

    def render_metrics_prom(self) -> str:
        """GET /metrics?format=prom: the same document in the
        Prometheus text exposition (utils/metrics.py prom_render —
        every JSON counter/gauge/histogram becomes a sample; validated
        by scripts/check_prom.py)."""
        from raftsql_tpu.utils.metrics import prom_render
        return prom_render(self.metrics())

    # -- membership admin (raftsql_tpu/membership/) ---------------------

    def members(self) -> dict:
        """GET /members: per-group active configuration + leader."""
        node = self.pipe.node
        fn = getattr(node, "members_doc", None)
        if fn is None:
            return {"error": "engine has no membership plane"}
        return fn()

    def member_change(self, group: int, op: str, peer: int) -> dict:
        """POST /members: propose add/remove/promote of a peer slot.
        Maps the membership plane's not-leader error onto the API's
        NotLeaderError so both HTTP planes answer 421 + the hint."""
        from raftsql_tpu.membership import NotLeaderForChange
        node = self.pipe.node
        fn = getattr(node, "member_change", None)
        if fn is None:
            raise ValueError("engine has no membership plane")
        try:
            return fn(group, op, peer)
        except NotLeaderForChange as e:
            raise NotLeaderError(e.group, e.leader) from e

    def transfer(self, group: int, target: int) -> dict:
        """POST /transfer: arm a graceful leadership transfer of
        `group` to peer slot `target` (0-based, like /members' `peer`;
        thesis §3.10 TimeoutNow — the device plane stalls intake, waits
        for catch-up, fires the grant).  Not-leader maps onto
        NotLeaderError so both HTTP planes answer 421 + the hint;
        validation refusals (in-flight transfer, learner target)
        surface as 400s."""
        from raftsql_tpu.membership import NotLeaderForChange
        node = self.pipe.node
        fn = getattr(node, "transfer_leadership", None)
        if fn is None:
            raise ValueError("engine has no leadership-transfer plane")
        try:
            return fn(group, target)
        except NotLeaderForChange as e:
            raise NotLeaderError(e.group, e.leader) from e

    def render_members(self) -> str:
        return json.dumps(self.members(), sort_keys=True) + "\n"

    # -- readiness (GET /healthz) ---------------------------------------

    def health_doc(self) -> dict:
        """GET /healthz: node id, per-group role / leader hint / term /
        commit (from the engine's host-side status caches) plus each
        group's APPLIED index from the state machines.  Answering at
        all means the process is up and replay finished (the
        constructor blocks on replay); the nemesis and operators read
        role/leader to detect restart completion without a write
        probe."""
        node = self.pipe.node
        status_fn = getattr(node, "status", None)
        groups = status_fn() if status_fn is not None else {
            str(g): {"role": "unknown",
                     "leader": int(node.leader_of(g)) + 1
                     if hasattr(node, "leader_of") else 0}
            for g in range(self.num_groups)}
        # Routing hints (PR 12, api/client.py front router): per-group
        # remaining lease seconds — a client routes linearizable reads
        # to the node reporting a live lease, writes to the leader.
        lease_fn = getattr(node, "lease_deadline_s", None)
        now = time.monotonic()
        for g in range(self.num_groups):
            row = groups.get(str(g))
            if row is not None:
                row["applied"] = int(self._sms[g].applied_index())
                if lease_fn is not None:
                    row["lease_s"] = round(
                        max(lease_fn(g) - now, 0.0), 4)
        doc = {"id": int(getattr(node, "node_id", 0)),
               "ready": True, "groups": groups}
        # Pod deployment (raftsql_tpu/pod/): topology + ownership.  The
        # `hosts` table lets a client pointed at ONE pod host discover
        # the sweep set; `pod_owned` on each group row names which rows
        # THIS host serves (compute is replicated, so every host
        # truthfully reports every group — ownership, not role, is the
        # routing key; api/client.py refresh_hints merges the sweep).
        pod_fn = getattr(node, "pod_doc", None)
        if pod_fn is not None:
            doc["pod"] = pod_fn()
            for g in range(self.num_groups):
                row = groups.get(str(g))
                if row is not None:
                    row["pod_owned"] = bool(node.owns_group(g))
        if self.witness_self:
            # Routers and the chaos harness key off this: witnesses
            # accept writes (forwarded like any follower) but must
            # never be picked as a read target.
            doc["witness"] = True
        # Elastic keyspace (raftsql_tpu/reshard/): the versioned
        # key->group mapping.  Clients cache this and fail closed when
        # a /kv response reports a newer epoch.
        if self.reshard is not None:
            doc["keymap"] = self.reshard.keymap.to_doc()
        # Read-replica tier (raftsql_tpu/replica/): stream listen port,
        # per-subscriber applied/lag tails and — the client sweep's
        # hook — the advertised replica HTTP endpoints, which
        # api/client.py adopts and routes read-mode traffic to.
        if self.replica_plane is not None:
            try:
                doc["replica"] = self.replica_plane.health_doc()
            except Exception:                           # noqa: BLE001
                pass        # readiness must never break on a gauge
        return doc

    def render_health(self) -> str:
        return json.dumps(self.health_doc(), sort_keys=True) + "\n"

    # -- observability exports (raftsql_tpu/obs/) ----------------------

    def trace_doc(self) -> dict:
        """Chrome trace-event JSON of the engine's span tracer + device
        event ring + tick-phase profiler tracks + any worker-process
        trace segments (GET /trace; Perfetto-loadable).  A `--workers N`
        deployment's document is ONE multi-process timeline: the
        engine's spans/phases plus each worker's pid-tagged request
        segment (runtime/ring.py RingServer points
        `trace_segments_dir` at the ring directory the workers flush
        into).  Always a valid (possibly empty) document — tracing off
        just yields no span events."""
        from raftsql_tpu.obs.export import chrome_trace, collect_segments
        node = self.pipe.node
        tracer = self._node_tracer()
        ring = getattr(node, "ring", None)
        prof = getattr(node, "prof", None)
        if ring is not None:
            ring.drain()
        seg_dir = getattr(self, "trace_segments_dir", None)
        segs = collect_segments(seg_dir) if seg_dir else None
        # One time axis for every track family: the tracer's epoch when
        # tracing is on, else the profiler's.
        base = tracer.t0 if tracer is not None else (
            prof.epoch if prof is not None else 0.0)
        # Cap the counter window: a long-lived ring (keep=4096 ticks)
        # would emit ~20 counter events per tick per (peer, group) —
        # the last 1024 ticks keep the document loadable.
        return chrome_trace(
            tracer.snapshot() if tracer is not None else None,
            ring.rows(last=1024) if ring is not None else None,
            phase_events=prof.events() if prof is not None else None,
            process_segments=segs,
            base_monotonic=base)

    def events_doc(self, last: int = 256) -> dict:
        """Raw observability state (GET /events): the device ring's
        drained per-tick rows plus the host tracer's snapshot."""
        node = self.pipe.node
        tracer = self._node_tracer()
        ring = getattr(node, "ring", None)
        if ring is not None:
            ring.drain()
        return {
            "tracing": tracer is not None or ring is not None,
            "device": ring.rows(last=last) if ring is not None else [],
            "host": tracer.snapshot() if tracer is not None else {},
        }

    def render_trace(self) -> str:
        return json.dumps(self.trace_doc(), sort_keys=True) + "\n"

    def render_events(self) -> str:
        return json.dumps(self.events_doc(), sort_keys=True) + "\n"

    def close(self) -> Optional[Exception]:
        """Shut down, failing (not leaking) any still-pending acks.

        The reference fatals on pending acks (db.go:159-161); failing them
        with an error instead is the conscious improvement — a node with
        in-flight proposals at shutdown (e.g. lost quorum) must still be
        able to close its WAL and state machines cleanly."""
        with self._mu:
            if self._closed:
                return None
            self._closed = True
            pending = [cb for cbs in self._q2cb.values() for cb in cbs]
            self._q2cb.clear()
        for cb in pending:
            cb.set(RuntimeError("db closing with proposal outstanding"))
        if self.replica_plane is not None:
            try:
                self.replica_plane.stop()
            except Exception:                           # noqa: BLE001
                pass
            self.replica_plane = None
        err = self.pipe.close()
        self._reader.join(timeout=10)
        for sm in self._sms.values():
            sm.close()
        return err
