"""MeshClusterNode — the durable runtime SPMD over a real device mesh.

Everything before this subsystem ran G groups on ONE device; a
MULTICHIP pod shows 8 healthy devices and 7 of them idle.  This module
promotes the fused runtime to the mesh: the per-tick consensus program
runs under `Mesh` + `shard_map` with G sharded over a `groups` axis
(parallel/sharded.py — DrJAX-style MapReduce-over-shard_map is the
programming model: per-group math is embarrassingly parallel, zero
collectives on the group axis, and the optional `peers` axis rides one
all_to_all over ICI for the message exchange), while the DURABLE HOST
PLANE is sharded to match:

  * per-local-shard WAL dirs — each peer's log splits into one
    directory (one append stream + one fsync stream) per group shard
    (ShardedWAL below: data_dir/p<i>/s<j>), so the host's durable
    barrier parallelizes the way the device plane does;
  * per-shard publish workers — one ordered worker per group shard
    drains commits to the apply plane (ClusterHostPlane's publish
    seam), so the host side finally gets real cores;
  * per-shard state-machine placement — the server deployment lays
    SQLite files out under db/s<j>/ (server/main.py build_mesh_node).

The host phase itself (propose queues, WAL fsync barriers, commit
publish, membership apply-at-commit) is runtime/hostplane.py
ClusterHostPlane, SHARED with the single-device FusedClusterNode — the
two runtimes differ only in `_device_step`.  The durable ordering
argument is unchanged on the mesh because the host still interposes
every peer's WAL fsync between dispatches: what was rafthttp between
processes in the reference (raft.go:230) is a collective between
chips here.

Per-peer clock skew is fully plumbed: `timer_inc` [P] shards over the
`peers` axis (parallel/sharded.py timer_spec), so chaos SkewWindow
schedules run on the mesh exactly as on the fused runtime — the old
`MeshLockstepOnlyError` frontier is closed.

Payload note: one host process drives the whole mesh (the
single-controller model), so payload mirroring between peers stays a
host-memory copy exactly as in the fused runtime — only consensus math
and message metadata ride the mesh.

Testable without hardware: force a multi-device CPU platform with
`XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu`
(tests/conftest.py does this for the whole suite).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from raftsql_tpu.config import RaftConfig
from raftsql_tpu.parallel.sharded import (GROUPS_AXIS, PEERS_AXIS,
                                          make_mesh,
                                          make_sharded_cluster_step_host,
                                          shard_cluster_arrays,
                                          timer_spec)
from raftsql_tpu.runtime.hostplane import ClusterHostPlane
from raftsql_tpu.storage.wal import (DEFAULT_SEGMENT_BYTES, WAL,
                                     wal_exists)

MESH_META = "MESHMETA"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh description for the consensus runtime.

    `peer_shards × group_shards` devices arranged as the
    ('peers', 'groups') mesh of parallel/sharded.py.  The group axis is
    the scale dimension (data-parallel, zero collectives); shard the
    peer axis only when one group's peers should span chips (the
    message exchange then rides all_to_all over ICI).
    """

    peer_shards: int = 1
    group_shards: int = 1

    def __post_init__(self) -> None:
        if self.peer_shards <= 0 or self.group_shards <= 0:
            raise ValueError(
                f"mesh axes must be positive, got "
                f"{self.peer_shards}x{self.group_shards}")

    @property
    def total_devices(self) -> int:
        return self.peer_shards * self.group_shards

    def validate(self, cfg: RaftConfig) -> None:
        if cfg.num_peers % self.peer_shards:
            raise ValueError(f"num_peers {cfg.num_peers} not divisible "
                             f"by peer shards {self.peer_shards}")
        if cfg.num_groups % self.group_shards:
            raise ValueError(f"num_groups {cfg.num_groups} not "
                             f"divisible by group shards "
                             f"{self.group_shards}")

    def build(self, devices=None):
        """Materialize the jax Mesh over the first
        `total_devices` devices."""
        return make_mesh(self.peer_shards, self.group_shards,
                         devices=devices)

    @staticmethod
    def for_groups(cfg: RaftConfig, devices=None,
                   peer_shards: int = 1) -> "MeshConfig":
        """The widest groups-only mesh this host can run: the largest
        group-shard count that divides cfg.num_groups and fits the
        visible devices (after reserving `peer_shards` of them per
        group shard)."""
        n = len(jax.devices() if devices is None else devices)
        avail = max(1, n // peer_shards)
        gg = max(j for j in range(1, avail + 1)
                 if cfg.num_groups % j == 0)
        return MeshConfig(peer_shards=peer_shards, group_shards=gg)


class ShardedWAL:
    """A peer's durable log split per group shard.

    Implements the WAL surface the host plane writes through
    (append_ranges / set_hardstates / set_conf / epoch_mark / sync /
    compact / close), routing each group to the shard WAL that owns its
    block — group g lives in shard g // groups_per_shard, matching the
    device mesh's block layout, so one directory holds exactly the
    groups one device shard computes.  Each shard is a full
    storage/wal.py WAL (same record formats, same repair, same
    compaction), so every durability property is inherited per shard;
    cross-shard atomicity is not needed because the host plane's
    barrier semantics are per-peer fsync-before-next-dispatch, and
    sync() here syncs every dirty shard before returning.

    The combined native WAL+payload fast paths are per-directory and do
    not span shards: `_lib` is None so wal_mirror_all and
    append_ranges_uniform fall back to the (shard-routed) classic
    calls.
    """

    def __init__(self, dirname: str, num_shards: int,
                 groups_per_shard: int,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.dirname = dirname
        self.num_shards = num_shards
        self._gl = groups_per_shard
        self.shards = [WAL(d, segment_bytes=segment_bytes)
                       for d in self.shard_dirs(dirname, num_shards)]
        self._lib = None        # no cross-shard combined native calls

    @staticmethod
    def shard_dirs(dirname: str, num_shards: int) -> List[str]:
        return [os.path.join(dirname, f"s{j}") for j in range(num_shards)]

    @classmethod
    def exists(cls, dirname: str, num_shards: int) -> bool:
        return any(wal_exists(d)
                   for d in cls.shard_dirs(dirname, num_shards))

    @classmethod
    def replay(cls, dirname: str, num_shards: int,
               groups_per_shard: int):
        """Merged per-group replay across every shard dir.  Groups are
        disjoint across shards by construction; a group found in the
        wrong shard means the directory was written under a different
        group-shard count — re-sharding an existing data dir is
        unsupported (fail loudly, never silently mis-route appends)."""
        merged = {}
        for j, d in enumerate(cls.shard_dirs(dirname, num_shards)):
            if not wal_exists(d):
                continue
            for g, gl in WAL.replay(d).items():
                if g // groups_per_shard != j:
                    raise ValueError(
                        f"{dirname}: group {g} replayed from shard {j} "
                        f"but belongs to shard {g // groups_per_shard} "
                        "— this WAL was written under a different "
                        "group-shard count (re-sharding an existing "
                        "data dir is unsupported)")
                merged[g] = gl
        return merged

    @classmethod
    def repair_epochs(cls, dirname: str, committed: int,
                      num_shards: int) -> None:
        for d in cls.shard_dirs(dirname, num_shards):
            if wal_exists(d):
                WAL.repair_epochs(d, committed)

    # -- observability fan-out -----------------------------------------

    @property
    def obs(self):
        return self.shards[0].obs

    @obs.setter
    def obs(self, tracer) -> None:
        for s in self.shards:
            s.obs = tracer

    # -- routed write surface ------------------------------------------

    def _shard(self, group: int) -> WAL:
        return self.shards[group // self._gl]

    def append_ranges(self, groups, starts, counts, terms,
                      datas) -> None:
        by: Dict[int, Tuple[list, list, list, list, list]] = {}
        pos = 0
        for g, st, c, tm in zip(groups, starts, counts, terms):
            g = int(g)
            b = by.setdefault(g // self._gl, ([], [], [], [], []))
            b[0].append(g)
            b[1].append(st)
            b[2].append(c)
            b[3].append(tm)
            b[4].extend(datas[pos:pos + c])
            pos += c
        for j, b in by.items():
            self.shards[j].append_ranges(*b)

    def append_ranges_uniform(self, plog, groups, starts, counts, terms,
                              blob, lens) -> bool:
        # The combined WAL+payload native call is per-directory; the
        # caller falls back to append_ranges + plog.put_ranges.
        return False

    def set_hardstates(self, groups, terms, votes, commits) -> None:
        ga = np.asarray(groups)
        sh = ga // self._gl
        ta, va, ca = (np.asarray(terms), np.asarray(votes),
                      np.asarray(commits))
        for j in np.unique(sh):
            m = sh == j
            self.shards[int(j)].set_hardstates(ga[m], ta[m], va[m],
                                               ca[m])

    def set_conf(self, group: int, index: int, kind: int, voters: int,
                 joint: int, learners: int) -> None:
        self._shard(group).set_conf(group, index, kind, voters, joint,
                                    learners)

    def epoch_mark(self, no: int, end: bool) -> None:
        # Dispatch framing lands in every shard that the dispatch may
        # touch.  (The mesh runtime pins steps-per-dispatch to 1, so
        # this is never reached in practice — kept for API parity.)
        for s in self.shards:
            s.epoch_mark(no, end)

    def sync(self) -> None:
        # Serial over shards: WAL.sync returns immediately when a shard
        # has nothing pending, and the host plane already overlaps this
        # call across peers (its per-peer sync pool), so the barrier
        # costs ~max(dirty shard fsyncs) across peers.
        for s in self.shards:
            s.sync()

    def compact(self, floors, hard) -> int:
        deleted = 0
        for j, s in enumerate(self.shards):
            fj = {g: v for g, v in floors.items() if g // self._gl == j}
            if not fj:
                continue
            hj = {g: v for g, v in hard.items() if g // self._gl == j}
            deleted += s.compact(fj, hj)
        return deleted

    def close(self) -> None:
        for s in self.shards:
            s.close()


class MeshClusterNode(ClusterHostPlane):
    """The durable runtime SPMD over a multi-chip mesh.

    Same host plane as FusedClusterNode (runtime/hostplane.py) — WALs,
    payload mirroring, fsync-before-next-dispatch, publish — with three
    mesh-specific choices (see module docstring): the shard_map'd
    device step with per-peer `timer_inc` sharded alongside, per-peer
    WALs split per group shard (ShardedWAL), and one publish worker per
    group shard.
    """

    # The per-shard WAL layout supersedes the single-file group-commit
    # layout (each shard dir is its own append+fsync stream).
    supports_group_commit = False

    def __init__(self, cfg: RaftConfig, data_dir: str, mesh,
                 seed: Optional[int] = None):
        gg = mesh.shape[GROUPS_AXIS]
        pp = mesh.shape[PEERS_AXIS]
        MeshConfig(peer_shards=pp, group_shards=gg).validate(cfg)
        self.mesh = mesh
        self._gg = gg
        self._g_loc = cfg.num_groups // gg
        self._check_mesh_meta(data_dir, gg)
        super().__init__(cfg, data_dir, seed)
        # The sharded step dispatches exactly one consensus step: pin
        # steps-per-dispatch so a RAFTSQL_FUSED_STEPS env meant for the
        # single-chip runtime cannot silently misreport the mesh's
        # dispatch granularity.
        self._steps = 1
        self._sharded_step = make_sharded_cluster_step_host(cfg, mesh)
        self._ti_spec = NamedSharding(mesh, timer_spec())
        self._ti_ones = jax.device_put(
            jnp.ones((cfg.num_peers,), jnp.int32), self._ti_spec)
        # Lay the freshly built (or replayed) cluster state out over the
        # mesh; subsequent steps keep the sharding (donated in/out).
        self.states, self.inboxes = shard_cluster_arrays(
            mesh, self.states, self.inboxes)

    @staticmethod
    def _check_mesh_meta(data_dir: str, gg: int) -> None:
        """Refuse to open a data dir written under a different
        group-shard count: the per-shard WAL layout routes each group's
        records by the CURRENT shard count, so re-sharding in place
        would scatter one group's history across directories."""
        os.makedirs(data_dir, exist_ok=True)
        path = os.path.join(data_dir, MESH_META)
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                meta = json.load(f)
            if meta.get("group_shards") != gg:
                raise ValueError(
                    f"{data_dir}: written with group_shards="
                    f"{meta.get('group_shards')}, opened with {gg} — "
                    "re-sharding an existing data dir is unsupported; "
                    "use a fresh dir (or the original shard count)")
        else:
            with open(path, "w", encoding="utf-8") as f:
                json.dump({"group_shards": gg}, f)

    def enable_membership(self, initial_voters=None) -> None:
        # The sharded step closure captured the construction-time cfg;
        # rebuild it after the host plane leaves the static-full-voter
        # fast path (config.py dynamic_membership) so the mesh program
        # reads the masks membership will patch.
        super().enable_membership(initial_voters)
        self._sharded_step = make_sharded_cluster_step_host(self.cfg,
                                                            self.mesh)

    def _group_shard_of(self, group: int) -> int:
        """Which mesh group shard owns `group` — the `shard` column of
        the /metrics hot-groups table, so the placement story (ROADMAP:
        traffic-aware leadership migration) can see which device shard
        a hot group's load lands on before deciding to move it."""
        return group // self._g_loc

    # -- host-plane seams (runtime/hostplane.py) ------------------------

    def _new_wal(self, dirname: str) -> ShardedWAL:
        return ShardedWAL(dirname, self._gg, self._g_loc,
                          segment_bytes=self.cfg.wal_segment_bytes)

    def _wal_exists(self, dirname: str) -> bool:
        return ShardedWAL.exists(dirname, self._gg)

    def _wal_replay(self, dirname: str):
        return ShardedWAL.replay(dirname, self._gg, self._g_loc)

    def _wal_repair_epochs(self, dirname: str, committed: int) -> None:
        ShardedWAL.repair_epochs(dirname, committed, self._gg)

    def _pub_shard_groups(self) -> List[np.ndarray]:
        # One ordered publish worker per group shard, each owning the
        # shard's contiguous group block (disjoint by construction, so
        # per-group commit order is each worker's FIFO).
        return [np.arange(j * self._g_loc, (j + 1) * self._g_loc)
                for j in range(self._gg)]

    # -- the device step ------------------------------------------------

    def _device_step(self, prop_n: np.ndarray,
                     timer_inc: Optional[np.ndarray] = None):
        """One SPMD tick over the mesh.  `timer_inc` is the per-peer
        [P] timer advance (chaos skew schedules; None = lockstep) —
        sharded over the `peers` axis so each device block advances
        exactly its own peers' clocks, bit-identically to the fused
        runtime's cluster_step."""
        if timer_inc is None:
            ti = self._ti_ones
        else:
            ti = jax.device_put(
                jnp.asarray(np.asarray(timer_inc, np.int32)),
                self._ti_spec)
        self.states, self.inboxes, pinfo_dev, busy = self._sharded_step(
            self.states, self.inboxes, jnp.asarray(prop_n), ti)
        return pinfo_dev, busy
