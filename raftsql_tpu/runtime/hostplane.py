"""ClusterHostPlane — the durable host phase shared by every
single-controller runtime.

runtime/fused.py (one chip) and runtime/mesh.py (a device mesh) run the
same per-tick contract (reference raft.go:227-235: wal.Save →
transport.Send → publish, with the dispatch itself as the send barrier):

  messages composed at tick t are OBSERVED by their receivers only
  inside step t+1 — and the host does not dispatch step t+1 until every
  peer's tick-t appends and hard states are fsynced.

This module is the host half of that contract, factored out of the
original ~1400-line runtime/fused.py so both runtimes share ONE codepath
for propose queues and leader routing, WAL + payload-log writes, the
per-peer fsync barrier, epoch-framed multi-step dispatch, commit
publish, and membership apply-at-commit.  The device half — how one
tick's consensus math is dispatched — is the single abstract method
`_device_step`, implemented by:

  * FusedClusterNode (runtime/fused.py): core/cluster.py
    cluster_step_host / cluster_multistep_host on one device;
  * MeshClusterNode (runtime/mesh.py): the shard_map'd SPMD step
    (parallel/sharded.py) over a `Mesh`, G sharded over a `groups`
    axis and the peer exchange riding all_to_all.

Subclass seams (all default to the single-device layout):

  _new_wal / _wal_exists / _wal_replay / _wal_repair_epochs — how a
    peer's durable log is laid out on disk.  The mesh runtime shards
    each peer's WAL per group shard (runtime/mesh.py ShardedWAL) so the
    durable plane gets one directory — and one fsync stream — per local
    device shard.
  _pub_shard_count / _pub_shard_groups — how many ordered publish
    workers drain commits to the apply plane and which group block each
    owns.  The fused runtime keeps the single FIFO worker; the mesh
    runtime runs one worker per group shard (disjoint groups, so
    per-group commit order is preserved without any cross-worker
    coordination).

Payload plane: entry BYTES never touch the device (the step moves
counts, terms and indexes).  Each peer owns a host PayloadLog + WAL;
a follower that accepts entries mirrors the bytes from the SOURCE
peer's payload log.  Within one host phase all mirror READS happen
before any payload-log WRITES: the reads then see exactly the
end-of-previous-tick state the device composed those appends from, so
a same-tick truncation on the source cannot tear a mirror.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from raftsql_tpu.config import NO_XFER, RaftConfig
from raftsql_tpu.core.cluster import (empty_cluster_inbox,
                                      init_cluster_state)
from raftsql_tpu.core.state import (restore_peer_state,
                                    set_group_config_stacked,
                                    set_transfer_target_stacked)
from raftsql_tpu.core.step import INFO_FIELDS
from raftsql_tpu.transport.codec import (CONF_PREFIX as _CONF_PREFIX,
                                         decode_conf_entry,
                                         is_conf_entry)
from raftsql_tpu.runtime.node import (CLOSED, RAW_MANY, RAW_PLAIN,
                                      TransferRefused)
from raftsql_tpu.native.build import load_native_plog
from raftsql_tpu.storage import fsio
from raftsql_tpu.storage.log import NativePayloadLog, PayloadLog
from raftsql_tpu.obs.prof import TickPhaseProfiler
from raftsql_tpu.storage.wal import (WAL, split_uniform_runs,
                                     wal_exists, wal_mirror_all)
from raftsql_tpu.utils.metrics import GroupTraffic, NodeMetrics

_C = {n: i for i, n in enumerate(INFO_FIELDS)}


def _read_committed_epoch(path: str) -> int:
    """Last valid (u64 no, u32 crc) record of the epoch-commit file; 0
    when missing/empty.  A torn trailing record (crash mid-append)
    falls back to the previous one — the dispatch it would have
    committed is dropped by WAL.repair_epochs, which is exactly the
    uncommitted-dispatch semantics."""
    import struct
    import zlib
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return 0
    no = 0
    for off in range(0, len(blob) - 11, 12):
        n, crc = struct.unpack_from("<QI", blob, off)
        if zlib.crc32(blob[off:off + 8]) == crc:
            no = n
    return no


def _expand_ranges(groups, starts, counts):
    """Per-entry (group, index) columns from per-range lists — the
    fallback form for WAL.append_entries when a combined native call is
    unavailable."""
    ca = np.asarray(counts)
    sa = np.asarray(starts)
    offs = np.cumsum(ca) - ca
    tot = int(ca.sum())
    ga = np.repeat(np.asarray(groups), ca)
    ia = np.arange(tot) - np.repeat(offs, ca) + np.repeat(sa, ca)
    return ga, ia, ca


class ClusterHostPlane:
    """P peers × G groups, one device program per tick, durable WALs.

    Abstract over `_device_step` (see module docstring).  Public
    surface mirrors the distributed runtime where it overlaps:
    `propose_many(group, payloads)` routes to the current leader peer,
    `tick()` advances the whole cluster one step, `commit_q(peer)` is
    that peer's totally-ordered commit stream (same item protocol as
    RaftNode: any replayed (RAW_PLAIN, g, base, [bytes...]) batches
    first, then the None replay-complete sentinel, then live ticks as
    (RAW_MANY, [(g, base, [bytes...]), ...]) batch-of-batches items;
    CLOSED ends the stream), `leader_of(group)` reports the last hint.
    """

    # Epoch-commit file rotation threshold (12 bytes/dispatch; only the
    # last record matters — see _commit_epoch).
    _EPOCH_ROTATE_BYTES = 1 << 20

    # WAL group commit (storage/wal.py GroupCommitWAL) is a per-data-dir
    # layout choice; the mesh runtime's ShardedWAL seams supersede it.
    supports_group_commit = True

    # Which mesh shard owns a group (the hot-groups table's `shard`
    # column); None on unsharded runtimes, a method on MeshClusterNode.
    _group_shard_of = None

    def __init__(self, cfg: RaftConfig, data_dir: str,
                 seed: Optional[int] = None,
                 group_commit: Optional[bool] = None):
        P, G = cfg.num_peers, cfg.num_groups
        self.cfg = cfg
        self.metrics = NodeMetrics()
        # Telemetry plane (raftsql_tpu/obs/prof.py), DEFAULT ON — both
        # are pure observers (pre-allocated buffers, no allocation on
        # the hot path, never any control-flow influence: chaos digests
        # are pinned identical with RAFTSQL_PROF on and off).
        #   prof: per-phase tick wall-time rings -> /metrics
        #     phase_profile + Perfetto phase tracks in /trace
        #     (RAFTSQL_PROF=0 off, RAFTSQL_PROF_SAMPLE=N 1-in-N ticks);
        #   traffic: [G] propose/commit/ack counters + EWMA rates ->
        #     /metrics group_traffic top-K hot-groups table.
        self.prof = TickPhaseProfiler.from_env(G)
        self.traffic = GroupTraffic(G)
        # Overlap-aware phase attribution: the tick that OWNS the
        # durable/publish work currently running (a stashed durable
        # phase retiring inside tick t+1's dispatch window is tick
        # t's).  _pending_tick tags the deferred-publish pinfo.
        self._prof_tick = 0
        self._pending_tick = 0
        self._fsync_dur = np.zeros(P, np.float64)   # parallel-path syncs
        self._fsync_span: Optional[tuple] = None    # (t0, dur) last tick
        self.dirs = [os.path.join(data_dir, f"p{i + 1}") for i in range(P)]
        # WAL group commit: multiplex all P peers' records into ONE
        # physical log (flat group id peer*G+g) so the durable barrier
        # is one write+fsync per tick instead of P fsyncs in flight.
        # None = env RAFTSQL_WAL_GROUP_COMMIT (the serving deployment
        # and the durable bench turn it on); an existing per-peer
        # layout wins over the flag — never mix layouts in one dir.
        if group_commit is None:
            group_commit = os.environ.get(
                "RAFTSQL_WAL_GROUP_COMMIT") == "1"
        self._gc_dir = os.path.join(data_dir, "gc")
        self._gcwal = None
        self._gc_mode = False
        self._gc_replay: Optional[dict] = None
        self._gc_repaired = False
        if group_commit and self.supports_group_commit:
            from raftsql_tpu.storage.wal import GroupCommitWAL
            legacy = any(wal_exists(d) for d in self.dirs)
            if legacy and not GroupCommitWAL.exists(self._gc_dir):
                import logging
                logging.getLogger("raftsql.hostplane").warning(
                    "%s: per-peer WAL layout exists; group commit "
                    "disabled for this data dir", data_dir)
            else:
                self._gc_mode = True
        self.wals: List[WAL] = []
        self.plogs: List[PayloadLog] = []
        self._commit_qs: List["queue.Queue"] = [queue.Queue()
                                                for _ in range(P)]
        self._applied = np.zeros((P, G), np.int64)
        self._hard = np.zeros((P, G, 3), np.int64)
        self._hard[:, :, 1] = -1
        # Per-(peer, group) proposal queues as plain lists: the tick
        # pops a whole batch with one C-level slice + del, vs a Python
        # popleft per entry on a deque.  _prop_lock covers _props and
        # _queued: under the threaded --fused deployment (start()),
        # HTTP client threads propose concurrently with the tick
        # thread's routing and batch pops.
        # raftlint: guarded-by=_prop_lock
        self._props: List[List[list]] = [
            [[] for _ in range(G)] for _ in range(P)]
        self._queued: set = set()  # raftlint: guarded-by=_prop_lock
        self._prop_lock = threading.Lock()
        self._hints = np.full(G, -1, np.int64)
        self._tick_no = 0
        # Leader-lease host cache (config.lease_ticks): the device
        # lease phase (core/step.py Phase 8b) returns each peer row's
        # [G] lease-expiry vector in device-STEP units; `_lease_col`
        # is the last dispatch's [P, G] slice and `_device_steps` the
        # host's running step count (ticks x steps-per-dispatch), the
        # "now" the validity check compares against.  Sound here
        # because the fused/mesh plane steps every peer once per host
        # step — per-peer skew only scales timer_inc, which is exactly
        # the rate bound cfg.max_clock_skew/lease_ticks must cover.
        self._lease_col: Optional[np.ndarray] = None
        self._device_steps = 0
        # Last tick's packed info, published at the START of the next
        # tick (overlapped with the device dispatch) — its entries are
        # already durable by then.
        self._pending_pinfo: Optional[np.ndarray] = None
        # Optional apply-plane work to run INSIDE the dispatch window,
        # right after the overlapped publish: through a remote-device
        # tunnel the dispatch+compute wall time is idle host time, and
        # draining/applying the commit stream there is free.  The hook
        # must only consume the commit queues (anything else races the
        # tick).
        self.overlap_hook = None
        # Which peers' commit queues receive live publishes (None =
        # all).  Deployments that consume a single peer's stream (the
        # --fused server and the durable bench drain peer 0) set {0}
        # and skip 2/3 of the publish slicing + queue traffic.
        self.publish_peers: Optional[set] = None
        # Witness peers (config.py quorum geometry): they vote, append
        # and fsync — full quorum citizens on the durability plane —
        # but own no state machine: their commit streams are never
        # materialized (cursor-advance only in _publish_shard) and
        # placement/transfer refuse them as leadership targets.
        self.witness_peers: frozenset = cfg.witness_set
        # Native KV apply plane (models/kv_native.py): when set AND the
        # payload plane is native, peer 0's committed ranges are applied
        # inside one C call per publish instead of being materialized as
        # Python bytes for a queue consumer.
        self.native_kv = None
        # Overload-control plane (raftsql_tpu/overload/), attachment-
        # gated like tracer/membership: None keeps propose_many and the
        # staging path byte-identical to the pre-overload code (the
        # chaos digest-neutrality pin).  When attached, propose_many
        # charges its budgets under _prop_lock and the staging path
        # sheds expired-deadline entries before any WAL cost.
        self.overload = None
        # True once any deadline-carrying proposal entered the queues:
        # only then does staging pay the per-entry deadline strip
        # (queue entries become (payload, deadline_step) pairs).
        self._deadlines_live = False  # raftlint: guarded-by=_prop_lock
        # Observability (raftsql_tpu/obs/, OFF by default): a host-plane
        # span tracer and the on-device event ring.  Every hook below is
        # gated on these being non-None, so the disabled tick pays one
        # attribute test and the step signatures are untouched.
        self.tracer = None
        self.ring = None
        # Dynamic membership (raftsql_tpu/membership/), opt-in via
        # enable_membership(): None keeps the static tick byte-identical
        # (every hook gates on one attribute test).
        self.membership = None
        # Leadership-transfer plane (thesis §3.10, PR 11): one latch
        # per group.  Client threads VALIDATE and enqueue into
        # _xfer_req; the tick thread arms the device latch (self.states
        # is donated every dispatch) and drives completion/abort in
        # _transfer_advance.  _xfer_events is the recent-outcome log
        # flight bundles attach for attribution.
        from collections import deque as _deque
        self._xfer_lock = threading.Lock()
        self._xfer_req: List[Tuple[int, int, int]] = []  # raftlint: guarded-by=_xfer_lock
        self._xfers: Dict[int, dict] = {}  # raftlint: guarded-by=_xfer_lock
        self._xfer_events = _deque(maxlen=256)
        self._conf_pending: List[list] = []      # per group [(idx, data)]
        self._conf_scrub: List[set] = []         # per group conf indexes
        self._conf_cursor: Optional[np.ndarray] = None   # [P, G]
        self._replayed_conf: List[Dict[int, tuple]] = [
            {} for _ in range(P)]
        self.error: Optional[Exception] = None
        self._work_evt = threading.Event()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick_active = True
        self._spin_hot = True
        # One worker per peer for the end-of-tick durable barrier: the
        # P per-peer fsyncs overlap (independent files; fsync releases
        # the GIL), so the barrier costs max not sum of the fsyncs.
        from concurrent.futures import ThreadPoolExecutor
        self._sync_pool = ThreadPoolExecutor(
            max_workers=P, thread_name_prefix="wal-sync")
        # Host-plane parallelism (per-peer mirror/hardstate/fsync
        # workers + the async publishers): only pays when the host has
        # cores to run them on — on a 1-core host the same threads just
        # time-slice the tick thread's core and the serial path wins
        # (measured: 652k vs 601k commits/s at G=1000/E=64).
        # RAFTSQL_FUSED_PARALLEL=1/0 overrides the autodetect.
        par_env = os.environ.get("RAFTSQL_FUSED_PARALLEL", "")
        self._host_parallel = (par_env == "1"
                               or (par_env != "0"
                                   and (os.cpu_count() or 1) >= 4))
        # Serial hosts deliver a LIGHT tick's commits inline at tick end
        # (≤ this many entries) instead of deferring a whole tick for
        # dispatch overlap — ~0.4us/entry of publish against a full
        # tick of ack latency.  Saturated ticks keep the deferral.
        self._inline_publish_max = int(os.environ.get(
            "RAFTSQL_PUBLISH_INLINE_MAX", "4096"))
        # Steps per dispatch (RAFTSQL_FUSED_STEPS, default 1): run S
        # consensus steps inside one device program and replay the
        # durable phases per step on return (core/cluster.py
        # cluster_multistep_host).  Amortizes dispatch overhead — the
        # dominant per-tick cost through a remote-device tunnel — and
        # lets a proposal commit within ONE dispatch (the 3-step
        # pipeline completes before the durable barrier).  Election /
        # heartbeat timers advance once per STEP, so election_ticks
        # continue to mean steps, not dispatches.
        self._steps = max(1, int(os.environ.get(
            "RAFTSQL_FUSED_STEPS", "1")))
        # Publish workers (parallel hosts): delivering a tick's
        # (already durable) commits to the apply plane costs ~40% of a
        # saturated tick's wall time; ordered workers take it off the
        # tick thread entirely.  The fused runtime runs ONE worker; the
        # mesh runtime runs one per group shard, each owning a disjoint
        # group block (per-group commit order needs no cross-worker
        # coordination).  maxsize=2 bounds the lag to one tick —
        # enqueueing tick t's publish blocks until tick t-1's delivery
        # started, so memory and commit-ack latency stay bounded.
        import queue as _queue
        self._metrics_mu = threading.Lock()
        self._shard_groups = self._pub_shard_groups()
        self._pub_qs: List["_queue.Queue"] = [
            _queue.Queue(maxsize=2) for _ in range(len(self._shard_groups))]
        self._pub_threads: List[threading.Thread] = []
        for j, q in enumerate(self._pub_qs):
            th = threading.Thread(
                target=self._pub_run, args=(q, j), daemon=True,
                name=f"publish-{j}")
            th.start()
            self._pub_threads.append(th)
        # Per-peer timer skew seam: None = lockstep (every peer's timers
        # advance 1 per step).  A [P] i32 array makes peers drift — the
        # chaos harness's clock-skew schedules set it, modeling the real
        # world where deployments never tick in lockstep.  Applied on
        # the next tick(); plumbed through the runtime's per-peer
        # timer_inc (core/cluster.py, parallel/sharded.py).
        self.timer_inc: Optional[np.ndarray] = None
        # Native payload plane (native/wal.cc): combined WAL+payload-log
        # C calls, OPT-IN via RAFTSQL_FUSED_NATIVE_PLOG=1.  Measured on
        # the Python-consumer stack it LOSES to the columnar Python
        # payload log (104k vs 239k commits/s at G=1000/E=32): the C
        # store must materialize fresh bytes objects on every publish,
        # while the Python store hands the consumer the very objects it
        # already holds.  It wins only once the apply plane itself is
        # C++-resident (reads bytes in place) — kept for that path, and
        # every call site degrades per-call to the Python forms.
        self._plog_lib = (load_native_plog()
                          if os.environ.get("RAFTSQL_FUSED_NATIVE_PLOG")
                          == "1" else None)

        # Double-buffered dispatch (RAFTSQL_OVERLAP_DISPATCH, default
        # on): tick t's heavy durable phase (WAL writes + the fsync
        # barrier) is STASHED at the end of tick t and retired inside
        # tick t+1's device-dispatch window — the disk and the device
        # work at the same time instead of in series.  Correctness gate
        # (the module-doc contract, re-proved for the pipeline):
        # durable phase t+1 begins only after durable phase t fully
        # completed, and publish/acks for tick t follow its own
        # barrier — so when any effect of a message is durable or
        # externalized, its cause is durable.  The speculative dispatch
        # t+1 (which observes tick t's not-yet-fsynced messages) lives
        # only in volatile device memory until then; a crash loses the
        # stash and dispatch together, and replay resumes from the last
        # completed barrier (multi-step dispatches keep their epoch
        # framing — an uncommitted epoch is erased on every peer).
        # Proposal POPS for the stashed tick happen at stage time, so
        # the next _build_prop_n snapshot (and its re-routes) see
        # exactly the queue state the serialized pipeline would — the
        # chaos digest must not move under overlap.
        self._overlap = os.environ.get(
            "RAFTSQL_OVERLAP_DISPATCH", "1") == "1"
        self._stash: Optional[tuple] = None    # (step_infos, staged)

        # Multi-step dispatch epoch state (see tick()): the committed
        # epoch lives in data_dir/EPOCHS (12-byte records, fsynced once
        # per multi-step dispatch AFTER every peer's WAL barrier — the
        # cluster-atomic commit point).  Before any replay, drop every
        # peer's trailing UNCOMMITTED dispatch: within a dispatch peers
        # observe each other's un-fsynced messages, and the per-peer
        # barrier is not atomic, so a crash mid-barrier must erase the
        # whole dispatch everywhere or a vote/append observed by one
        # peer could survive while its sender's record did not (two
        # leaders in one term after replay).
        self._epoch_path = os.path.join(data_dir, "EPOCHS")
        self._epoch_no = _read_committed_epoch(self._epoch_path)
        self._epoch_f = None
        self._ep_active = False
        self._ep_begun = [False] * P
        self._ep_no_this: Optional[int] = None
        # Repair runs whenever any peer WAL exists — even when EPOCHS is
        # missing (committed epoch 0): EPOCHS is created lazily by the
        # FIRST _commit_epoch, so a crash mid-barrier during the
        # first-ever multi-step dispatch leaves epoch-1 BEGIN-framed
        # records durable on some peers with no EPOCHS file at all, and
        # skipping repair would replay exactly the non-atomic dispatch
        # (e.g. a durable vote grant whose sender's term bump was lost)
        # this mechanism exists to drop.
        for d in self.dirs:
            if self._wal_exists(d):
                self._wal_repair_epochs(d, self._epoch_no)

        states = []
        for p in range(P):
            d = self.dirs[p]
            if self._wal_exists(d):
                states.append(self._replay_peer(p, d, seed))
            else:
                os.makedirs(d, exist_ok=True)
                self.wals.append(self._new_wal(d))
                self.plogs.append(
                    NativePayloadLog(G, self._plog_lib)
                    if self._plog_lib is not None else PayloadLog(G))
                states.append(None)
            # Replay-complete sentinel, replayed-or-not (the reference's
            # nil on commitC, raft.go:131-132).
            self._commit_qs[p].put(None)
        if all(s is None for s in states):
            self.states = init_cluster_state(cfg, seed)
        else:
            per_peer = [s if s is not None
                        else restore_peer_state(cfg, p, {}, {}, seed)
                        for p, s in enumerate(states)]
            self.states = jax.tree.map(lambda *xs: jnp.stack(xs),
                                       *per_peer)
        self.inboxes = empty_cluster_inbox(cfg)
        self._E = cfg.max_entries_per_msg
        self._gc_replay = None          # free the boot replay cache

    # -- subclass seams -------------------------------------------------

    def _device_step(self, prop_n: np.ndarray,
                     timer_inc: Optional[np.ndarray] = None):
        """Dispatch one cluster step; returns (packed-info device array,
        device busy bit or None).  `timer_inc` is the per-peer [P]
        timer advance (None = lockstep 1s, the steady-state fast path).
        Implemented by the concrete runtime — the durable host plane in
        this class is identical either way."""
        raise NotImplementedError

    def _new_wal(self, dirname: str) -> WAL:
        """Construct a peer's durable log handle.  The mesh runtime
        overrides this with a per-group-shard layout (ShardedWAL); the
        group-commit mode hands out per-peer views of ONE shared log."""
        if self._gc_mode:
            if self._gcwal is None:
                from raftsql_tpu.storage.wal import GroupCommitWAL
                self._gcwal = GroupCommitWAL(
                    self._gc_dir, self.cfg.num_peers,
                    self.cfg.num_groups,
                    segment_bytes=self.cfg.wal_segment_bytes)
            return self._gcwal.view(self.dirs.index(dirname))
        return WAL(dirname, segment_bytes=self.cfg.wal_segment_bytes)

    def _wal_exists(self, dirname: str) -> bool:
        if self._gc_mode:
            from raftsql_tpu.storage.wal import GroupCommitWAL
            return GroupCommitWAL.exists(self._gc_dir)
        return wal_exists(dirname)

    def _wal_replay(self, dirname: str):
        if self._gc_mode:
            from raftsql_tpu.storage.wal import GroupCommitWAL
            if self._gc_replay is None:
                self._gc_replay = GroupCommitWAL.replay_flat(self._gc_dir)
            return GroupCommitWAL.split_replay(
                self._gc_replay, self.dirs.index(dirname),
                self.cfg.num_groups)
        return WAL.replay(dirname)

    def _wal_repair_epochs(self, dirname: str, committed: int) -> None:
        if self._gc_mode:
            if not self._gc_repaired:
                self._gc_repaired = True
                from raftsql_tpu.storage.wal import GroupCommitWAL
                GroupCommitWAL.repair_epochs(self._gc_dir, committed)
            return
        WAL.repair_epochs(dirname, committed)

    def _pub_shard_groups(self) -> List[Optional[np.ndarray]]:
        """One entry per ordered publish worker: the group-id block it
        owns (None = all groups).  Workers' blocks MUST be disjoint —
        each group's commit stream is then FIFO through exactly one
        worker, which is what preserves per-group publish order."""
        return [None]

    def _note_commits(self, n: int) -> None:
        """Commit-counter increment, safe from concurrent publish
        workers (disjoint groups, shared counter)."""
        with self._metrics_mu:
            self.metrics.commits += n

    # -- boot -----------------------------------------------------------

    def _replay_peer(self, p: int, d: str, seed):
        """Rebuild peer p from its WAL (RestartNode, raft.go:122-134):
        device state, payload log, and the replayed committed prefix
        published to its commit stream."""
        logs = self._wal_replay(d)
        self._replayed_conf[p] = {g: gl.conf for g, gl in logs.items()
                                  if gl.conf is not None}
        self.wals.append(self._new_wal(d))
        plog = (NativePayloadLog(self.cfg.num_groups, self._plog_lib)
                if self._plog_lib is not None
                else PayloadLog(self.cfg.num_groups))
        self.plogs.append(plog)
        log_terms: Dict[int, list] = {}
        hard: Dict[int, tuple] = {}
        starts: Dict[int, tuple] = {}
        g_peer_publishes = p not in self.cfg.witness_set
        for g, gl in logs.items():
            log_terms[g] = [t for (t, _) in gl.entries]
            hard[g] = (gl.hard.term, gl.hard.vote, gl.hard.commit)
            if gl.start:
                starts[g] = (gl.start, gl.start_term)
                plog.set_start(g, gl.start, gl.start_term)
            plog.put(g, gl.start + 1, [dt for (_, dt) in gl.entries],
                     [t for (t, _) in gl.entries])
            self._hard[p, g] = hard[g]
            commit = gl.hard.commit
            self._applied[p, g] = commit
            datas = plog.try_slice(g, gl.start + 1,
                                   max(commit - gl.start, 0))
            # A witness replays its WAL for votes/terms/log only — it
            # has no apply plane, so nothing is re-published (the live
            # path in _publish_shard advances its cursor the same way).
            if datas and g_peer_publishes:
                self._commit_qs[p].put((RAW_PLAIN, g, gl.start, datas))
        return restore_peer_state(self.cfg, p, log_terms, hard, seed,
                                  starts=starts or None)

    # -- client plane ---------------------------------------------------

    def commit_q(self, peer: int) -> "queue.Queue":
        return self._commit_qs[peer]

    def leader_of(self, group: int) -> int:
        """Last known leader peer (0-based), -1 unknown."""
        return int(self._hints[group])

    def enable_tracing(self, ring_depth: int = 64,
                       keep: int = 4096) -> None:
        """Turn on both observability planes (raftsql_tpu/obs/): the
        host span tracer and the on-device event ring.  Safe to call
        before the tick loop starts; idempotent."""
        from raftsql_tpu.obs.device_ring import DeviceEventRing
        from raftsql_tpu.obs.spans import SpanTracer
        if self.tracer is None:
            self.tracer = SpanTracer()
        if self.ring is None:
            self.ring = DeviceEventRing(self.cfg.num_peers,
                                        self.cfg.num_groups,
                                        depth=ring_depth, keep=keep)
        for w in self.wals:
            w.obs = self.tracer

    # -- dynamic membership (raftsql_tpu/membership/) -------------------

    def enable_membership(self, initial_voters=None) -> None:
        """Attach the membership plane: per-group voter masks as device
        state, conf entries applied per PEER ROW as that row's commit
        passes them, durable REC_CONF baselines per peer WAL.  Restores
        each peer's active config from its replayed WAL (baseline +
        retained conf entries).  Call before the tick loop; idempotent."""
        from raftsql_tpu.membership import MembershipManager
        if self.membership is not None:
            return
        # Leave the static-full-voter fast path (config.py
        # dynamic_membership): the device program must start reading the
        # per-group masks BEFORE any of them can change.  One recompile.
        import dataclasses as _dc
        if self.cfg.static_full_voters:
            self.cfg = _dc.replace(self.cfg, dynamic_membership=True)
        P, G = self.cfg.num_peers, self.cfg.num_groups
        iv = initial_voters if initial_voters is not None \
            else self.cfg.initial_voters
        geo = dict(write_quorum=self.cfg.write_quorum,
                   election_quorum=self.cfg.election_quorum,
                   witnesses=self.cfg.witnesses or (),
                   unsafe_geometry=self.cfg.unsafe_quorum_geometry)
        mm = MembershipManager(P, G, initial_voters=iv, **geo)
        self._conf_pending = [[] for _ in range(G)]
        self._conf_scrub = [set() for _ in range(G)]
        self._conf_cursor = np.zeros((P, G), np.int64)
        pend: List[Dict[int, bytes]] = [dict() for _ in range(G)]
        for p in range(P):
            view = MembershipManager(P, G, initial_voters=iv, **geo)
            for g in range(G):
                base = self._replayed_conf[p].get(g)
                plog = self.plogs[p]
                start, ln = plog.start(g), plog.length(g)
                datas = plog.try_slice(g, start + 1, ln - start) \
                    if ln > start else []
                entries = [(0, d) for d in (datas or [])]
                if view.restore(g, base, entries, start,
                                int(self._hard[p, g, 2])):
                    c = view.config(g)
                    self._patch_conf_row(p, g, c.entry(0))
                    self._conf_cursor[p, g] = c.index
                    # The cluster authority adopts the most advanced
                    # per-group view (full-picture entries make this a
                    # plain superseding apply).
                    mm.apply(g, c.index, c.entry(0))
                for idx, d in view.appended_list(g):
                    pend[g].setdefault(idx, d)
        self.membership = mm
        for g in range(G):
            for idx in sorted(pend[g]):
                self._conf_note(g, idx, pend[g][idx])

    def _conf_note(self, g: int, idx: int, data: bytes) -> None:
        """A conf entry entered some peer's log at `idx` (tick thread)."""
        lst = self._conf_pending[g]
        lst[:] = [(i, d) for (i, d) in lst if i != idx]
        lst.append((idx, data))
        lst.sort()
        # New set object (not in-place add): the publisher thread scrubs
        # from whatever reference it grabbed — no concurrent mutation.
        self._conf_scrub[g] = self._conf_scrub[g] | {idx}

    def _patch_conf_row(self, p: int, g: int, data: bytes) -> None:
        got = decode_conf_entry(data)
        if got is None:
            return
        _, v, j, _l = got
        P = self.cfg.num_peers
        vrow = np.array([bool(v >> i & 1) for i in range(P)])
        jrow = np.array([bool(j >> i & 1) for i in range(P)])
        self.states = set_group_config_stacked(
            self.states, p, g, vrow, jrow, bool((v | j) >> p & 1))

    def _membership_advance(self, pinfo: np.ndarray) -> None:
        """Apply pending conf entries to each peer row whose commit
        passed them, drive the auto LEAVE_JOINT, and keep the cluster
        authority in sync.  Tick thread, after the durable phases."""
        mm = self.membership
        P = self.cfg.num_peers
        commit = pinfo[:, :, _C["commit"]]
        for g, lst in enumerate(self._conf_pending):
            if not lst:
                continue
            drop: List[int] = []
            for (idx, data) in list(lst):
                all_done = True
                superseded = False
                for p in range(P):
                    if self._conf_cursor[p, g] >= idx:
                        continue
                    if commit[p, g] < idx:
                        all_done = False
                        continue
                    got = self.plogs[p].try_slice(g, idx, 1)
                    if got is None:
                        continue          # compacted under us: settled
                    if got[0] != data:
                        # Conflict truncation rewrote the slot before
                        # commit: this conf never happened.
                        superseded = True
                        break
                    self._patch_conf_row(p, g, data)
                    self._conf_cursor[p, g] = idx
                    # Per-peer durable baseline: THIS entry's masks (the
                    # cluster authority may already be ahead).
                    _k, cv, cj, cl = decode_conf_entry(data)
                    self.wals[p].set_conf(g, idx, _k, cv, cj, cl)
                    if mm.apply(g, idx, data) is not None:
                        self.metrics.conf_changes_applied += 1
                if superseded:
                    mm.abort_pending(g)      # the change never happened
                if superseded or all_done:
                    drop.append(idx)
            if drop:
                lst[:] = [(i, d) for (i, d) in lst if i not in drop]
        # Whichever peer leads a joint group finishes the transition.
        for g in list(mm.joint_groups):
            if self._hints[g] >= 0:
                entry = mm.maybe_leave(g, self._tick_no,
                                       4 * self.cfg.election_ticks)
                if entry is not None:
                    self.propose_many(g, [entry])

    def members_doc(self) -> dict:
        if self.membership is None:
            return {"error": "membership plane not enabled "
                             "(enable_membership())"}
        out = {}
        for g in range(self.cfg.num_groups):
            d = self.membership.describe(g)
            d["leader"] = self.leader_of(g) + 1
            out[str(g)] = d
        return {"num_peers": self.cfg.num_peers, "groups": out,
                "witnesses": sorted(self.witness_peers), "node": 0}

    def member_change(self, group: int, op: str, peer: int) -> dict:
        """Admin plane for the co-located cluster: every peer lives in
        this process, so routing goes through propose_many's leader
        hint instead of a wire forward."""
        from raftsql_tpu.membership import MembershipLagError
        if self.membership is None:
            raise RuntimeError("membership plane not enabled "
                               "(enable_membership())")
        if op == "promote":
            lead = int(self._hints[group])
            commit = int(self._hard[max(lead, 0), group, 2])
            have = self.plogs[peer].length(group)
            if commit - have > self.cfg.max_entries_per_msg:
                raise MembershipLagError(
                    f"group {group}: learner {peer} is "
                    f"{commit - have} entries behind; retry after "
                    "catch-up")
        entry = self.membership.make_change(group, op, peer)
        self.propose_many(group, [entry])
        return self.membership.describe(group)

    # -- leadership transfer (raft thesis §3.10, PR 11) -----------------

    def transfer_leadership(self, group: int, target: int,
                            deadline_ticks: Optional[int] = None) -> dict:
        """Arm a graceful leadership transfer of `group` to peer slot
        `target` (0-based).  The device latch stops proposal intake for
        the group, waits for the target's match_index to catch up, then
        fires the TimeoutNow grant (core/step.py Phase 9); queued
        proposals re-route to the new leader automatically once the
        hint moves.  One in flight per group; past `deadline_ticks` of
        device steps (default 4 election timeouts) the host clears the
        latch and the group resumes serving under the old leader.
        Client-thread safe — the tick thread patches device state."""
        cfg = self.cfg
        if not 0 <= group < cfg.num_groups:
            raise ValueError(f"group {group} out of range")
        if not 0 <= target < cfg.num_peers:
            raise ValueError(f"target {target} out of peer-slot range")
        lead = int(self._hints[group])
        if lead < 0:
            self.metrics.transfers_refused += 1
            raise TransferRefused(group, "group has no leader yet")
        if target == lead:
            self.metrics.transfers_refused += 1
            raise TransferRefused(group, "target already leads")
        if self.membership is not None \
                and not self.membership.is_voter(group, target):
            self.metrics.transfers_refused += 1
            raise TransferRefused(
                group, f"peer {target} is a learner/non-voter")
        if target in self.witness_peers:
            # A witness never campaigns or applies (core/step.py Phase
            # 8 gate): arming the latch would stall the group until the
            # transfer deadline aborts it.
            self.metrics.transfers_refused += 1
            raise TransferRefused(group, f"peer {target} is a witness")
        dl = int(deadline_ticks) if deadline_ticks \
            else 4 * cfg.election_ticks
        with self._xfer_lock:
            if group in self._xfers:
                self.metrics.transfers_refused += 1
                raise TransferRefused(group, "transfer already in flight")
            self._xfers[group] = {"target": target, "from": lead,
                                  "start_tick": self._tick_no,
                                  "deadline_ticks": dl, "deadline": None,
                                  "armed": False}
            self._xfer_req.append((lead, group, target))
        self.metrics.transfers_initiated += 1
        self._work_evt.set()          # wake a parked tick loop
        return {"group": group, "from": lead + 1, "target": target + 1,
                "deadline_ticks": dl}

    def _transfer_arm(self) -> None:
        """Apply queued transfer requests to device state (tick thread,
        before the dispatch so this tick's step sees the latch)."""
        with self._xfer_lock:
            reqs, self._xfer_req = self._xfer_req, []
            for (p, g, tgt) in reqs:
                self.states = set_transfer_target_stacked(
                    self.states, p, g, tgt)
                tr = self._xfers.get(g)
                if tr is not None:
                    tr["armed"] = True
                    tr["deadline"] = (self._device_steps
                                      + tr["deadline_ticks"])

    def _transfer_advance(self, pinfo: np.ndarray) -> None:
        """Completion/abort driver (tick thread, right after the hint
        refresh).  Completed: the hint names the target.  Aborted: the
        deadline passed, or leadership settled on a third peer — either
        way the latch is cleared so the group keeps serving."""
        xcol = pinfo[:, :, _C["xfer"]]
        now = self._device_steps
        with self._xfer_lock:
            for g, tr in list(self._xfers.items()):
                if not tr["armed"]:
                    continue
                outcome = None
                h = int(self._hints[g])
                frm = tr["from"]
                armed_dev = int(xcol[frm, g]) == tr["target"]
                if h == tr["target"]:
                    outcome = "completed"
                elif now >= tr["deadline"]:
                    if armed_dev:
                        self.states = set_transfer_target_stacked(
                            self.states, frm, g, NO_XFER)
                    outcome = "aborted"
                elif not armed_dev and 0 <= h != frm:
                    outcome = "aborted"    # settled elsewhere
                if outcome is None:
                    continue
                del self._xfers[g]
                stall = self._tick_no - tr["start_tick"]
                if outcome == "completed":
                    self.metrics.transfers_completed += 1
                else:
                    self.metrics.transfers_aborted += 1
                self.metrics.note_transfer_stall(stall)
                self._xfer_events.append(
                    {"group": g, "from": frm + 1,
                     "to": tr["target"] + 1, "outcome": outcome,
                     "stall_ticks": int(stall), "tick": self._tick_no})

    def transferring_groups(self) -> set:
        """Groups with a transfer in flight (hot-groups `transferring`
        flag)."""
        with self._xfer_lock:
            return set(self._xfers)

    def transfers_doc(self) -> dict:
        """In-flight latches + the recent-outcome log (flight bundles,
        placement-controller feedback)."""
        with self._xfer_lock:
            inflight = {str(g): {"target": tr["target"] + 1,
                                 "from": tr["from"] + 1,
                                 "start_tick": tr["start_tick"]}
                        for g, tr in self._xfers.items()}
            recent = list(self._xfer_events)
        return {"in_flight": inflight, "recent": recent}

    def propose_many(self, group: int, payloads,
                     deadline_step: Optional[int] = None) -> None:
        """Queue payloads at the group's current leader peer (host-side
        routing — all peers share this process; the distributed
        runtime's forward-over-transport becomes a list move).

        `deadline_step` (absolute device-step deadline, overload plane
        only) rides each entry as a (payload, deadline) pair; staging
        strips it and sheds entries already past it BEFORE any WAL
        cost.  With no overload controller attached and no deadline,
        this path is byte-identical to the pre-overload code."""
        if self.tracer is not None:
            for d in payloads:
                self.tracer.begin(group,
                                  d.decode("utf-8", "replace"))
        ov = self.overload
        if deadline_step is not None:
            payloads = [(d, int(deadline_step)) for d in payloads]
        p = int(self._hints[group])
        if p < 0:
            p = 0
        with self._prop_lock:
            if ov is not None:
                ov.admit(group, len(payloads))   # raises Overloaded
            if deadline_step is not None:
                self._deadlines_live = True
            self._props[p][group].extend(payloads)
            self._queued.add((p, group))
        self._work_evt.set()

    # -- threaded serving (single-process deployments) ------------------

    def start(self, interval_s: float = 0.002) -> None:
        """Run the tick loop on a background thread: wake immediately
        on proposals; tick at `interval_s` while consensus is active;
        PARK at a 0.5 s safety heartbeat once the cluster is quiet
        (nothing queued, committed-but-unpublished, leaderless, written
        this tick, or busy on-device — see the runtime's busy bit).
        Pausing a quiet cluster is safe precisely because it is
        single-controller: ALL peers pause together, so no peer can
        observe missed heartbeats, no timer skews, and elections fire
        only when a group actually lacks a leader."""
        def _run():
            while not self._stop_evt.is_set():
                self._work_evt.clear()
                try:
                    self.tick()
                except Exception as e:   # pragma: no cover - defensive
                    self.error = e
                    for q in self._commit_qs:
                        q.put(CLOSED)
                    return
                # Idle parking: a QUIET single-controller cluster can
                # pause consensus outright — every peer pauses with it,
                # so no election can fire spuriously and nothing is
                # missed; the next proposal (work event) resumes it.
                # The 0.5 s cap is a safety heartbeat.  While HOT
                # (client work in flight), loop back-to-back: the
                # tick's own wall time is the pacing, and relative
                # timer safety (heartbeat period < election timeout)
                # holds at any wall rate because all peers step
                # together — each saved interval_s is a propose→commit
                # pipeline hop clients don't wait.  ACTIVE-but-not-hot
                # (e.g. leaderless warmup) paces at interval_s.
                if not self._tick_active:
                    self._work_evt.wait(0.5)
                elif not self._spin_hot:
                    self._work_evt.wait(interval_s)

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="cluster-tick")
        self._thread.start()

    # -- linearizable reads (single-controller cluster) -----------------

    def commit_watermark(self, group: int) -> int:
        """Replicated read-index watermark for follower/session reads
        (X-Raft-Session): the hinted leader's commit index — in the
        co-located cluster that IS the global commit point."""
        p = max(int(self._hints[group]), 0)
        return int(self._hard[p, group, 2])

    def lease_read(self, group: int) -> Optional[int]:
        """Serve a linearizable read from the device-computed leader
        lease: the read's target commit index while the hinted
        leader's lease covers `now + max_clock_skew`, else None (the
        caller degrades to read_index — never a silent stale read).
        The §6.4 current-term-commit precondition is folded into the
        device lease value (0 while pending)."""
        cfg = self.cfg
        if cfg.lease_ticks <= 0:
            return None
        lc = self._lease_col
        p = int(self._hints[group])
        if lc is None or p < 0:
            return None
        until = int(lc[p, group])
        if until > 0 \
                and self._device_steps + cfg.max_clock_skew < until:
            self.metrics.lease_grants += 1
            return int(self._hard[p, group, 2])
        if until > 0:
            self.metrics.lease_expiries += 1
        return None

    def read_index(self, group: int):
        """ReadIndex for the co-located cluster: every peer of the
        group lives in THIS process, so no other process can hold a
        newer leadership — the leader's current commit index IS the
        linearization point, no quorum round needed.  Returns () while
        the group has no leader yet (caller polls)."""
        p = int(self._hints[group])
        if p < 0:
            return ()
        return int(self._hard[p, group, 2]), 0

    def read_ready(self, group: int, reg_tick: int) -> bool:
        return True

    def status(self) -> dict:
        """Per-group consensus status for GET /healthz (same shape as
        runtime/node.py status()): in the co-located cluster the
        process's role for a group is "leader" once a leader is known
        — every peer lives here — and "unknown" while leaderless.
        Host caches only (hints + hard-state mirror); never touches
        device arrays."""
        out = {}
        for g in range(self.cfg.num_groups):
            p = int(self._hints[g])
            if p >= 0:
                out[str(g)] = {"role": "leader", "leader": p + 1,
                               "term": int(self._hard[p, g, 0]),
                               "commit": int(self._hard[p, g, 2])}
            else:
                out[str(g)] = {"role": "unknown", "leader": 0,
                               "term": 0, "commit": 0}
        return out

    # Published-deadline horizon: see runtime/node.py — the shm
    # publisher refreshes every millisecond or two, so capping how far
    # ahead a deadline reaches bounds staleness when the tick loop
    # hot-spins device steps faster than the wall interval.
    _LEASE_HORIZON_S = 0.05

    def lease_deadline_s(self, group: int) -> float:
        """The time.monotonic() instant until which a lease read for
        `group` stays provably safe, 0.0 when no live lease — the
        shm-snapshot / routing-hint surface (runtime/shm.py).  The
        remaining lease is measured in DEVICE steps against the same
        `_device_steps + max_clock_skew` bound lease_read enforces, so
        a mis-sized max_clock_skew propagates verbatim into the
        published deadline (the chaos falsification pair still
        catches it on the shm plane).  No metric side effects."""
        cfg = self.cfg
        if cfg.lease_ticks <= 0:
            return 0.0
        lc = self._lease_col
        p = int(self._hints[group])
        if lc is None or p < 0:
            return 0.0
        until = int(lc[p, group])
        remaining = until - (self._device_steps + cfg.max_clock_skew)
        if until <= 0 or remaining <= 0:
            return 0.0
        interval = max(cfg.tick_interval_s, 1e-4)
        return time.monotonic() + min(remaining * interval,
                                      self._LEASE_HORIZON_S)

    # -- the tick -------------------------------------------------------

    def _build_prop_n(self, steps: int = 1) -> np.ndarray:
        """Per-dispatch proposal counts.  steps == 1: [P, G], up to E
        per group.  steps > 1 (multi-step dispatch): [S, P, G] — each
        step gets its own ≤E chunk of the backlog, so one dispatch can
        accept (and commit) up to S×E per group.  The device may accept
        less at any step (window pressure); the host pops exactly what
        each step REPORTS accepted, in step order, and offers were cut
        from one backlog snapshot — so pops never outrun the queue and
        payloads stay aligned with the device's assigned indexes."""
        P, G = self.cfg.num_peers, self.cfg.num_groups
        cap = self._E * steps
        prop_n = np.zeros((P, G), np.int32)
        dead = []
        ov = self.overload
        now_step = self._device_steps
        with self._prop_lock:
            for (p, g) in list(self._queued):  # snapshot: re-routes mutate
                q = self._props[p][g]
                if not q:
                    dead.append((p, g))
                    continue
                h = int(self._hints[g])
                if 0 <= h != p:
                    # Re-route a backlog stranded at a deposed/wrong peer.
                    self._props[h][g].extend(q)
                    q.clear()
                    self._queued.add((h, g))
                    dead.append((p, g))
                    continue
                if self._deadlines_live:
                    # Shed queued entries whose device-step deadline
                    # already passed — BEFORE they are offered to the
                    # device, so no WAL write, fsync or publish is ever
                    # paid for work the client has given up on
                    # (overload plane; entries are (payload, deadline)
                    # pairs only when a deadline was supplied).
                    live = [e for e in q
                            if type(e) is not tuple or e[1] >= now_step]
                    n_shed = len(q) - len(live)
                    if n_shed:
                        q[:] = live
                        if ov is not None:
                            ov.stage_shed(g, n_shed)
                        if not q:
                            dead.append((p, g))
                            continue
                prop_n[p, g] = min(len(q), cap)
            for k in dead:
                self._queued.discard(k)
        if steps <= 1:
            return prop_n
        return np.stack([np.clip(prop_n - s * self._E, 0, self._E)
                         for s in range(steps)]).astype(np.int32)

    def _pub_run(self, q: "queue.Queue", shard: int) -> None:
        """Ordered publish worker (see __init__): per worker one queue,
        one disjoint group block, FIFO — publishes retire in tick
        order.  `_applied` and the commit queues for a given group are
        touched only by its owning worker after construction, so the
        cursor needs no lock; compact() reads _applied from other
        threads but a stale (lower) value only makes its floor more
        conservative."""
        import time as _t
        while True:
            item = q.get()
            try:
                # After a publish fault, keep draining (so flush/stop
                # never hang) but publish nothing more: the CLOSED
                # sentinel must stay the queues' last item.
                if item is not None and self.error is None:
                    pinfo, ptick = item
                    t0 = _t.monotonic()
                    self._publish_shard(pinfo, shard)
                    dur = _t.monotonic() - t0
                    with self._metrics_mu:
                        self.metrics.t_publish_ms += dur * 1e3
                    prof = self.prof
                    if prof is not None and prof.sampled(ptick):
                        # Per-shard publish workers tag their shard id
                        # — the mesh runtime's N workers each get their
                        # own Perfetto phase track.
                        prof.record("publish", ptick, t0, dur,
                                    tid=shard)
            except Exception as e:
                self.error = e
                for cq in self._commit_qs:
                    cq.put(CLOSED)
            finally:
                q.task_done()
            if item is None:
                return

    def _enqueue_publish(self, pinfo: np.ndarray) -> None:
        """Hand a durable tick's packed info to every publish worker
        (each delivers only its own group block).  The owning tick id
        (`self._prof_tick`, set by the caller) rides the queue item so
        the workers' publish phases attribute to the right tick."""
        item = (pinfo, self._prof_tick)
        for q in self._pub_qs:
            q.put(item)

    def publish_flush(self) -> None:
        """Block until every enqueued publish has been delivered (the
        bench and tests read apply-plane state right after a tick
        loop).  Re-raises a publish fault — the async path must fail as
        loudly as the inline one did.  Manual-tick callers (no tick
        thread) also retire any stashed double-buffered durable phase
        first — this is the pipeline drain."""
        if self._thread is None:
            self._drain_pipeline()
        for q in self._pub_qs:
            q.join()
        if self.error is not None:
            raise self.error

    def _ensure_epoch_begin(self, p: int) -> None:
        """Lazily open peer p's dispatch frame: the BEGIN marker is
        written only when the dispatch actually writes to that peer's
        WAL (an idle multi-step tick costs zero records and zero epoch
        fsyncs).  Safe from the per-peer workers: each touches only its
        own slot, and the epoch-number allocation is idempotent."""
        if not self._ep_active or self._ep_begun[p]:
            return
        if self._ep_no_this is None:
            self._ep_no_this = self._epoch_no + 1
        self._ep_begun[p] = True
        self.wals[p].epoch_mark(self._ep_no_this, end=False)

    def _commit_epoch(self, no: int) -> None:
        """The multi-step dispatch's atomic commit point: append the
        epoch number to data_dir/EPOCHS and fsync it — AFTER every
        peer's WAL barrier, BEFORE publish.  Recovery drops any
        dispatch whose number never made it here."""
        import struct
        import zlib
        created = False
        if self._epoch_f is None:
            created = not os.path.exists(self._epoch_path)
            self._epoch_f = open(self._epoch_path, "ab")
        rec = struct.pack("<Q", no)
        fsio.write(self._epoch_f,
                   rec + struct.pack("<I", zlib.crc32(rec)))
        fsio.fsync_file(self._epoch_f)
        if created:
            # Dirent durability for the just-created file, BEFORE the
            # epoch counts as committed: the record fsync above makes
            # the bytes durable but not the directory entry — a crash
            # could drop the whole file, and recovery would then
            # misclassify committed (already published/acked)
            # dispatches as uncommitted.  Mirrors the rotation path.
            fsio.fsync_dir(os.path.dirname(self._epoch_path) or ".")
        if self._epoch_f.tell() >= self._EPOCH_ROTATE_BYTES:
            # Rotate: only the LAST record matters for recovery.  Write
            # a one-record replacement beside the live file, fsync it,
            # atomically swap (rename is the commit), fsync the dir.
            tmp = self._epoch_path + ".tmp"
            with open(tmp, "wb") as f:
                fsio.write(f, rec + struct.pack("<I", zlib.crc32(rec)))
                fsio.fsync_file(f)
            os.replace(tmp, self._epoch_path)
            fsio.fsync_dir(os.path.dirname(self._epoch_path) or ".")
            self._epoch_f.close()
            self._epoch_f = open(self._epoch_path, "ab")

    def _save_hard(self, p: int, pinfo: np.ndarray) -> bool:
        """Write peer p's changed hard states (term/vote/commit) to its
        WAL, AFTER the tick's entry records (etcd wal.Save order: a
        torn tail can then never leave a hard state referencing lost
        entries).  Shared by the serial phase 2c and the parallel
        per-peer workers; True when anything changed."""
        col = pinfo[p]
        hs = np.stack([col[:, _C["term"]], col[:, _C["voted_for"]],
                       col[:, _C["commit"]]], axis=1)
        changed = np.nonzero((hs != self._hard[p]).any(axis=1))[0]
        if not changed.size:
            return False
        self._ensure_epoch_begin(p)
        self.wals[p].set_hardstates(changed, hs[changed, 0],
                                    hs[changed, 1], hs[changed, 2])
        self._hard[p][changed] = hs[changed]
        return True

    def tick(self) -> None:
        """One device step + the durable host phase.

        Order (the contract in the module docstring): dispatch → (while
        the device runs: publish the PREVIOUS tick's commits — they are
        already durable) → read packed info → mirror-reads → WAL +
        payload-log writes → fsync every peer.  The NEXT dispatch cannot
        happen before this method returns, so every message composed
        this tick is durable on its sender before any receiver observes
        it; publish always runs after the save of the tick it publishes.
        """
        import time as _t
        prof = self.prof
        prof_on = prof is not None and prof.sampled(self._tick_no)
        t0 = _t.monotonic()
        if self._xfer_req:
            self._transfer_arm()     # latch visible to THIS dispatch
        # Snapshot _queued: _build_prop_n may re-route into the set.
        prop_n = self._build_prop_n(self._steps)
        tb = _t.monotonic() if prof_on else t0
        if prof_on:
            prof.record("pop", self._tick_no, t0, tb - t0)
        ti = self.timer_inc
        if ti is not None:
            # Skew accounting: how far this tick's timer advances
            # deviate from lockstep, per peer, summed.
            self.metrics.faults_skew_ticks += int(
                np.abs(np.asarray(ti, np.int64) - 1).sum())
        pinfo_dev, busy_dev = self._device_step(prop_n, ti)
        if self.ring is not None:
            # Device-plane event ring: one extra small fused program
            # over arrays already resident (tracing-on cost only); the
            # ring stays on device and drains to host in batches.  A
            # multi-step dispatch records its final step — the ring is
            # tick-indexed at dispatch granularity, like the runtime.
            self.ring.record(self._tick_no,
                             pinfo_dev if self._steps == 1
                             else pinfo_dev[-1],
                             self.states.votes, self.inboxes.v_type,
                             self.inboxes.a_type, self._applied)
        t1 = _t.monotonic()
        if prof_on:
            prof.record("dispatch", self._tick_no, tb, t1 - tb)
        # Double-buffered dispatch: the PREVIOUS tick's stashed durable
        # phase (WAL writes + fsync barrier + publish) runs HERE, inside
        # this dispatch's device window — tick t's disk time overlaps
        # tick t+1's compute.  Strictly ordered: this completes before
        # this tick's own durable phase can begin.
        if self._stash is not None:
            tw0 = _t.monotonic()
            self._retire_stash()
            self.metrics.overlap_ticks += 1
            self.metrics.t_wal_ms += (_t.monotonic() - tw0) * 1e3
        # Overlap: tick t-1's commits are durable (fsynced last tick).
        # Parallel hosts hand them to the publish workers (the apply
        # plane runs concurrently with this whole tick); a 1-core host
        # delivers inline while the device computes.
        if self._pending_pinfo is not None:
            self._prof_tick = self._pending_tick
            if self._host_parallel:
                self._enqueue_publish(self._pending_pinfo)
            else:
                tp = _t.monotonic()
                self._publish(self._pending_pinfo)
                pdur = _t.monotonic() - tp
                self.metrics.t_publish_ms += pdur * 1e3
                if prof is not None and prof.sampled(self._pending_tick):
                    prof.record("publish", self._pending_tick, tp, pdur)
            self._pending_pinfo = None
        t2 = _t.monotonic()
        if self.overlap_hook is not None:
            # Hook wall time is the caller's (apply-plane) cost, not a
            # tick phase: charge it to neither publish nor device.
            self.overlap_hook()
            t2b = _t.monotonic()
        else:
            t2b = t2
        if busy_dev is not None:
            pinfo, dev_busy = jax.device_get((pinfo_dev, busy_dev))
            pinfo = np.asarray(pinfo)
            dev_busy = bool(dev_busy)
        else:
            pinfo = np.asarray(jax.device_get(pinfo_dev))  # [P,G,NCOLS]
            dev_busy = True
        t3 = _t.monotonic()
        if prof_on:
            # The readback is dispatch time too: the host blocks on the
            # device completing this tick's program.
            prof.record("dispatch", self._tick_no, t2b, t3 - t2b)

        # Multi-step dispatch (RAFTSQL_FUSED_STEPS > 1): packed info
        # arrives stacked [S, P, G, C]; the host replays its durable
        # phases in step order — every step's entries land before the
        # ONE hard-state save + fsync barrier of the dispatch, which
        # preserves the etcd wal.Save order (entries-then-hardstate)
        # at dispatch granularity.
        step_infos = ([np.asarray(pinfo[s])
                       for s in range(pinfo.shape[0])]
                      if pinfo.ndim == 4 else [pinfo])
        pinfo = step_infos[-1]
        self._hints = pinfo[0, :, _C["leader_hint"]]
        self._lease_col = pinfo[:, :, _C["lease"]]
        self._device_steps += len(step_infos)
        if self._xfers:
            self._transfer_advance(pinfo)
        # Stage the 2a ranges NOW (this pops the device-accepted
        # proposals off the queues): whether the durable phase runs
        # inline below or stashed into the next dispatch window, the
        # next _build_prop_n snapshot must see post-pop queue state —
        # that is what keeps the overlapped pipeline's trajectory
        # bit-identical to the serialized one.
        ts0 = _t.monotonic() if prof_on else 0.0
        staged = [self._stage_ranges(pi) for pi in step_infos]
        if prof_on:
            prof.record("pop", self._tick_no, ts0,
                        _t.monotonic() - ts0)
        if self.overload is not None:
            # Overload plane tick feed: drain-rate EWMA (Retry-After)
            # + queue-depth EWMA (the brownout governor's hysteresis).
            self.overload.note_tick()
        # Content-derived activity signals (durable-independent so the
        # stash decision cannot change them): any append staged or
        # mirrored, or any hard state due to change.
        tick_active = any(
            bool(st_p[0]) for st in staged for st_p in st)
        if not tick_active:
            for pi in step_infos:
                if (pi[:, :, _C["app_from"]] >= 0).any():
                    tick_active = True
                    break
        if not tick_active:
            hs = pinfo[:, :, [_C["term"], _C["voted_for"],
                              _C["commit"]]]
            tick_active = bool((hs != self._hard).any())
        # Quiescence signal for the threaded loop: anything written,
        # any group leaderless, or any proposal backlog means "keep
        # ticking at full pace".
        base_active = (tick_active
                       or dev_busy
                       or bool((self._hints < 0).any())
                       or bool(self._queued)
                       or bool(self._xfers))
        # HOT means real client work is flowing (writes this tick, a
        # device dispatch still in flight, or a proposal backlog): the
        # threaded loop then ticks back-to-back.  Merely-leaderless
        # groups keep the loop ACTIVE (elections must advance) but not
        # hot — warmup paces at interval_s instead of starving the
        # host core the cluster shares with its clients.
        self._spin_hot = tick_active or dev_busy or bool(self._queued)
        # Double-buffer decision: while the pipeline is HOT another
        # dispatch follows immediately, so this tick's durable phase is
        # stashed and retired inside that dispatch's device window.
        # Cold/parking ticks finish inline — deferring would add a
        # whole (possibly parked) tick of ack latency for no overlap.
        if self._overlap and self._spin_hot:
            # The stash remembers its ORIGINATING tick: when it retires
            # inside the next dispatch window, its durable/publish
            # phases are attributed to this tick, not the one that
            # happens to host the work (overlap-aware profiling).
            self._stash = (step_infos, staged, self._tick_no)
            self.metrics.t_device_ms += ((t1 - t0) + (t3 - t2b)) * 1e3
            self._tick_active = base_active
            self._tick_no += 1
            self.metrics.ticks += 1
            return
        self._prof_tick = self._tick_no
        tick_active = self._finish_durable(step_infos, staged) \
            or tick_active
        base_active = base_active or tick_active
        t4 = _t.monotonic()
        if base_active:
            if self._host_parallel:
                # The publish workers ARE the overlap: hand the tick's
                # commits over right after the durable barrier instead
                # of deferring to the next tick's dispatch window —
                # one whole tick less propose→ack latency.
                self._enqueue_publish(pinfo)
            else:
                # Serial host: defer-and-overlap pays only when the
                # publish is expensive.  A light tick's batch (a few
                # serving requests) costs far less to deliver NOW than
                # the whole tick of ack latency the deferral adds.
                delta = int(np.clip(
                    pinfo[0][:, _C["commit"]] - self._applied[0],
                    0, None).sum())
                if delta <= self._inline_publish_max:
                    tp = _t.monotonic()
                    self._publish(pinfo)
                    pdur = _t.monotonic() - tp
                    self.metrics.t_publish_ms += pdur * 1e3
                    if prof_on:
                        prof.record("publish", self._tick_no, tp, pdur)
                    self._pending_pinfo = None
                else:
                    self._pending_pinfo = pinfo  # next tick overlaps
                    self._pending_tick = self._tick_no
        else:
            # About to go quiet: deliver this tick's commits NOW (they
            # are fsynced above) instead of deferring to a next tick
            # that may be a parked 0.5s away — the deferral only pays
            # when another dispatch immediately follows to overlap.
            if self._host_parallel:
                self._enqueue_publish(pinfo)
            else:
                tp = _t.monotonic()
                self._publish(pinfo)
                pdur = _t.monotonic() - tp
                self.metrics.t_publish_ms += pdur * 1e3
                if prof_on:
                    prof.record("publish", self._tick_no, tp, pdur)
            self._pending_pinfo = None
        self._tick_active = base_active
        self.metrics.t_device_ms += ((t1 - t0) + (t3 - t2b)) * 1e3
        self.metrics.t_wal_ms += (_t.monotonic() - t4) * 1e3
        self._tick_no += 1
        self.metrics.ticks += 1

    def _retire_stash(self) -> None:
        """Run the stashed tick's durable phase + publish (the
        double-buffered pipeline's back half).  Caller order guarantees
        this precedes the NEXT durable phase and its publish."""
        import time as _t
        step_infos, staged, stick = self._stash
        self._stash = None
        # Attribute the whole retired phase to its ORIGINATING tick.
        self._prof_tick = stick
        self._finish_durable(step_infos, staged)
        pinfo = step_infos[-1]
        if self._host_parallel:
            self._enqueue_publish(pinfo)
        else:
            tp = _t.monotonic()
            self._publish(pinfo)
            pdur = _t.monotonic() - tp
            self.metrics.t_publish_ms += pdur * 1e3
            prof = self.prof
            if prof is not None and prof.sampled(stick):
                prof.record("publish", stick, tp, pdur)

    def _drain_pipeline(self) -> None:
        """Retire any stashed durable phase (manual-tick callers: the
        bench, chaos runners, tests).  NOT safe against a concurrently
        running tick thread — stop() joins the thread first."""
        if self._stash is not None:
            self._retire_stash()

    def _finish_durable(self, step_infos, staged) -> bool:
        """The whole durable back half for one dispatch: per-step
        durable phases (epoch-framed when multi-step), the epoch
        commit, and membership apply-at-commit.  Returns tick_active
        (anything written).  Attributed to `self._prof_tick` (set by
        the caller: the live tick inline, the originating tick when a
        stash retires)."""
        import time as _t
        pinfo = step_infos[-1]
        prof = self.prof
        ptick = self._prof_tick
        prof_on = prof is not None and prof.sampled(ptick)
        td0 = _t.monotonic() if prof_on else 0.0
        self._fsync_span = None
        # Multi-step dispatches are epoch-framed (see _ensure_epoch_
        # begin / _commit_epoch): BEGIN lazily wraps each peer's first
        # write, END lands before its fsync, and the dispatch commits
        # atomically below.
        self._ep_active = len(step_infos) > 1
        if self._ep_active:
            self._ep_begun = [False] * self.cfg.num_peers
            self._ep_no_this = None
        tick_active = False
        for si, (pi, st) in enumerate(zip(step_infos, staged)):
            tick_active = self._durable_phases(
                pi, final=(si == len(step_infos) - 1),
                staged=st) or tick_active
        if self._ep_active and self._ep_no_this is not None:
            # Every peer's barrier is down; this fsync is the
            # dispatch's atomic commit point (before any publish).
            self._epoch_no = self._ep_no_this
            self._commit_epoch(self._epoch_no)
        self._ep_active = False
        if self.membership is not None:
            # Apply-at-commit for conf entries: patch each peer row
            # whose commit passed a pending entry, BEFORE this tick's
            # publish enqueue (the scrub set must cover the batch).
            self._membership_advance(pinfo)
        if self._gcwal is not None:
            self.metrics.wal_group_commits = self._gcwal.group_commits
        if prof_on and tick_active:
            # wal_write = the durable back half minus the fsync barrier
            # (the barrier was clocked where it ran, serial or across
            # the per-peer workers — _durable_phases fills _fsync_span).
            t_tot = _t.monotonic() - td0
            fs = self._fsync_span
            fdur = fs[1] if fs is not None else 0.0
            prof.record("wal_write", ptick, td0, max(t_tot - fdur, 0.0))
            if fs is not None:
                prof.record("fsync", ptick, fs[0], fdur)
        return tick_active

    def _stage_ranges(self, pinfo: np.ndarray) -> list:
        """Build one step's phase-2a write plan — per peer the
        (r_g, r_start, r_count, r_term, w_d) uniform-term ranges of
        fresh-leader no-ops + accepted proposals — POPPING the accepted
        payloads off the proposal queues.  Runs at stage time, in the
        tick that read this pinfo: the pops must settle before the next
        tick's _build_prop_n snapshot (offer counts and re-routes read
        queue lengths), whether the heavy durable write runs inline or
        stashed into the next dispatch window.  Side effects that ride
        the pop (conf-entry notes, tracer append stamps, the proposals
        counter) happen here too, in step order."""
        P = self.cfg.num_peers
        out = []
        for p in range(P):
            col = pinfo[p]
            noop = col[:, _C["noop"]]
            acc = col[:, _C["prop_accepted"]]
            base = col[:, _C["prop_base"]]
            term = col[:, _C["term"]]
            r_g: List[int] = []
            r_start: List[int] = []
            r_count: List[int] = []
            r_term: List[int] = []
            w_d: List[bytes] = []
            ngs = np.nonzero(noop)[0]
            if ngs.size:
                # One empty record at prop_base per fresh leader
                # (ordered before any accepted proposals of the same
                # group — base < base+1, both pure tail appends).
                r_g.extend(ngs.tolist())
                r_start.extend(base[ngs].tolist())
                r_count.extend([1] * ngs.size)
                r_term.extend(term[ngs].tolist())
                w_d.extend([b""] * ngs.size)
            ags = np.nonzero(acc > 0)[0]
            if ags.size:
                props_p = self._props[p]
                traced = [] if self.tracer is not None else None
                confs = [] if self.membership is not None else None
                ov = self.overload
                strip = self._deadlines_live
                with self._prop_lock:   # pops race client-thread extends
                    for g, n, b0, tm in zip(ags.tolist(),
                                            acc[ags].tolist(),
                                            (base[ags] + 1).tolist(),
                                            term[ags].tolist()):
                        q = props_p[g]
                        batch = q[:n]
                        del q[:n]
                        if strip:
                            # Deadline-carrying entries are (payload,
                            # deadline_step) pairs — strip to plain
                            # bytes before WAL/trace/conf consumers.
                            batch = [e[0] if type(e) is tuple else e
                                     for e in batch]
                        if ov is not None:
                            ov.drained(g, n)
                        w_d.extend(batch)
                        r_g.append(g)
                        r_start.append(b0)
                        r_count.append(n)
                        r_term.append(tm)
                        if traced is not None:
                            traced.append((g, b0, batch))
                        if confs is not None:
                            # Conf entries entering the cluster log —
                            # one leading-byte test per accepted
                            # proposal, only with membership enabled.
                            for off, d in enumerate(batch):
                                if d[:1] == _CONF_PREFIX \
                                        and is_conf_entry(d):
                                    confs.append((g, b0 + off, d))
                if confs:
                    for (cg, cidx, cd) in confs:
                        self._conf_note(cg, cidx, cd)
                self.metrics.proposals += int(acc[ags].sum())
                # Per-group traffic: the accepted counts are already in
                # hand per group — one vectorized add, no new walks.
                self.traffic.add_propose(ags, acc[ags])
                if traced:
                    # Append stamp + index binding, outside the lock.
                    for g, b0, batch in traced:
                        self.tracer.note_append(
                            g, b0, [d.decode("utf-8", "replace")
                                    for d in batch])
            out.append((r_g, r_start, r_count, r_term, w_d))
        return out

    def _durable_phases(self, pinfo: np.ndarray, final: bool,
                        staged: list) -> bool:
        """The durable host phases for ONE step's packed info [P,G,C]:
        phase 1 collects mirror METADATA (peer, src, group, start,
        count, new_len) with no reads; phase 2a writes leader appends
        (fresh-leader no-ops + accepted proposals, pre-popped into
        `staged` by _stage_ranges) as uniform-term RANGES; phase 2b
        mirrors follower appends.  Mirror-source
        staging happens inside 2b AFTER 2a's appends — safe because 2a
        writes are pure TAIL appends strictly above any mirrored range
        (mirror ranges were composed from the source's ring at the end
        of the PREVIOUS step), and the only same-step writes that can
        truncate or overwrite a mirrored range are OTHER MIRRORS, which
        both 2b paths stage fully before writing.  Any future 2a change
        that is not a pure tail append breaks this argument and must
        move 2a after 2b's staging.

        On the dispatch's FINAL step only, phase 2c (hard states) and
        the per-peer fsync barrier run — a multi-step dispatch saves
        every step's entries, then one hard state, then one fsync,
        which is the etcd wal.Save order at dispatch granularity.
        Returns tick_active (entries or hard states written)."""
        P = self.cfg.num_peers
        m_peer: List[int] = []
        m_src: List[int] = []
        m_g: List[int] = []
        m_start: List[int] = []
        m_count: List[int] = []
        m_newlen: List[int] = []
        for p in range(P):
            col = pinfo[p]
            accepted = np.nonzero(col[:, _C["app_from"]] >= 0)[0]
            if not accepted.size:
                continue
            sub = col[accepted]
            m_peer.extend([p] * accepted.size)
            m_g.extend(accepted.tolist())
            m_src.extend(sub[:, _C["app_from"]].tolist())
            m_start.extend(sub[:, _C["app_start"]].tolist())
            m_count.extend(sub[:, _C["app_n"]].tolist())
            m_newlen.extend(sub[:, _C["new_log_len"]].tolist())

        if self.tracer is not None and m_peer:
            # Replicate stamp: the mirrored range is landing in a
            # follower's log this step (first stamp wins per index).
            for g, st, c in zip(m_g, m_start, m_count):
                if c:
                    self.tracer.note_replicate(g, st + c - 1)

        if self.witness_peers and m_peer:
            # Witnesses never lead, so every entry they persist arrives
            # here as a mirrored follower append.
            self.metrics.witness_appends += sum(
                c for p, c in zip(m_peer, m_count)
                if c and p in self.witness_peers)

        # Phase 2a: leader appends (fresh-leader no-ops + accepted
        # proposals) as uniform-term RANGES per peer — the write plan
        # was staged (and the payloads popped) by _stage_ranges; one
        # combined native call writes the WAL records and the
        # payload-log range (wal.append_ranges_uniform); the fallback
        # expands ranges to per-entry numpy columns for the classic
        # two-call path.
        tick_active = bool(m_peer)
        for p in range(P):
            r_g, r_start, r_count, r_term, w_d = staged[p]
            if not r_g:
                continue
            tick_active = True
            self._ensure_epoch_begin(p)
            plog_native = (self.plogs[p]
                           if hasattr(self.plogs[p], "handle") else None)
            wrote = False
            if plog_native is not None:
                blob = b"".join(w_d)
                lens = np.fromiter(map(len, w_d), np.uint32, len(w_d))
                wrote = self.wals[p].append_ranges_uniform(
                    plog_native, r_g, r_start, r_count, r_term, blob,
                    lens)
            if not wrote:
                # Python plog path: RANGE records — one framed record
                # per (group, start, term) run, not one per entry.
                self.wals[p].append_ranges(r_g, r_start, r_count,
                                           r_term, w_d)
                puts = []
                pos = 0
                for g, s, c, tm in zip(r_g, r_start, r_count, r_term):
                    puts.append((g, s, w_d[pos: pos + c], [tm] * c,
                                 None))
                    pos += c
                self.plogs[p].put_ranges(puts)

        # Phases 2b+2c+fsync, PARALLEL per peer when the native plane
        # is up: worker p runs [mirrors INTO peer p] + [peer p's hard
        # states] + [peer p's fsync].  Safe to run concurrently: phase
        # 2a's appends are complete; a group's mirror source (its
        # leader's plog) and dest (a follower's) are different peers,
        # and since a group has ONE leader, worker A writing group g'
        # into plog[X] can never touch the group-g ranges worker B
        # reads FROM plog[X] — per-group data is disjoint across
        # workers, and every C structure carries its own mutex.  This
        # overlaps the 3x payload memcpy + write + fsync across cores
        # instead of serializing them on the tick thread.
        par_ok = (final
                  and self._host_parallel
                  and self.wals
                  and self.wals[0]._lib is not None
                  and hasattr(self.wals[0]._lib, "walplog_mirror_all")
                  and all(w._lib is not None for w in self.wals)
                  and all(hasattr(pl, "handle") for pl in self.plogs))
        if par_ok and m_peer:
            # Per-group disjointness holds per LEADER, and a leader can
            # change within a tick: group g's old leader X may accept
            # from new leader Y (mirror INTO plog[X], with truncation)
            # in the same tick another peer still mirrors g FROM
            # plog[X].  Concurrent workers would then write a source
            # mid-read.  Detect it (a group whose mirror source is also
            # one of its mirror dests) and take the serial staged path
            # for this tick — it is an election-tick rarity.
            dests: Dict[int, set] = {}
            for g, p in zip(m_g, m_peer):
                dests.setdefault(g, set()).add(p)
            for g, s in zip(m_g, m_src):
                if s in dests.get(g, ()):
                    par_ok = False
                    break
        if par_ok:
            by_peer: List[List[int]] = [[] for _ in range(P)]
            for i, mp in enumerate(m_peer):
                by_peer[mp].append(i)

            import time as _t

            def _host_peer(p: int) -> bool:
                idx = by_peer[p]
                if idx:
                    self._ensure_epoch_begin(p)
                    wal_mirror_all(
                        self.wals, self.plogs,
                        [m_peer[i] for i in idx],
                        [m_src[i] for i in idx],
                        [m_g[i] for i in idx],
                        [m_start[i] for i in idx],
                        [m_count[i] for i in idx],
                        [m_newlen[i] for i in idx])
                changed = self._save_hard(p, pinfo)
                if self._ep_begun[p]:
                    self.wals[p].epoch_mark(self._ep_no_this, end=True)
                ts = _t.monotonic()
                self.wals[p].sync()
                self._fsync_dur[p] = _t.monotonic() - ts
                return changed

            tm0 = _t.monotonic()
            for act in self._sync_pool.map(_host_peer, range(P)):
                tick_active = tick_active or act
            # The barrier cost is max, not sum: the per-peer syncs ran
            # concurrently on the pool (see _finish_durable's profiler
            # attribution).
            self._fsync_span = (tm0, float(self._fsync_dur[:P].max()))
        elif m_peer:
            for p in sorted(set(m_peer)):
                self._ensure_epoch_begin(p)
            if not wal_mirror_all(self.wals, self.plogs, m_peer, m_src,
                                  m_g, m_start, m_count, m_newlen):
                # Python two-pass fallback: ALL source reads first (the
                # staging contract), then one batched write per peer.
                reads = [self.plogs[s].slice_columns(g, st, c)
                         if c else ([], [])
                         for (s, g, st, c) in zip(m_src, m_g, m_start,
                                                  m_count)]
                for p in range(P):
                    b_g: List[int] = []
                    b_start: List[int] = []
                    b_count: List[int] = []
                    b_terms: List[int] = []
                    b_d: List[bytes] = []
                    puts = []
                    for (mp, g, st, c, nl), (terms, datas) in zip(
                            zip(m_peer, m_g, m_start, m_count,
                                m_newlen), reads):
                        if mp != p:
                            continue
                        puts.append((g, st, datas, terms, nl))
                        if c:
                            b_g.append(g)
                            b_start.append(st)
                            b_count.append(c)
                            b_terms.extend(terms)
                            b_d.extend(datas)
                    if puts:
                        self.plogs[p].put_ranges(puts)
                    if b_g:
                        # Mirrored batches may cross term boundaries;
                        # RANGE records are uniform-term, so split each
                        # mirror at its term changes (rare: elections).
                        s_g: List[int] = []
                        s_start: List[int] = []
                        s_count: List[int] = []
                        s_term: List[int] = []
                        pos = 0
                        for g, st0, c in zip(b_g, b_start, b_count):
                            for (rs, rc, rt) in split_uniform_runs(
                                    st0, b_terms[pos: pos + c]):
                                s_g.append(g)
                                s_start.append(rs)
                                s_count.append(rc)
                                s_term.append(rt)
                            pos += c
                        self.wals[p].append_ranges(s_g, s_start, s_count,
                                                   s_term, b_d)

        # Phase 2c (serial path only — the parallel path folded hard
        # states + fsync into its per-peer workers): hard states after
        # every ENTRY record of the tick (etcd wal.Save order: a torn
        # tail can then never leave a hard state referencing lost
        # entries), then the per-peer fsync that is the durable barrier
        # before the next dispatch.
        if final and not par_ok:
            for p in range(P):
                tick_active = self._save_hard(p, pinfo) or tick_active
            if self._ep_active:
                for p in range(P):
                    if self._ep_begun[p]:
                        self.wals[p].epoch_mark(self._ep_no_this,
                                                end=True)
            # The durable barrier: every peer fsynced before this
            # tick's messages can be observed (the next dispatch).  The
            # P fsyncs are independent files — run them concurrently
            # (os.fsync and the native wal_sync both release the GIL),
            # so the barrier costs one fsync wall-time, not P.  A peer
            # with nothing pending returns immediately.
            import time as _t
            tf0 = _t.monotonic()
            list(self._sync_pool.map(lambda w: w.sync(), self.wals))
            self._fsync_span = (tf0, _t.monotonic() - tf0)
        return tick_active

    def _scrub_conf(self, g: int, base: int, datas: list) -> list:
        """Blank conf entries out of a publish batch (entries at
        base+1..): the apply plane sees an empty slot where the
        membership change sat.  Index-driven off the scrub set — zero
        per-entry work; `_conf_scrub[g]` is replaced (never mutated) so
        the async publish workers can read it lock-free."""
        scrub = self._conf_scrub[g]
        if scrub:
            top = base + len(datas)
            for idx in scrub:
                if base < idx <= top:
                    datas[idx - base - 1] = b""
        return datas

    def _publish(self, pinfo: np.ndarray) -> None:
        """Deliver a saved tick's newly committed entries to every
        peer's commit stream, across ALL group shards (the inline /
        serial-host path; the async path fans the same pinfo out to the
        per-shard workers instead)."""
        for shard in range(len(self._shard_groups)):
            self._publish_shard(pinfo, shard)

    def _publish_shard(self, pinfo: np.ndarray, shard: int) -> None:
        """Deliver one group shard's newly committed entries to each
        peer's commit stream (they were fsynced before this runs) — the
        whole tick's block as ONE RAW_MANY queue item per peer."""
        gsel = self._shard_groups[shard]
        for p in range(self.cfg.num_peers):
            col = pinfo[p]
            commit = col[:, _C["commit"]]
            if gsel is None:
                ready = np.nonzero(commit > self._applied[p])[0]
            else:
                ready = gsel[commit[gsel] > self._applied[p][gsel]]
            if not ready.size:
                continue
            if p == 0 and self.tracer is not None:
                # Quorum/commit stamp on the client-facing stream.
                for g, c in zip(ready.tolist(), commit[ready].tolist()):
                    self.tracer.note_commit(g, int(c))
            if (self.publish_peers is not None
                    and p not in self.publish_peers) \
                    or p in self.witness_peers:
                # Nobody consumes this peer's stream (or it is a
                # witness, which never applies): advance the cursor
                # without materializing anything.
                if p == 0:
                    deltas = commit[ready] - self._applied[p][ready]
                    self.traffic.add_commit(ready, deltas)
                    self._note_commits(int(deltas.sum()))
                self._applied[p][ready] = commit[ready]
                continue
            plog = self.plogs[p]
            gl = ready.tolist()
            cl = commit[ready].tolist()
            al = self._applied[p][ready].tolist()
            if p == 0 and self.native_kv is not None \
                    and self.membership is None:
                # C-resident apply: one call, zero Python per entry.
                self.native_kv.apply_plog(
                    plog.handle, gl, [a + 1 for a in al],
                    [c - a for c, a in zip(cl, al)])
                self._applied[p][ready] = commit[ready]
                deltas = commit[ready] - np.asarray(al)
                self.traffic.add_commit(ready, deltas)
                self._note_commits(int(deltas.sum()))
                continue
            items = []
            if hasattr(plog, "read_groups"):
                # Native plog: every ready range in TWO ctypes calls.
                per_range = plog.read_groups(
                    gl, [a + 1 for a in al],
                    [c - a for c, a in zip(cl, al)])
                for g, a, datas in zip(gl, al, per_range):
                    if self.membership is not None:
                        datas = self._scrub_conf(g, a, list(datas))
                    if any(datas):
                        items.append((g, a, datas))
            else:
                sl = plog.slice
                for g, a, c in zip(gl, al, cl):
                    datas = sl(g, a + 1, c - a)
                    if len(datas) != c - a:
                        raise RuntimeError(
                            f"peer {p} g{g}: payload log shorter than "
                            f"commit ({a}+{len(datas)} < {c})")
                    if self.membership is not None:
                        datas = self._scrub_conf(g, a, datas)
                    if any(datas):
                        items.append((g, a, datas))
            if items:
                self._commit_qs[p].put((RAW_MANY, items))
            self._applied[p][ready] = commit[ready]
            if p == 0:
                deltas = commit[ready] - np.asarray(al)
                self.traffic.add_commit(ready, deltas)
                self._note_commits(int(deltas.sum()))

    # -- log compaction (SURVEY §5.4) -----------------------------------

    def compact(self, applied: Optional[Dict[int, int]] = None,
                keep: int = 1024) -> bool:
        """Advance every peer's compaction floor to (applied - keep):
        payload-log prefixes drop, COMPACT markers land in the WALs, and
        fully-superseded closed segments unlink (storage/wal.py compact)
        — the memory-bound story for sustained load (the reference's
        MemoryStorage grows forever, raft.go:129).

        `keep` is clamped to >= log_window so every index the device
        ring can still reference stays servable (mirror reads and
        in-window resends).  The publish cursor gates the floor: only
        entries already delivered to the apply plane are dropped.
        `applied` optionally tightens it further to the state machines'
        DURABLY applied indexes — the calling convention RaftDB's
        snapshot-driven compaction uses (runtime/db.py _maybe_compact),
        so the --fused --resume --compact-every deployment works.
        """
        keep = max(keep, self.cfg.log_window)
        G = self.cfg.num_groups
        any_changed = False
        for p in range(self.cfg.num_peers):
            plog = self.plogs[p]
            floors: Dict[int, Tuple[int, int]] = {}
            changed = False
            for g in range(G):
                floor = int(self._applied[p][g]) - keep
                if applied is not None:
                    floor = min(floor, applied.get(g, 0) - keep)
                if floor > plog.start(g):
                    plog.compact(g, floor, plog.term_of(g, floor))
                    changed = True
                s = plog.start(g)
                if s > 0:
                    floors[g] = (s, plog.term_of(g, s))
            if changed:
                hard = {g: tuple(int(x) for x in self._hard[p][g])
                        for g in range(G)}
                self.wals[p].compact(floors, hard)
                self.metrics.compactions += 1
                any_changed = True
        return any_changed

    # -- teardown -------------------------------------------------------

    def stop(self) -> None:
        if self._thread is not None:
            self._stop_evt.set()
            self._work_evt.set()
            self._thread.join(timeout=10)
            self._thread = None
        if self.error is None:
            # Clean shutdown retires the double-buffered tail (WAL
            # write + fsync + publish) so nothing acked-able is lost;
            # an errored engine must NOT touch the WALs again.
            try:
                self._drain_pipeline()
            except Exception as e:      # pragma: no cover - defensive
                self.error = e
        else:
            self._stash = None
        if self._pending_pinfo is not None:
            self._prof_tick = self._pending_tick
            self._enqueue_publish(self._pending_pinfo)  # already durable
            self._pending_pinfo = None
        for q in self._pub_qs:
            q.put(None)                       # drain, then retire
        for th in self._pub_threads:
            th.join(timeout=10)
        self._sync_pool.shutdown(wait=True)
        if self._epoch_f is not None:
            self._epoch_f.close()
            self._epoch_f = None
        for w in self.wals:
            w.close()
        for plog in self.plogs:
            if hasattr(plog, "close"):
                plog.close()
        for q in self._commit_qs:
            q.put(CLOSED)

    # -- introspection (tests) -----------------------------------------

    def roles(self) -> np.ndarray:
        """[P, G] role matrix from the live device state."""
        return np.asarray(self.states.role)
