"""Propose ring — shared-memory request plane for multi-worker serving.

BENCH_r05 measured the fused engine committing 500k+ writes/s durable
while ONE event-loop process served 5.8k HTTP req/s: request parsing,
ack serialization, and the consensus tick all contend for a single
GIL.  This module splits the serving plane across OS processes the way
the reference splits peers (one process per concern) without giving up
the single fused engine:

    worker 0 ─┐  request ring (mmap SPSC)  ┌─> RingServer drain ──┐
    worker 1 ─┼──────────────────────────>─┤   rdb.propose(...)    │ engine
    worker N ─┘ <────────────────────────  └─< completion rings <──┘
                completion ring (mmap SPSC, acks batched per commit)

Each worker is a full asyncio HTTP plane (api/aio.py) binding the SAME
port via SO_REUSEPORT — the kernel load-balances connections — whose
"RaftDB" is a `RingClient` facade: proposals become fixed-layout
records in a per-worker mmap'd SPSC request ring, acknowledgements
come back through a per-worker completion ring resolved straight into
the worker's event loop.  HTTP parsing and response serialization now
burn OTHER processes' GILs; the engine process spends its cycles on
the consensus tick and the WAL.

Ring design (`SpscRing`): a file-backed mmap with a 64-byte header
(head = consumer cursor, tail = producer cursor, both monotonically
increasing u64) and a power-of-two data region.  Records are
`u32 length | payload`, contiguous; a record that would straddle the
end of the region is preceded by a WRAP marker (length 0xFFFFFFFF) and
restarts at offset 0.  Exactly one producer and one consumer advance
their own cursor and only READ the other's, so no locks cross the
process boundary; `pop()` hands out a zero-copy memoryview into the
mmap that is valid until `pop_commit()` publishes the new head —
`pop_batch()` uses that window to decode a whole backlog before
releasing any of it.  Within the engine process several threads may
complete requests concurrently, so the completion ring's producer side
takes an in-process lock (the SPSC contract is per process pair, not
per thread).

Record grammar (little endian; shared by RingClient/RingServer only —
nothing else parses these):

  request:    u8 op | u64 req_id | u32 group | u8 flags | u64 token
              | u64 deadline | bytes body
      op 1 PUT      body = sql          (token: X-Raft-Retry-Token, 0 none)
      op 2 GET      body = sql          (flags bit 0: linearizable,
                                         bit 1: session, bit 2: follower;
                                         token = session watermark)
      op 3 DOC      body = document name (metrics/health/members/...)
      op 4 MEMBER   body = json {group, op, peer}
      op 5 XFER     body = json {group, target} (leadership transfer)
      deadline: absolute CLOCK_MONOTONIC milliseconds after which the
      request is dead (0 = none).  Rings are same-machine mmaps, so the
      monotonic clock is shared; the engine sheds expired records at the
      drain (counted shed_ring) before any WAL/fsync cost.
  completion: u64 req_id | u8 status | u32 leader | bytes body
      status 0 OK   (body = rows/doc for GET/DOC/MEMBER, empty for PUT;
                     leader = the engine's session watermark for the
                     request's group — the X-Raft-Session echo)
      status 1 ERR  (body = message; deterministic 400 class)
      status 2 NOT_LEADER (leader = 1-based hint; 421 class)
      status 3 UNAVAILABLE (body = message; 503 class)
      status 4 OVERLOADED (body = message; 429 class — admission
                     refusal or ring-drain deadline shed; leader =
                     Retry-After in MILLISECONDS, the controller's
                     jittered drain-rate estimate)
"""
from __future__ import annotations

import json
import logging
import mmap
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("raftsql_tpu.ring")

_MAGIC = 0x52494E47                   # "RING"
_HDR = 64                             # file header bytes
_OFF_MAGIC, _OFF_CAP, _OFF_HEAD, _OFF_TAIL = 0, 4, 16, 32
_WRAP = 0xFFFFFFFF

_REQ = struct.Struct("<BQIBQQ")       # op, req_id, group, flags, token,
#                                       deadline (monotonic ms, 0 none)
_CPL = struct.Struct("<QBI")          # req_id, status, leader

OP_PUT, OP_GET, OP_DOC, OP_MEMBER, OP_XFER, OP_RESHARD = 1, 2, 3, 4, 5, 6
ST_OK, ST_ERR, ST_NOT_LEADER, ST_UNAVAILABLE, ST_OVERLOADED = 0, 1, 2, 3, 4

DEFAULT_RING_BYTES = 4 << 20


class RingFull(RuntimeError):
    """Producer outran the consumer past the ring's capacity."""


class SpscRing:
    """File-backed single-producer/single-consumer byte ring (see
    module doc for the layout).  One side constructs with create=True
    (truncates + initializes), the other attaches."""

    def __init__(self, path: str, size: int = DEFAULT_RING_BYTES,
                 create: bool = False):
        if create:
            if os.environ.get("RAFTSQL_RING_DEBUG"):
                import traceback
                with open("/tmp/ring_creates.log", "a") as dbg:
                    dbg.write(f"pid={os.getpid()} create {path}\n")
                    dbg.write("".join(traceback.format_stack()[-6:]))
                    dbg.write("----\n")
            size = 1 << (size - 1).bit_length()        # power of two
            with open(path, "wb") as f:
                f.truncate(_HDR + size)
                f.flush()
            fd = os.open(path, os.O_RDWR)
            try:
                self._mm = mmap.mmap(fd, _HDR + size)
            finally:
                os.close(fd)
            struct.pack_into("<II", self._mm, _OFF_MAGIC, _MAGIC, size)
            struct.pack_into("<Q", self._mm, _OFF_HEAD, 0)
            struct.pack_into("<Q", self._mm, _OFF_TAIL, 0)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                st_size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, st_size)
            finally:
                os.close(fd)
            magic, size = struct.unpack_from("<II", self._mm, _OFF_MAGIC)
            if magic != _MAGIC or st_size != _HDR + size:
                raise ValueError(f"{path}: not a ring file")
        self.path = path
        self.cap = size
        self._mask = size - 1
        self._view = memoryview(self._mm)
        # Cached cursors: the producer owns tail (its cached copy is
        # authoritative), the consumer owns head; each re-reads the
        # OTHER side's cursor from the mmap on demand.
        self._tail = self._load(_OFF_TAIL)
        self._head = self._load(_OFF_HEAD)
        self._pending_head: Optional[int] = None

    # -- cursor I/O ------------------------------------------------------

    def _load(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def _store(self, off: int, v: int) -> None:
        struct.pack_into("<Q", self._mm, off, v)

    # -- producer --------------------------------------------------------

    def push(self, payload: bytes) -> bool:
        """Append one record; False when the ring lacks space (caller
        backs off — records are never torn)."""
        n = len(payload)
        if n == 0:
            # An empty record is indistinguishable from unwritten ring
            # memory — the consumer's corruption check keys on exactly
            # that, so empties are illegal (both codecs' records are
            # ≥ 13 bytes anyway).
            raise ValueError("empty ring records are not allowed")
        need = 4 + n
        if need + 4 > self.cap:
            raise ValueError(f"record of {n} bytes exceeds ring capacity")
        tail = self._tail
        head = self._load(_OFF_HEAD)
        pos = tail & self._mask
        room = self.cap - (tail - head)
        contig = self.cap - pos
        if contig < need:
            # Wrap: marker (if 4 bytes fit) + restart at 0.  The skipped
            # gap consumes capacity, so account for it in `room`.
            if room < contig + need:
                return False
            if contig >= 4:
                struct.pack_into("<I", self._mm, _HDR + pos, _WRAP)
            tail += contig
            pos = 0
        elif room < need:
            return False
        struct.pack_into("<I", self._mm, _HDR + pos, n)
        self._mm[_HDR + pos + 4:_HDR + pos + 4 + n] = payload
        tail += need
        self._tail = tail
        self._store(_OFF_TAIL, tail)
        return True

    # -- consumer --------------------------------------------------------

    def pop(self) -> Optional[memoryview]:
        """Next record as a zero-copy view into the mmap, or None when
        empty.  The view stays valid until pop_commit(); interleave
        pop/pop_commit freely (commit releases everything popped so
        far)."""
        head = self._pending_head if self._pending_head is not None \
            else self._head
        tail = self._load(_OFF_TAIL)
        # DIRECTIONAL emptiness check, not equality: both cursors are
        # monotone, so a cross-process read of the producer's tail can
        # only ever be STALE-SMALL — observed in practice (a freshly
        # faulted header page served an old value under memory
        # pressure).  With `==`, a stale tail below our head sails past
        # the check and pop() walks into unwritten bytes; with `<=` any
        # stale read just looks momentarily empty and the next poll
        # sees the real cursor.
        if tail <= head:
            return None
        pos = head & self._mask
        contig = self.cap - pos
        if contig < 4:
            head += contig
            pos = 0
        else:
            (n,) = struct.unpack_from("<I", self._mm, _HDR + pos)
            if n == _WRAP:
                head += contig
                pos = 0
            else:
                self._check_len(n, head, tail, pos)
                view = self._view[_HDR + pos + 4:_HDR + pos + 4 + n]
                self._pending_head = head + 4 + n
                return view
        if tail <= head:
            self._pending_head = head
            return None
        (n,) = struct.unpack_from("<I", self._mm, _HDR + pos)
        self._check_len(n, head, tail, pos)
        view = self._view[_HDR + pos + 4:_HDR + pos + 4 + n]
        self._pending_head = head + 4 + n
        return view

    def _check_len(self, n: int, head: int, tail: int,
                   pos: int) -> None:
        """A record length must be sane (records are never empty and
        never straddle the region end).  A violation means cursor
        desync or an outside writer — fail loudly with the cursor
        state instead of handing garbage to a decoder."""
        if n == 0 or pos + 4 + n > self.cap:
            raise RuntimeError(
                f"{self.path}: corrupt ring record: len={n} at "
                f"pos={pos} head={head} tail={tail} cap={self.cap}")

    def pop_commit(self) -> None:
        """Publish the consumer cursor past everything pop() returned —
        after this the producer may overwrite those bytes."""
        if self._pending_head is not None:
            self._head = self._pending_head
            self._pending_head = None
            self._store(_OFF_HEAD, self._head)

    def depth_bytes(self) -> int:
        """Unconsumed bytes (either side may call; approximate under
        concurrency — clamped, a stale cursor pair can momentarily
        invert)."""
        return max(0, self._load(_OFF_TAIL) - self._load(_OFF_HEAD))

    def cursors(self) -> Tuple[int, int]:
        """Raw (head, tail) byte cursors — the flight recorder's view
        of where each side of the ring stood at crash time."""
        return self._load(_OFF_HEAD), self._load(_OFF_TAIL)

    def close(self) -> None:
        self._view.release()
        self._mm.close()


# ---------------------------------------------------------------------------
# Record codecs.


def encode_request(op: int, req_id: int, group: int, flags: int,
                   token: int, body: bytes,
                   deadline_mono_ms: int = 0) -> bytes:
    return _REQ.pack(op, req_id, group, flags, token,
                     deadline_mono_ms) + body


def decode_request(view) -> Tuple[int, int, int, int, int, int, bytes]:
    op, req_id, group, flags, token, deadline = \
        _REQ.unpack_from(view, 0)
    return op, req_id, group, flags, token, deadline, \
        bytes(view[_REQ.size:])


def encode_completion(req_id: int, status: int, leader: int,
                      body: bytes) -> bytes:
    return _CPL.pack(req_id, status, leader) + body


def decode_completion(view) -> Tuple[int, int, int, bytes]:
    req_id, status, leader = _CPL.unpack_from(view, 0)
    return req_id, status, leader, bytes(view[_CPL.size:])


def ring_paths(dirname: str, worker: int) -> Tuple[str, str]:
    return (os.path.join(dirname, f"req-{worker}.ring"),
            os.path.join(dirname, f"cpl-{worker}.ring"))


def _spin_wait(last_work_s: float) -> float:
    """Adaptive poll backoff: hot rings poll back-to-back, idle rings
    sleep up to 2 ms (cheap enough that N workers' drains cost <1% of a
    core at idle, short enough to be invisible under load)."""
    idle = time.monotonic() - last_work_s
    if idle < 0.002:
        return 0.0
    return min(0.002, idle * 0.1)


# ---------------------------------------------------------------------------
# Engine side.


class RingServer:
    """Drains every worker's request ring into the shared RaftDB and
    routes acks back through the per-worker completion rings.

    One drain thread per worker: proposals are popped in BATCHES
    (everything queued between two polls joins one pop window), handed
    to `rdb.propose` whose AckFutures complete on the engine's commit-
    consumer thread — the completion write happens there, so ack
    batching follows commit batching for free.  Blocking work (reads,
    document renders, membership admin) runs on a small executor so a
    slow SQLite read cannot stall the propose drain.
    """

    def __init__(self, rdb, dirname: str, workers: int,
                 ring_bytes: int = DEFAULT_RING_BYTES,
                 timeout_s: float = 30.0):
        os.makedirs(dirname, exist_ok=True)
        self.rdb = rdb
        self.dirname = dirname
        self.workers = workers
        self.timeout_s = timeout_s
        self._req: List[SpscRing] = []
        self._cpl: List[SpscRing] = []
        self._cpl_mu: List[threading.Lock] = []
        self.proposed = 0
        self.completed = 0
        self.deduped = 0
        self._stop = threading.Event()
        # Retry-token dedup at the serving plane: the fused engine
        # routes proposals on the host with PLAIN payloads (FusedPipe
        # drops the envelope pid), so the engine-side dedup window the
        # distributed runtime uses never sees these tokens.  The ring
        # server is the single choke point every worker's PUT crosses —
        # an LRU of token → outcome makes client retry-after-accept
        # exactly-once across ALL workers: a re-sent token joins the
        # in-flight proposal's waiters or replays its recorded outcome
        # instead of re-proposing.
        from collections import OrderedDict
        self._tok_mu = threading.Lock()
        # token -> [resolved, err_body|None, waiters [(worker, req_id)]]
        self._tokens: "OrderedDict[int, list]" = OrderedDict()  # raftlint: guarded-by=_tok_mu
        self._tok_cap = 1 << 16
        for i in range(workers):
            req_p, cpl_p = ring_paths(dirname, i)
            self._req.append(SpscRing(req_p, ring_bytes, create=True))
            self._cpl.append(SpscRing(cpl_p, ring_bytes, create=True))
            self._cpl_mu.append(threading.Lock())
        from concurrent.futures import ThreadPoolExecutor
        self._read_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * workers),
            thread_name_prefix="ring-read")
        self._threads = [
            threading.Thread(target=self._drain, args=(i,), daemon=True,
                             name=f"ring-drain-{i}")
            for i in range(workers)]
        # Serving-plane gauges for GET /metrics (merged by
        # RaftDB.metrics via the serving_metrics hook).
        if hasattr(rdb, "serving_metrics"):
            rdb.serving_metrics = self.metrics
        # Cross-process trace merge: workers flush per-process trace
        # segments into the ring directory; pointing the engine's
        # RaftDB at it makes GET /trace one multi-process timeline.
        rdb.trace_segments_dir = dirname
        # Ring-drain phase profiling rides the engine's tick-phase
        # profiler (obs/prof.py) when the engine exposes one.
        self._prof_node = getattr(getattr(rdb, "pipe", None), "node",
                                  None)
        # Shared-memory snapshot plane (runtime/shm.py, PR 12): the
        # read fast path workers map.  Attach the delta hook FIRST,
        # then start() with base images — the ordering makes the
        # published stream complete (shm.py start docstring).  Env
        # gate RAFTSQL_SHM_READS=0 turns the plane off on both sides
        # (chaos digest baselines run with it compiled in but idle).
        self.shm = None
        self._shm_thread = None
        if os.environ.get("RAFTSQL_SHM_READS", "1") != "0" \
                and hasattr(rdb, "_snapshot_of"):
            try:
                from raftsql_tpu.runtime.shm import ShmSnapshotPublisher
                self.shm = ShmSnapshotPublisher(dirname, rdb.num_groups)
                rdb.shm = self.shm
                self.shm.start(rdb._snapshot_of, rdb.watermark)
            except Exception:                           # noqa: BLE001
                log.exception("shm snapshot plane disabled")
                rdb.shm = None
                self.shm = None
        if self.shm is not None:
            self._shm_thread = threading.Thread(
                target=self._shm_refresh, daemon=True,
                name="shm-refresh")

    def _shm_refresh(self) -> None:
        """Restamp the shm watermark/leader/lease columns from the
        engine's host caches every couple of milliseconds — the
        publisher heartbeat a worker's lease read requires to be
        fresh (shm.py PUB_STALE_NS)."""
        node = self._prof_node
        commit_of = getattr(node, "commit_watermark", lambda g: 0)
        leader_of = getattr(node, "leader_of", lambda g: -1)
        lease_of = getattr(node, "lease_deadline_s", lambda g: 0.0)
        while not self._stop.is_set():
            try:
                self.shm.refresh(commit_of, leader_of, lease_of)
            except Exception:                           # noqa: BLE001
                log.exception("shm refresh failed; stopping")
                return
            self._stop.wait(0.002)

    def start(self) -> None:
        for t in self._threads:
            t.start()
        if self._shm_thread is not None:
            self._shm_thread.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        if self._shm_thread is not None:
            self._shm_thread.join(timeout=5)
        if self.shm is not None:
            self.rdb.shm = None
            self.shm.close()
        for r in self._req + self._cpl:
            r.close()

    def metrics(self) -> dict:
        return {
            "ring_workers": self.workers,
            "ring_proposed": self.proposed,
            "ring_completed": self.completed,
            "ring_deduped": self.deduped,
            "ring_depth": sum(r.depth_bytes() for r in self._req),
        }

    def flight_doc(self) -> dict:
        """Serving-plane state for a chaos flight bundle
        (obs/flight.py): the counters plus every worker's raw ring
        cursors/depths — where each producer and consumer stood at
        crash time."""
        rings = []
        for i in range(self.workers):
            rh, rt = self._req[i].cursors()
            ch, ct = self._cpl[i].cursors()
            rings.append({"worker": i,
                          "req_head": rh, "req_tail": rt,
                          "req_depth": max(0, rt - rh),
                          "cpl_head": ch, "cpl_tail": ct,
                          "cpl_depth": max(0, ct - ch)})
        return {"counters": self.metrics(), "rings": rings}

    # -- completion path (any engine thread) ----------------------------

    def _complete(self, worker: int, req_id: int, status: int,
                  leader: int, body: bytes) -> None:
        rec = encode_completion(req_id, status, leader, body)
        deadline = time.monotonic() + self.timeout_s
        mu, ring = self._cpl_mu[worker], self._cpl[worker]
        while True:
            with mu:
                if ring.push(rec):
                    self.completed += 1
                    return
            # Completion ring full: the worker is alive but behind —
            # wait it out (dropping an ack would hang a client).
            if time.monotonic() > deadline or self._stop.is_set():
                return
            time.sleep(0.0002)

    def _err_body(self, e: BaseException) -> bytes:
        return str(e).encode("utf-8", "replace")[:4096]

    def _overload(self):
        """The engine's attached admission controller, or None — the
        same attachment point the HTTP planes consult
        (node.overload, raftsql_tpu/overload/)."""
        return getattr(getattr(getattr(self.rdb, "pipe", None),
                               "node", None), "overload", None)

    def _retry_after_ms(self) -> int:
        """Retry-After for an ST_OVERLOADED completion's leader field
        (milliseconds, clamped to the wire's u32)."""
        ov = self._overload()
        if ov is None:
            return 1000
        return min(int(ov.retry_after_s() * 1000), 0xFFFFFFFF)

    # -- request handlers -----------------------------------------------

    def _watermark(self, group: int) -> int:
        """Engine session watermark for a ST_OK completion's leader
        field (clamped to the wire's u32; advisory, never fatal)."""
        try:
            return min(int(self.rdb.watermark(group)), 0xFFFFFFFF)
        except Exception:                               # noqa: BLE001
            return 0

    def _handle_put(self, worker: int, req_id: int, group: int,
                    token: int, body: bytes,
                    deadline_ms: Optional[float] = None) -> None:
        entry = None
        if token:
            with self._tok_mu:
                ent = self._tokens.get(token)
                if ent is not None:
                    self._tokens.move_to_end(token)
                    if ent[0]:          # resolved: replay the outcome
                        self.deduped += 1
                        err_body, wm = ent[1], ent[3]
                    else:               # in flight: join its waiters
                        ent[2].append((worker, req_id))
                        self.deduped += 1
                        return
                else:
                    entry = [False, None, [(worker, req_id)], 0]
                    self._tokens[token] = entry
                    while len(self._tokens) > self._tok_cap:
                        self._tokens.popitem(last=False)
            if entry is None:
                if err_body is None:
                    self._complete(worker, req_id, ST_OK, wm, b"")
                else:
                    self._complete(worker, req_id, ST_ERR, 0, err_body)
                return
        try:
            fut = self.rdb.propose(body.decode("utf-8"), group,
                                   token=token or None,
                                   **({} if deadline_ms is None
                                      else {"deadline_ms": deadline_ms}))
        except Exception as e:                          # noqa: BLE001
            from raftsql_tpu.overload import Overloaded
            if isinstance(e, Overloaded):
                # Admission refusal: 429 class — Retry-After rides the
                # completion's leader field (milliseconds).  Drop the
                # token entry (nothing is in flight), so a backed-off
                # retry re-proposes fresh instead of joining a waiter
                # list nothing will ever resolve.
                waiters = [(worker, req_id)]
                if entry is not None:
                    with self._tok_mu:
                        self._tokens.pop(token, None)
                        waiters = entry[2]
                ra = min(int(e.retry_after_s * 1000), 0xFFFFFFFF)
                for (w, rid) in waiters:
                    self._complete(w, rid, ST_OVERLOADED, ra,
                                   self._err_body(e))
                return
            self._resolve_put(entry, worker, req_id, self._err_body(e),
                              0)
            return
        self.proposed += 1

        def _done(err):
            self._resolve_put(
                entry, worker, req_id,
                None if err is None else self._err_body(err),
                self._watermark(group) if err is None else 0)

        fut.add_done_callback(_done)

    def _resolve_put(self, entry, worker: int, req_id: int,
                     err_body: Optional[bytes], wm: int) -> None:
        """Deliver a PUT outcome to its requester — and, for a
        tokenized PUT, to every retry that joined while it was in
        flight, recording the outcome (incl. the session watermark)
        for late retries."""
        if entry is None:
            waiters = [(worker, req_id)]
        else:
            with self._tok_mu:
                entry[0] = True
                entry[1] = err_body
                entry[3] = wm
                waiters, entry[2] = entry[2], []
        for (w, rid) in waiters:
            if err_body is None:
                self._complete(w, rid, ST_OK, wm, b"")
            else:
                self._complete(w, rid, ST_ERR, 0, err_body)

    def _handle_get(self, worker: int, req_id: int, group: int,
                    flags: int, token: int, body: bytes,
                    deadline_ms: Optional[float] = None) -> None:
        from raftsql_tpu.overload import Overloaded
        from raftsql_tpu.runtime.db import NotLeaderError
        # Flags bit 0 = linear, bit 1 = session (token carries the
        # watermark), bit 2 = follower; no bit = stale local read.
        mode = ("linear" if flags & 1 else
                "session" if flags & 2 else
                "follower" if flags & 4 else "local")

        def _run():
            try:
                rows = self.rdb.query(
                    body.decode("utf-8"), group, mode=mode,
                    watermark=token, timeout=self.timeout_s,
                    **({} if deadline_ms is None
                       else {"deadline_ms": deadline_ms}))
            except Overloaded as e:
                # Brownout refusal at the engine: over the ring the
                # opt-in downgrade is NOT offered (the completion has
                # no served-mode channel and a silent downgrade is
                # forbidden) — 429 + Retry-After, the client backs off.
                self._complete(worker, req_id, ST_OVERLOADED,
                               min(int(e.retry_after_s * 1000),
                                   0xFFFFFFFF), self._err_body(e))
            except NotLeaderError as e:
                self._complete(worker, req_id, ST_NOT_LEADER,
                               max(e.leader, 0), self._err_body(e))
            except TimeoutError as e:
                self._complete(worker, req_id, ST_UNAVAILABLE, 0,
                               self._err_body(e))
            except Exception as e:                      # noqa: BLE001
                self._complete(worker, req_id, ST_ERR, 0,
                               self._err_body(e))
            else:
                self._complete(worker, req_id, ST_OK,
                               self._watermark(group),
                               rows.encode("utf-8"))

        self._read_pool.submit(_run)

    def _handle_doc(self, worker: int, req_id: int, body: bytes) -> None:
        name = body.decode("utf-8", "replace")
        render = {
            "metrics": self.rdb.render_metrics,
            "health": self.rdb.render_health,
            "members": self.rdb.render_members,
            "trace": self.rdb.render_trace,
            "events": self.rdb.render_events,
        }.get(name)

        def _run():
            if render is None:
                self._complete(worker, req_id, ST_ERR, 0,
                               f"unknown document {name!r}".encode())
                return
            try:
                self._complete(worker, req_id, ST_OK, 0,
                               render().encode("utf-8"))
            except Exception as e:                      # noqa: BLE001
                self._complete(worker, req_id, ST_ERR, 0,
                               self._err_body(e))

        self._read_pool.submit(_run)

    def _handle_member(self, worker: int, req_id: int,
                       body: bytes) -> None:
        from raftsql_tpu.runtime.db import NotLeaderError

        def _run():
            try:
                req = json.loads(body.decode("utf-8") or "{}")
                got = self.rdb.member_change(int(req.get("group", 0)),
                                             str(req.get("op", "")),
                                             int(req.get("peer", -1)))
            except NotLeaderError as e:
                self._complete(worker, req_id, ST_NOT_LEADER,
                               max(e.leader, 0), self._err_body(e))
            except Exception as e:                      # noqa: BLE001
                self._complete(worker, req_id, ST_ERR, 0,
                               self._err_body(e))
            else:
                self._complete(worker, req_id, ST_OK, 0,
                               (json.dumps(got, sort_keys=True) + "\n")
                               .encode("utf-8"))

        self._read_pool.submit(_run)

    def _handle_transfer(self, worker: int, req_id: int,
                         body: bytes) -> None:
        from raftsql_tpu.runtime.db import NotLeaderError

        def _run():
            try:
                req = json.loads(body.decode("utf-8") or "{}")
                got = self.rdb.transfer(int(req.get("group", 0)),
                                        int(req.get("target", -1)))
            except NotLeaderError as e:
                self._complete(worker, req_id, ST_NOT_LEADER,
                               max(e.leader, 0), self._err_body(e))
            except Exception as e:                      # noqa: BLE001
                self._complete(worker, req_id, ST_ERR, 0,
                               self._err_body(e))
            else:
                self._complete(worker, req_id, ST_OK, 0,
                               (json.dumps(got, sort_keys=True) + "\n")
                               .encode("utf-8"))

        self._read_pool.submit(_run)

    def _handle_reshard(self, worker: int, req_id: int,
                        body: bytes) -> None:
        """POST /reshard over the ring (op 6): enqueue an elastic-
        keyspace verb at the engine's reshard plane.  Busy (one verb
        in flight) and no-plane refusals surface as ST_ERR text the
        worker maps back onto 409/503."""
        def _run():
            try:
                if self.rdb.reshard is None:
                    raise ValueError("no reshard plane (--reshard)")
                req = json.loads(body.decode("utf-8") or "{}")
                got = self.rdb.reshard.enqueue(
                    str(req.get("verb", "")),
                    int(req.get("src", -1)),
                    int(req.get("dst", -1)),
                    req.get("slots"))
            except Exception as e:                      # noqa: BLE001
                self._complete(worker, req_id, ST_ERR, 0,
                               self._err_body(e))
            else:
                self._complete(worker, req_id, ST_OK, 0,
                               (json.dumps(got, sort_keys=True) + "\n")
                               .encode("utf-8"))

        self._read_pool.submit(_run)

    # -- the drain loop --------------------------------------------------

    def _drain(self, worker: int) -> None:
        ring = self._req[worker]
        last = time.monotonic()
        while not self._stop.is_set():
            worked = False
            t_b0 = time.monotonic()
            while True:
                view = ring.pop()
                if view is None:
                    break
                op, req_id, group, flags, token, wire_dl, body = \
                    decode_request(view)
                ring.pop_commit()       # bytes copied out; release early
                worked = True
                # Ring-phase deadline shed (overload plane): a record
                # whose absolute monotonic-ms deadline already passed
                # while queued does no consensus work — ST_OVERLOADED
                # before any WAL/fsync cost, counted shed_ring.
                deadline_ms = None
                if wire_dl:
                    remain = wire_dl - time.monotonic() * 1000.0
                    if remain <= 0:
                        ov = self._overload()
                        if ov is not None:
                            ov.note_shed("ring")
                        self._complete(worker, req_id, ST_OVERLOADED,
                                       self._retry_after_ms(),
                                       b"deadline exceeded (ring)")
                        continue
                    deadline_ms = remain
                try:
                    if op == OP_PUT:
                        self._handle_put(worker, req_id, group, token,
                                         body, deadline_ms)
                    elif op == OP_GET:
                        self._handle_get(worker, req_id, group, flags,
                                         token, body, deadline_ms)
                    elif op == OP_DOC:
                        self._handle_doc(worker, req_id, body)
                    elif op == OP_MEMBER:
                        self._handle_member(worker, req_id, body)
                    elif op == OP_XFER:
                        self._handle_transfer(worker, req_id, body)
                    elif op == OP_RESHARD:
                        self._handle_reshard(worker, req_id, body)
                    else:
                        self._complete(worker, req_id, ST_ERR, 0,
                                       f"unknown op {op}".encode())
                except Exception as e:                  # noqa: BLE001
                    self._complete(worker, req_id, ST_ERR, 0,
                                   self._err_body(e))
            if worked:
                last = time.monotonic()
                # ring_drain phase sample (obs/prof.py): how long this
                # batch of popped requests took to hand off, tagged
                # with the worker id it drained.
                prof = getattr(self._prof_node, "prof", None)
                if prof is not None:
                    tick = int(getattr(self._prof_node, "_tick_no", 0))
                    if prof.sampled(tick):
                        prof.record("ring_drain", tick, t_b0,
                                    last - t_b0, tid=worker)
            else:
                delay = _spin_wait(last)
                if delay:
                    time.sleep(delay)


# ---------------------------------------------------------------------------
# Worker side.


class RingNotLeader(Exception):
    def __init__(self, leader: int, text: str):
        super().__init__(text)
        self.leader = leader


class RingClient:
    """The worker's RaftDB facade: the exact surface api/aio.py
    consumes — propose/abandon/query/member_change plus the render_*
    documents — implemented as ring round trips to the engine process.

    Proposals return an AckFuture-compatible object (add_done_callback
    + wait); completions are resolved by one consumer thread off the
    completion ring, so the aio plane's batched ack bridge works
    unchanged on top.
    """

    def __init__(self, dirname: str, worker: int,
                 attach_timeout_s: float = 60.0, trace: bool = False):
        req_p, cpl_p = ring_paths(dirname, worker)
        deadline = time.monotonic() + attach_timeout_s
        while True:
            try:
                self._req = SpscRing(req_p)
                self._cpl = SpscRing(cpl_p)
                break
            except (OSError, ValueError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self.worker = worker
        self._mu = threading.Lock()                 # producer + id alloc
        self._next_id = 1
        self._pending: Dict[int, "RingFuture"] = {}
        self._stop = threading.Event()
        self.error: Optional[Exception] = None      # facade parity
        # Session watermarks observed from ST_OK completions (the
        # engine's leader-field echo), per group: this worker's
        # X-Raft-Session response header source.  Monotone max — a
        # slightly stale value only makes a session read wait less.
        self._wm: Dict[int, int] = {}
        self._req_group: Dict[int, int] = {}
        # Cross-process trace merge (--trace): this worker stamps each
        # ring round trip (submit -> completion, pid/worker-id tagged)
        # into a per-process segment file under the ring dir; the
        # engine's /trace merges every segment into ONE multi-process
        # Perfetto timeline (obs/export.py TraceSegmentWriter).
        self._obs = None
        self._t0s: Dict[int, Tuple[float, str]] = {}
        if trace:
            from raftsql_tpu.obs.export import TraceSegmentWriter
            self._obs = TraceSegmentWriter(
                dirname, f"http worker {worker}",
                tag=f"w{worker}-{os.getpid()}")
        # Shared-memory read fast path (runtime/shm.py, PR 12):
        # best-effort attach — the engine creates the snapshot region
        # before the rings, so if the map fails (gate off, older
        # engine) every read simply takes the ring round trip.
        self._shm = None
        self._shm_hits = 0
        self._shm_fallbacks = 0
        if os.environ.get("RAFTSQL_SHM_READS", "1") != "0":
            try:
                from raftsql_tpu.runtime.shm import ShmSnapshotReader
                self._shm = ShmSnapshotReader(dirname)
            except Exception:                           # noqa: BLE001
                self._shm = None
        self._consumer = threading.Thread(
            target=self._consume, daemon=True,
            name=f"ring-cpl-{worker}")
        self._consumer.start()

    # -- plumbing --------------------------------------------------------

    _OP_NAMES = {OP_PUT: "ring.put", OP_GET: "ring.get",
                 OP_DOC: "ring.doc", OP_MEMBER: "ring.member",
                 OP_XFER: "ring.transfer", OP_RESHARD: "ring.reshard"}

    def _submit(self, op: int, group: int, flags: int, token: int,
                body: bytes, deadline_s: Optional[float] = None,
                deadline_ms: Optional[float] = None) -> "RingFuture":
        """`deadline_s` bounds the ring-full backoff below — callers
        plumb their own timeout through (member/transfer/doc pass
        their wait budgets, query passes its `timeout`) instead of the
        old hardcoded 2 s, so worker-side timeouts and engine-side
        deadlines agree.  `deadline_ms` (remaining client budget)
        additionally rides the record as an absolute monotonic-ms
        deadline the engine sheds against."""
        if deadline_s is None:
            deadline_s = 2.0
        wire_dl = 0 if deadline_ms is None else \
            max(1, int(time.monotonic() * 1000.0 + deadline_ms))
        fut = RingFuture()
        with self._mu:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
            self._req_group[req_id] = group
            if self._obs is not None:
                # Submit stamp: the span closes when the completion
                # pops (the client-visible ring round trip — HTTP
                # parse happened just before, the ack rides after).
                self._t0s[req_id] = (time.monotonic(),
                                     self._OP_NAMES.get(op, "ring.op"))
            ok = self._req.push(encode_request(op, req_id, group, flags,
                                               token, body, wire_dl))
        if not ok:
            # Ring full: back off briefly — the engine drains in big
            # gulps, so a full ring clears in microseconds unless the
            # engine is down.
            deadline = time.monotonic() + deadline_s
            while not ok:
                time.sleep(0.0002)
                with self._mu:
                    ok = self._req.push(encode_request(
                        op, req_id, group, flags, token, body, wire_dl))
                    if not ok and time.monotonic() > deadline:
                        self._pending.pop(req_id, None)
                        raise RingFull("propose ring full "
                                       "(engine stalled?)")
        return fut

    def _consume(self) -> None:
        last = time.monotonic()
        while not self._stop.is_set():
            worked = False
            while True:
                view = self._cpl.pop()
                if view is None:
                    break
                req_id, status, leader, body = decode_completion(view)
                self._cpl.pop_commit()
                worked = True
                fut = self._pending.pop(req_id, None)
                g = self._req_group.pop(req_id, None)
                if status == ST_OK and g is not None:
                    # ST_OK's leader field is the engine's session
                    # watermark echo — record BEFORE resolving so a
                    # caller reading watermark(g) right after wait()
                    # sees a value covering its own request.
                    if leader > self._wm.get(g, 0):
                        self._wm[g] = leader
                if fut is not None:
                    fut._resolve(status, leader, body)
                if self._obs is not None:
                    got = self._t0s.pop(req_id, None)
                    if got is not None:
                        now = time.monotonic()
                        self._obs.note(got[1], got[0], now - got[0],
                                       tid=0, status=status)
            if worked:
                last = time.monotonic()
                if self._obs is not None:
                    self._obs.maybe_flush()
            else:
                delay = _spin_wait(last)
                if delay:
                    time.sleep(delay)

    def close(self) -> None:
        self._stop.set()
        self._consumer.join(timeout=2)
        if self._obs is not None:
            self._obs.flush()       # the segment file outlives us
        if self._shm is not None:
            self._shm.close()
        self._req.close()
        self._cpl.close()

    # -- the RaftDB surface ---------------------------------------------

    def propose(self, query: str, group: int = 0,
                token: Optional[int] = None,
                deadline_ms: Optional[float] = None) -> "RingFuture":
        """`deadline_ms` (the client's remaining X-Raft-Deadline-Ms
        budget) rides the ring record so the engine sheds expired
        proposals before staging, and bounds the ring-full backoff so
        the worker never outwaits its own client."""
        return self._submit(
            OP_PUT, group, 0, token or 0, query.encode("utf-8"),
            deadline_s=(None if deadline_ms is None
                        else max(deadline_ms / 1000.0, 0.001)),
            deadline_ms=deadline_ms)

    def abandon(self, query: str, group: int, fut) -> None:
        """Deregister a timed-out proposal's callback (parity with
        RaftDB.abandon): the engine may still commit it — only this
        worker's interest is dropped."""
        with self._mu:
            for req_id, f in list(self._pending.items()):
                if f is fut:
                    self._pending.pop(req_id, None)
                    self._req_group.pop(req_id, None)
                    return

    def watermark(self, group: int = 0) -> int:
        """Session watermark for this worker's X-Raft-Session response
        header: the newest engine watermark observed on this worker's
        own completions (monotone; covers every request this worker
        has acked)."""
        return self._wm.get(group, 0)

    def query(self, query: str, group: int = 0, linear: bool = False,
              timeout: float = 10.0, mode: Optional[str] = None,
              watermark: int = 0, deadline_ms: Optional[float] = None,
              brownout: bool = False,
              info: Optional[dict] = None) -> str:
        """`deadline_ms` bounds the wait AND rides the ring record so
        the engine sheds the read once expired.  `brownout` (the
        client's X-Raft-Brownout opt-in) is accepted for facade parity
        but NOT forwarded: the completion wire has no served-mode
        channel and the overload contract forbids a silent downgrade,
        so a browned-out lease miss surfaces as Overloaded (429) here
        and the client backs off or retries another node."""
        from raftsql_tpu.overload import Overloaded
        from raftsql_tpu.runtime.db import NotLeaderError
        if deadline_ms is not None:
            timeout = min(timeout, max(deadline_ms / 1000.0, 0.0))
        if info is not None:
            info["served"] = mode if mode is not None else \
                ("linear" if linear else "local")
        if mode is None:
            mode = "linear" if linear else "local"
        flags = {"local": 0, "linear": 1, "session": 2,
                 "follower": 4}.get(mode)
        if flags is None:
            raise ValueError(f"unknown read mode {mode!r}")
        if self._shm is not None:
            # Zero-round-trip fast path: serve from the mapped
            # snapshot when it PROVES this mode's freshness contract
            # (shm.py module docstring); anything unprovable — stale
            # epoch, uncovered watermark, lapsed lease, SQL error —
            # falls through to the authoritative ring path below.
            got = None
            try:
                got = self._shm.try_read(mode, group, query,
                                         max(int(watermark), 0))
            except Exception:                           # noqa: BLE001
                self._shm.close()      # release the mmap, don't leak
                self._shm = None       # a broken mapping is dead
            if got is not None:
                rows, wm = got
                self._shm_hits += 1
                if wm > self._wm.get(group, 0):
                    self._wm[group] = wm
                return rows
            self._shm_fallbacks += 1
        fut = self._submit(OP_GET, group, flags,
                           max(int(watermark), 0),
                           query.encode("utf-8"),
                           deadline_s=timeout, deadline_ms=deadline_ms)
        status, leader, body = fut.wait_raw(timeout)
        if status == ST_OK:
            return body.decode("utf-8")
        text = body.decode("utf-8", "replace")
        if status == ST_NOT_LEADER:
            raise NotLeaderError(group, leader)
        if status == ST_UNAVAILABLE:
            raise TimeoutError(text)
        if status == ST_OVERLOADED:
            # leader field = Retry-After in milliseconds.
            raise Overloaded("ring", max(leader, 10) / 1000.0, text)
        raise ValueError(text)

    def member_change(self, group: int, op: str, peer: int) -> dict:
        from raftsql_tpu.runtime.db import NotLeaderError
        fut = self._submit(OP_MEMBER, group, 0, 0,
                           json.dumps({"group": group, "op": op,
                                       "peer": peer}).encode(),
                           deadline_s=10.0)
        status, leader, body = fut.wait_raw(10.0)
        if status == ST_OK:
            return json.loads(body.decode("utf-8"))
        if status == ST_NOT_LEADER:
            raise NotLeaderError(group, leader)
        raise ValueError(body.decode("utf-8", "replace"))

    def transfer(self, group: int, target: int) -> dict:
        """POST /transfer over the ring (op 5): arm a leadership
        transfer at the engine — same surface as RaftDB.transfer."""
        from raftsql_tpu.runtime.db import NotLeaderError
        fut = self._submit(OP_XFER, group, 0, 0,
                           json.dumps({"group": group,
                                       "target": target}).encode(),
                           deadline_s=10.0)
        status, leader, body = fut.wait_raw(10.0)
        if status == ST_OK:
            return json.loads(body.decode("utf-8"))
        if status == ST_NOT_LEADER:
            raise NotLeaderError(group, leader)
        raise ValueError(body.decode("utf-8", "replace"))

    def reshard(self, verb: str, src: int, dst: int,
                slots=None) -> dict:
        """POST /reshard over the ring (op 6): enqueue an elastic-
        keyspace verb — same surface as ReshardPlane.enqueue."""
        fut = self._submit(OP_RESHARD, 0, 0, 0,
                           json.dumps({"verb": verb, "src": src,
                                       "dst": dst,
                                       "slots": slots}).encode(),
                           deadline_s=10.0)
        status, _leader, body = fut.wait_raw(10.0)
        if status == ST_OK:
            return json.loads(body.decode("utf-8"))
        raise ValueError(body.decode("utf-8", "replace"))

    def _doc(self, name: str, timeout: float = 5.0) -> str:
        fut = self._submit(OP_DOC, 0, 0, 0, name.encode(),
                           deadline_s=timeout)
        status, _leader, body = fut.wait_raw(timeout)
        if status != ST_OK:
            raise RuntimeError(body.decode("utf-8", "replace"))
        return body.decode("utf-8")

    def _inject_reads(self, doc: dict) -> dict:
        """Fold this worker's shm fast-path counters into the engine's
        metrics document (the engine's own shm_hits/shm_fallbacks are
        always 0 — hits happen HERE).  Same mutation on both the JSON
        and prom renders, so scripts/check_prom.py's round-trip check
        stays exact."""
        r = doc.setdefault("reads", {})
        r["shm_hits"] = int(r.get("shm_hits", 0)) + self._shm_hits
        r["shm_fallbacks"] = (int(r.get("shm_fallbacks", 0))
                              + self._shm_fallbacks)
        return doc

    def render_metrics(self) -> str:
        return json.dumps(
            self._inject_reads(json.loads(self._doc("metrics"))),
            sort_keys=True) + "\n"

    def render_metrics_prom(self) -> str:
        """Prometheus exposition at a worker: fetch the engine's JSON
        document over the ring and render locally — same mapping as
        RaftDB.render_metrics_prom, no new ring op."""
        from raftsql_tpu.utils.metrics import prom_render
        return prom_render(
            self._inject_reads(json.loads(self._doc("metrics"))))

    def render_health(self) -> str:
        return self._doc("health")

    def render_members(self) -> str:
        return self._doc("members")

    def render_trace(self) -> str:
        return self._doc("trace", timeout=30.0)

    def render_events(self) -> str:
        return self._doc("events", timeout=30.0)


class RingFuture:
    """AckFuture-compatible result carrier for ring round trips: PUT
    consumers use add_done_callback(err)/wait(err contract); raw
    consumers (GET/DOC) read (status, leader, body)."""

    def __init__(self):
        self._evt = threading.Event()
        self._raw: Tuple[int, int, bytes] = (ST_UNAVAILABLE, 0,
                                             b"no completion")
        self._cb: Optional[Callable] = None
        self._mu = threading.Lock()

    def _resolve(self, status: int, leader: int, body: bytes) -> None:
        self._raw = (status, leader, body)
        self._evt.set()
        with self._mu:
            cb, self._cb = self._cb, None
        if cb is not None:
            cb(self._err())

    def _err(self) -> Optional[Exception]:
        status, leader, body = self._raw
        if status == ST_OK:
            return None
        text = body.decode("utf-8", "replace")
        if status == ST_NOT_LEADER:
            return RingNotLeader(leader, text)
        if status == ST_OVERLOADED:
            # leader field = Retry-After in milliseconds; the worker's
            # HTTP plane maps this onto 429 + Retry-After.
            from raftsql_tpu.overload import Overloaded
            return Overloaded("ring", max(leader, 10) / 1000.0, text)
        return RuntimeError(text)

    def add_done_callback(self, cb) -> None:
        with self._mu:
            if not self._evt.is_set():
                self._cb = cb
                return
        cb(self._err())

    def wait(self, timeout: Optional[float] = None) -> Optional[Exception]:
        if not self._evt.wait(timeout):
            raise TimeoutError("proposal not committed in time")
        return self._err()

    def wait_raw(self, timeout: Optional[float]) -> Tuple[int, int, bytes]:
        if not self._evt.wait(timeout):
            raise TimeoutError("no answer from engine in time")
        return self._raw
