"""RaftNode — the host event loop around the batched device step.

This is the TPU-native re-design of the reference's `raftNode`
(reference raft.go:38-273).  Where the reference's 100ms `serveChannels`
loop drives one vendored raft group (raft.go:204-245), this loop drives the
`peer_step` kernel for ALL G groups at once, then performs the host-side
I/O in the reference's exact durability order (raft.go:227-235):

    device step  →  WAL save (entries + hard state)  →  fsync
                 →  transport send                   →  publish commits

so entries are durable before they are sent, and sent before they are
published — invariant §2d.8 of SURVEY.md.

Host responsibilities (the device owns ordering/quorum math only):
  - staging inbound wire records into dense Inbox arrays;
  - mirroring entry payload bytes into storage.PayloadLog, both for local
    proposals (leader) and accepted appends (follower);
  - attaching payloads to outbound AppendEntries requests;
  - proposal forwarding to the current leader hint (the reference gets
    this from etcd/raft's MsgProp routing);
  - apply-at-commit publishing to the commit queue, with the reference's
    replay protocol: every replayed entry is published first, then a
    `None` sentinel marks the channel current (reference raft.go:122-134,
    consumed by db.go:45-52).
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from raftsql_tpu.config import (FOLLOWER, LEADER, MSG_REQ, MSG_RESP, NO_VOTE,
                                RaftConfig)
from raftsql_tpu.core.state import (Inbox, init_peer_state,
                                    install_snapshot_state,
                                    restore_peer_state, set_peer_progress)
from raftsql_tpu.core.step import peer_step_jit
from raftsql_tpu.runtime.envelope import DedupWindow, unwrap, wrap
from raftsql_tpu.storage.log import PayloadLog
from raftsql_tpu.storage.wal import WAL, wal_exists
from raftsql_tpu.transport.base import (AppendRec, ProposalRec, SnapshotRec,
                                        TickBatch, Transport, VoteRec)
from raftsql_tpu.utils.metrics import NodeMetrics

log = logging.getLogger("raftsql_tpu.node")

# Commit-queue sentinel marking end-of-stream (the reference closes the
# channel; Python queues need an explicit object).
CLOSED = object()


class RaftNode:
    """One consensus node: G raft groups, one peer row each.

    node_id is 1-based like the reference (raft.go:148-151); the device
    peer axis uses node_id - 1.
    """

    def __init__(self, node_id: int, num_nodes: int, cfg: RaftConfig,
                 transport: Transport, data_dir: str):
        if cfg.num_peers != num_nodes:
            raise ValueError("cfg.num_peers must equal num_nodes")
        self.cfg = cfg
        self.node_id = node_id
        self.self_id = node_id - 1
        self.num_nodes = num_nodes
        self.data_dir = data_dir
        self.transport = transport

        G = cfg.num_groups
        self.commit_q: "queue.Queue" = queue.Queue()
        self.error: Optional[Exception] = None
        self.metrics = NodeMetrics()

        self._stage_lock = threading.Lock()
        self._stage_votes: Dict[Tuple[int, int], VoteRec] = {}
        self._stage_apps: Dict[Tuple[int, int], AppendRec] = {}
        self._stage_snaps: Dict[int, SnapshotRec] = {}

        # InstallSnapshot hooks (wired by the apply layer in resume mode;
        # both unset => full state transfer disabled, catch-up below the
        # compaction floor just logs).  provider(g) -> (applied_idx, blob);
        # installer(g, last_idx, blob) replaces the state machine's state.
        self.snapshot_provider = None
        self.snapshot_installer = None
        self._snap_sent: Dict[Tuple[int, int], int] = {}
        self._snap_due: List[Tuple[int, int, int]] = []
        # Catch-up pacing: (group, dst) -> (next_idx last sent for, tick).
        # Rebuilding + resending the same out-of-window append every tick
        # is pure bandwidth waste; resend only on next_idx progress or
        # after a few ticks without it.
        self._catchup_sent: Dict[Tuple[int, int], Tuple[int, int]] = {}

        self._prop_lock = threading.Lock()
        self._props: List[deque] = [deque() for _ in range(G)]
        # Proposals forwarded to a (possibly stale) leader hint, kept as
        # (payload, deadline_tick): if the payload is not observed
        # committed by the deadline, it is re-queued and forwarded again.
        # Without this, a proposal forwarded to a crashed leader is lost
        # and its client hangs forever (the reference inherits the same
        # exposure from etcd/raft's MsgProp forwarding; the batched host
        # plane can do better cheaply).  Commit-observation matches by
        # payload identity — the same content-FIFO quirk as the ack
        # router (SURVEY.md §2d.3).
        self._fwd: List[List[Tuple[bytes, int]]] = [[] for _ in range(G)]
        self._tick_no = 0

        self.payload_log = PayloadLog(G)
        self._applied = [0] * G
        self._dedup = [DedupWindow() for _ in range(G)]
        self._hard_cache: Dict[int, Tuple[int, int, int]] = {}

        self._stop_evt = threading.Event()
        self._stopped = False           # full teardown ran (stop())
        self._thread: Optional[threading.Thread] = None
        self._tick_apps: Dict[Tuple[int, int], AppendRec] = {}
        # Serializes the tick's WAL phase against compaction rewrites.
        self._wal_lock = threading.Lock()

        # ---- replay (reference raft.go:122-134 + db.go:27-29 contract).
        self._had_wal = wal_exists(data_dir)
        groups = WAL.replay(data_dir)
        log_terms = {g: [t for (t, _) in gl.entries]
                     for g, gl in groups.items()}
        hard = {g: (gl.hard.term, gl.hard.vote, gl.hard.commit)
                for g, gl in groups.items()}
        starts = {g: (gl.start, gl.start_term) for g, gl in groups.items()}
        self.state = restore_peer_state(cfg, self.self_id, log_terms, hard,
                                        starts=starts)
        for g, gl in groups.items():
            if gl.start:
                self.payload_log.set_start(g, gl.start, gl.start_term)
            self.payload_log.put(g, gl.start + 1,
                                 [d for (_, d) in gl.entries],
                                 [t for (t, _) in gl.entries])
            self._hard_cache[g] = (gl.hard.term, gl.hard.vote,
                                   gl.hard.commit)
            # Reference parity: replay publishes every WAL entry, then the
            # nil sentinel (raft.go:130-132); apply-at-commit only governs
            # live traffic.  Empty (no-op/conf) entries are skipped
            # (raft.go:84-87).
            self._applied[g] = gl.log_len
        self._replay_groups = groups
        self.wal = WAL(data_dir, segment_bytes=cfg.wal_segment_bytes)
        self._self_arr = jnp.asarray(self.self_id, jnp.int32)

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        for g, gl in sorted(self._replay_groups.items()):
            for i, (term, data) in enumerate(gl.entries):
                sql = self._decode_entry(g, data)
                if sql is not None:
                    self.commit_q.put((g, gl.start + 1 + i, sql))
        self._replay_groups = {}
        self.commit_q.put(None)         # replay-complete sentinel
        self.transport.start(self.node_id, self._deliver, self._on_error)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-node-{self.node_id}")
        self._thread.start()

    def stop(self) -> None:
        # _on_error may have set _stop_evt already (transport failure
        # teardown); the transport/WAL cleanup below must STILL run then —
        # only a completed stop() makes a second call a no-op.
        if self._stopped:
            return
        self._stopped = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.transport.stop()
        self.wal.close()
        self.commit_q.put(CLOSED)

    def _on_error(self, err: Exception) -> None:
        # Transport failure → teardown, error fans out to pending acks
        # (reference raft.go:136-142, db.go:83-95).
        log.error("node %d transport error: %s", self.node_id, err)
        self.error = err
        self._stop_evt.set()
        self.commit_q.put(CLOSED)

    # ------------------------------------------------------------------
    # client plane

    def propose(self, group: int, payload: bytes) -> None:
        """Enqueue a proposal; routed to the leader on the next tick.

        The payload is wrapped with a unique envelope id so that
        forward-retries after leader failure apply exactly once
        (runtime/envelope.py)."""
        if not 0 <= group < self.cfg.num_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.cfg.num_groups})")
        with self._prop_lock:
            self._props[group].append(wrap(payload))

    def _decode_entry(self, group: int, data: bytes) -> Optional[str]:
        """Envelope-aware publish decision: None = skip (empty entry or
        duplicate of an already-applied forwarded proposal)."""
        if not data:
            return None
        pid, payload = unwrap(data)
        if pid is not None and self._dedup[group].seen(pid):
            return None
        return payload.decode("utf-8")

    def leader_of(self, group: int) -> int:
        """Last known leader (0-based peer), -1 if unknown."""
        return int(np.asarray(self.state.leader_hint)[group])

    # ------------------------------------------------------------------
    # log compaction (snapshot-resume mode, SURVEY.md §5.4 improvement)

    def compact(self, applied: Dict[int, int], keep: int = 256) -> bool:
        """Drop log prefixes covered by state-machine snapshots.

        `applied[g]` is the index durably applied by the snapshot-capable
        state machine.  Entries up to min(applied, commit) - keep are
        dropped from the payload log, COMPACT floor markers are appended
        to the WAL's active segment, and whole closed segments below
        every floor are unlinked (storage/wal.py compact) — never a
        stop-the-world rewrite of live data, so the tick's WAL phase is
        blocked only for the marker appends + unlinks.  The retained
        `keep` window lets slow followers catch up from the payload log;
        beyond it, the leader ships a full state transfer
        (InstallSnapshot, _send_phase).

        Returns True if anything was compacted.
        """
        # Never compact into the device ring window: the ordinary send
        # path slices payloads for any in-window prev index.
        keep = max(keep, self.cfg.log_window)
        with self._wal_lock:
            changed = False
            floors: Dict[int, Tuple[int, int]] = {}
            for g in range(self.cfg.num_groups):
                _, _, commit = self._hard_cache.get(g, (0, -1, 0))
                floor = min(applied.get(g, 0), commit,
                            self._applied[g]) - keep
                if floor > self.payload_log.start(g):
                    self.payload_log.compact(
                        g, floor, self.payload_log.term_of(g, floor))
                    changed = True
                s = self.payload_log.start(g)
                if s > 0:
                    floors[g] = (s, self.payload_log.term_of(g, s))
            if not changed:
                return False
            self.wal.compact(floors, self._hard_cache)
            self.metrics.compactions += 1
            return True

    # ------------------------------------------------------------------
    # transport plane

    def _deliver(self, src: int, batch: TickBatch) -> None:
        """Stage inbound records; newest message per (group, src, slot)
        wins, mirroring the dense Inbox overwrite semantics.

        Records that don't fit this node's configuration (unknown group,
        oversized entry batch, bad src) are dropped, not fatal: a
        misconfigured or malicious peer must not tear down this node
        (cf. the reference trusting rafthttp framing, raft.go:268-270)."""
        G, E = self.cfg.num_groups, self.cfg.max_entries_per_msg
        src0 = src - 1
        if not (0 <= src0 < self.num_nodes) or src0 == self.self_id:
            log.warning("node %d: dropping batch from bad src %d",
                        self.node_id, src)
            return
        with self._stage_lock:
            for v in batch.votes:
                if 0 <= v.group < G:
                    self._stage_votes[(v.group, src0)] = v
            for a in batch.appends:
                if 0 <= a.group < G and a.n <= E \
                        and len(a.payloads) in (0, a.n):
                    self._stage_apps[(a.group, src0)] = a
            for s in batch.snapshots:
                if 0 <= s.group < G:
                    old = self._stage_snaps.get(s.group)
                    if old is None or s.last_idx > old.last_idx:
                        self._stage_snaps[s.group] = s
        if batch.proposals:
            with self._prop_lock:
                for pr in batch.proposals:
                    if 0 <= pr.group < G:
                        self._props[pr.group].append(pr.payload)

    # ------------------------------------------------------------------
    # the event loop

    def _run(self) -> None:
        interval = self.cfg.tick_interval_s
        while not self._stop_evt.is_set():
            t0 = time.monotonic()
            try:
                self.tick()
            except Exception as e:       # pragma: no cover - defensive
                log.exception("node %d tick failed", self.node_id)
                self._on_error(e)
                return
            dt = time.monotonic() - t0
            if dt < interval:
                time.sleep(interval - dt)

    def tick(self) -> None:
        """One full consensus tick: stage → step → WAL → send → publish."""
        cfg = self.cfg
        G, P, E = cfg.num_groups, cfg.num_peers, cfg.max_entries_per_msg

        self._install_snapshots()
        inbox, tick_apps = self._build_inbox()
        self._tick_apps = tick_apps

        with self._prop_lock:
            prop_n = np.fromiter(
                (min(len(q), E) for q in self._props), np.int32, G)

        state, outbox, info = peer_step_jit(
            cfg, self.state, inbox, jnp.asarray(prop_n), self._self_arr)
        self.state = state
        outbox, info = jax.device_get((outbox, info))

        with self._wal_lock:
            self._wal_phase(info)       # durable …
        self._send_phase(outbox, info)  # … before sent …
        self._publish_phase(info)       # … before published.
        self._tick_no += 1
        self.metrics.ticks += 1

    # -- tick phases -----------------------------------------------------

    def _install_snapshots(self) -> None:
        """Apply staged InstallSnapshot transfers (receiver side).

        Only installs strictly ahead of both the local applied point and
        the device commit — snapshots carry committed state, so this
        never regresses; stale/duplicate transfers are dropped.
        """
        if self.snapshot_installer is None:
            # The apply layer registers the installer shortly after node
            # start; keep transfers staged instead of dropping them so a
            # snapshot arriving in that boot window still installs.
            return
        with self._stage_lock:
            snaps, self._stage_snaps = self._stage_snaps, {}
        if not snaps:
            return
        commit = term = None
        for g, rec in snaps.items():
            if commit is None:
                commit = np.asarray(self.state.commit)
                # Writable copy: adopted terms are folded back in so a
                # second staged snapshot for the same group sees them.
                term = np.array(self.state.term)
            if rec.term < int(term[g]):
                # Raft: reject any RPC whose term < currentTerm — a
                # delayed transfer from a deposed leader must not demote
                # a current-term leader or truncate its tail.
                continue
            if rec.term > int(term[g]):
                # A valid higher-term RPC steps this group down on
                # RECEIPT (raft §5.1), even if the transfer itself turns
                # out to be a duplicate or corrupt below.
                st = self.state
                self.state = st._replace(
                    term=st.term.at[g].set(rec.term),
                    voted_for=st.voted_for.at[g].set(NO_VOTE),
                    role=st.role.at[g].set(FOLLOWER),
                    votes=st.votes.at[g].set(False))
                term[g] = rec.term
            if rec.last_idx <= max(self._applied[g], int(commit[g])):
                continue
            try:
                self.snapshot_installer(g, rec.last_idx, rec.blob)
            except Exception as e:
                # A corrupt/truncated transfer must not tear down the
                # node (cf. the _deliver contract); drop it — the leader
                # re-sends after its cooldown.
                log.warning("node %d g%d: snapshot install failed (%s); "
                            "dropped", self.node_id, g, e)
                continue
            # Counted at SM-install time: observers (tests, operators)
            # see the data the moment the state machine has it, while the
            # device-state patch below may still be compiling.
            self.metrics.snapshots_installed += 1
            # The whole install — payload-log reset, WAL marker, device
            # patch, applied floor — is one atomic unit vs. compact()'s
            # multi-call read of the payload log (it holds _wal_lock for
            # its image build); a reset racing that read corrupts the
            # rewritten WAL.
            with self._wal_lock:
                self.payload_log.reset(g, rec.last_idx, rec.last_term)
                self.wal.set_snapshot(g, rec.last_idx, rec.last_term)
                self.wal.sync()
                self.state = install_snapshot_state(
                    self.state, g, rec.last_idx, rec.last_term,
                    self.cfg.log_window, rec.term)
                self._applied[g] = rec.last_idx
            log.info("node %d g%d: installed snapshot at idx %d",
                     self.node_id, g, rec.last_idx)

    def _build_inbox(self):
        cfg = self.cfg
        G, P, E = cfg.num_groups, cfg.num_peers, cfg.max_entries_per_msg
        z = lambda: np.zeros((G, P), np.int32)
        zb = lambda: np.zeros((G, P), bool)
        v_type, v_term, v_li, v_lt = z(), z(), z(), z()
        v_gr = zb()
        a_type, a_term, a_pi, a_pt, a_n, a_cm, a_ma = (
            z(), z(), z(), z(), z(), z(), z())
        a_su = zb()
        a_ents = np.zeros((G, P, E), np.int32)
        with self._stage_lock:
            votes, apps = self._stage_votes, self._stage_apps
            self._stage_votes, self._stage_apps = {}, {}
        for (g, s), v in votes.items():
            v_type[g, s], v_term[g, s] = v.type, v.term
            v_li[g, s], v_lt[g, s] = v.last_idx, v.last_term
            v_gr[g, s] = v.granted
        for (g, s), a in apps.items():
            a_type[g, s], a_term[g, s] = a.type, a.term
            a_pi[g, s], a_pt[g, s] = a.prev_idx, a.prev_term
            a_n[g, s], a_cm[g, s] = a.n, a.commit
            a_su[g, s], a_ma[g, s] = a.success, a.match
            a_ents[g, s, :a.n] = a.ent_terms[:E]
        inbox = Inbox(
            v_type=jnp.asarray(v_type), v_term=jnp.asarray(v_term),
            v_last_idx=jnp.asarray(v_li), v_last_term=jnp.asarray(v_lt),
            v_granted=jnp.asarray(v_gr),
            a_type=jnp.asarray(a_type), a_term=jnp.asarray(a_term),
            a_prev_idx=jnp.asarray(a_pi), a_prev_term=jnp.asarray(a_pt),
            a_n=jnp.asarray(a_n), a_ents=jnp.asarray(a_ents),
            a_commit=jnp.asarray(a_cm), a_success=jnp.asarray(a_su),
            a_match=jnp.asarray(a_ma))
        return inbox, apps

    def _wal_phase(self, info) -> None:
        """Persist this tick's appends + hard-state changes, one fsync.

        Entry records are accumulated across all groups and written with
        ONE batched WAL call (the C++ fast path frames them without a
        per-record Python round trip — native/wal.cc)."""
        G = self.cfg.num_groups
        term = info.term
        w_groups: List[int] = []
        w_idx: List[int] = []
        w_terms: List[int] = []
        w_data: List[bytes] = []
        hard_changes: List[Tuple[int, Tuple[int, int, int]]] = []

        def put_rec(g: int, idx: int, t: int, data: bytes) -> None:
            w_groups.append(g)
            w_idx.append(idx)
            w_terms.append(t)
            w_data.append(data)

        for g in range(G):
            n_acc = int(info.prop_accepted[g])
            if info.noop[g] or n_acc:
                base = int(info.prop_base[g])
                if info.noop[g]:
                    put_rec(g, base, int(term[g]), b"")
                    self.payload_log.put(g, base, [b""], [int(term[g])])
                if n_acc:
                    with self._prop_lock:
                        batch = [self._props[g].popleft()
                                 for _ in range(n_acc)]
                    for i, data in enumerate(batch):
                        put_rec(g, base + 1 + i, int(term[g]), data)
                    self.payload_log.put(g, base + 1, batch,
                                         [int(term[g])] * n_acc)
                self.metrics.proposals += n_acc
            src = int(info.app_from[g])
            if src >= 0:
                rec = self._tick_apps.get((g, src))
                if rec is None:      # staged slot raced away; next resend
                    continue         # re-delivers — raft tolerates loss
                start = int(info.app_start[g])
                new_len = int(info.new_log_len[g])
                for i in range(int(info.app_n[g])):
                    put_rec(g, start + i, rec.ent_terms[i],
                            rec.payloads[i])
                self.payload_log.put(g, start, rec.payloads,
                                     rec.ent_terms, new_len=new_len)
                if info.app_conflict[g] and self._applied[g] >= start:
                    # Only possible for replay-published uncommitted
                    # entries (the reference applies at append and shares
                    # this hazard — SURVEY.md §3.2 quirk).
                    log.warning("node %d g%d: conflict truncation below "
                                "applied=%d; state machine may have seen "
                                "an uncommitted entry", self.node_id, g,
                                self._applied[g])
                    self._applied[g] = min(self._applied[g], start - 1)
            hs = (int(term[g]), int(info.voted_for[g]), int(info.commit[g]))
            if self._hard_cache.get(g) != hs:
                hard_changes.append((g, hs))
                self._hard_cache[g] = hs
        # Entries land before hard states (etcd wal.Save order): a torn
        # tail can then never leave a hard state referencing lost entries.
        if w_groups:
            self.wal.append_entries(w_groups, w_idx, w_terms, w_data)
        for g, hs in hard_changes:
            self.wal.set_hardstate(g, *hs)
        self.wal.sync()

    def _build_catchups(self, info) -> Dict[Tuple[int, int], AppendRec]:
        """Host-built AppendEntries for followers beyond the device ring.

        The device term ring only describes the last W log positions; a
        follower whose next_idx has fallen out of that window gets empty
        heartbeats from the device (core/step.py Phase 9 window guard).
        The leader HOST owns the full (term, payload) history
        (storage/log.py), so it constructs the out-of-window appends here
        — the analog of etcd MemoryStorage-backed sendAppend for entries
        the in-memory window no longer covers.  Responses flow back
        through the normal device path, advancing next_idx/match until
        the follower re-enters the window.
        """
        cfg = self.cfg
        W, E = cfg.log_window, cfg.max_entries_per_msg
        self._snap_due = []
        role = np.asarray(info.role)
        if not (role == LEADER).any():
            return {}
        next_idx = np.asarray(info.next_idx)            # [G, P]
        log_len = np.asarray(info.new_log_len)          # [G]
        commit = np.asarray(info.commit)
        term = np.asarray(info.term)
        # Margin of 2E: start host catch-up slightly before the hard edge
        # of the ring so a race with concurrent appends cannot strand the
        # follower on garbage ring reads.
        lag = (role == LEADER)[:, None] & (next_idx >= 1) \
            & (next_idx - 1 <= log_len[:, None] - W + 2 * E)
        lag[:, self.self_id] = False
        out: Dict[Tuple[int, int], AppendRec] = {}
        for g, d in zip(*np.nonzero(lag)):
            g, d = int(g), int(d)
            ni = int(next_idx[g, d])
            prev_sent = self._catchup_sent.get((g, d))
            if prev_sent is not None and prev_sent[0] == ni \
                    and self._tick_no - prev_sent[1] < 4:
                continue        # no progress yet; give the ack time
            avail = self.payload_log.length(g)
            n = min(E, avail - ni + 1)
            got = self.payload_log.try_tail_with_terms(g, ni, n) \
                if n > 0 else None
            if got is None:
                if ni <= self.payload_log.start(g):
                    # Beyond the compacted prefix: needs a full state
                    # transfer (InstallSnapshot), queued by _send_phase.
                    self._snap_due.append((g, d, int(term[g])))
                continue
            prev_term, ents = got
            self._catchup_sent[(g, d)] = (ni, self._tick_no)
            out[(g, d)] = AppendRec(
                group=g, type=MSG_REQ, term=int(term[g]),
                prev_idx=ni - 1, prev_term=prev_term,
                ent_terms=[t for (t, _) in ents],
                payloads=[p for (_, p) in ents],
                commit=min(int(commit[g]), ni - 1 + len(ents)))
            self.metrics.catchup_appends += 1
        return out

    def _send_phase(self, outbox, info) -> None:
        cfg = self.cfg
        batches: Dict[int, TickBatch] = {}

        def batch_for(dst0: int) -> TickBatch:
            return batches.setdefault(dst0, TickBatch())

        catchups = self._build_catchups(info)

        vg, vd = np.nonzero(outbox.v_type)
        for g, d in zip(vg.tolist(), vd.tolist()):
            batch_for(d).votes.append(VoteRec(
                group=g, type=int(outbox.v_type[g, d]),
                term=int(outbox.v_term[g, d]),
                last_idx=int(outbox.v_last_idx[g, d]),
                last_term=int(outbox.v_last_term[g, d]),
                granted=bool(outbox.v_granted[g, d])))
        ag, ad = np.nonzero(outbox.a_type)
        emitted = set()
        for g, d in zip(ag.tolist(), ad.tolist()):
            emitted.add((g, d))
            mtype = int(outbox.a_type[g, d])
            cu = catchups.pop((g, d), None) if mtype == MSG_REQ else None
            if cu is not None:
                # The device could only offer an empty heartbeat to this
                # out-of-window follower; substitute the host-built
                # catch-up append (same slot, newest-wins semantics).
                batch_for(d).appends.append(cu)
                continue
            n = int(outbox.a_n[g, d])
            prev = int(outbox.a_prev_idx[g, d])
            if mtype == MSG_REQ:
                # The device ring can reference positions below the
                # payload floor (log-length regression after conflict
                # truncation / snapshot install, or a concurrent
                # compaction advancing the floor).  try_slice is atomic
                # against the compactor; on miss, drop the message — the
                # peer is served by catch-up or snapshot on a later tick.
                payloads = self.payload_log.try_slice(g, prev + 1, n)
                if payloads is None:
                    continue
            else:
                payloads = []
            batch_for(d).appends.append(AppendRec(
                group=g, type=mtype, term=int(outbox.a_term[g, d]),
                prev_idx=prev, prev_term=int(outbox.a_prev_term[g, d]),
                ent_terms=[int(t) for t in outbox.a_ents[g, d, :n]],
                payloads=payloads, commit=int(outbox.a_commit[g, d]),
                success=bool(outbox.a_success[g, d]),
                match=int(outbox.a_match[g, d])))
        for (g, d), cu in catchups.items():
            if (g, d) in emitted:
                # The device emitted a (response) message for this slot;
                # the receiver stages one append per (group, src), newest
                # wins — don't clobber it.  Un-record the pacing entry so
                # the catch-up is rebuilt next tick, not in 4.
                self._catchup_sent.pop((g, d), None)
                continue
            batch_for(d).appends.append(cu)

        # InstallSnapshot dispatch (rate-limited: transfers are bulky and
        # idempotent, a cooldown per (group, peer) is plenty).
        if self._snap_due and self.snapshot_provider is not None:
            cooldown = 8 * cfg.election_ticks
            for g, d, term_g in self._snap_due:
                last = self._snap_sent.get((g, d), -cooldown)
                if self._tick_no - last < cooldown:
                    continue
                got = self.snapshot_provider(g)
                if got is None:
                    continue
                last_idx, blob = got
                if last_idx <= self.payload_log.start(g) \
                        and last_idx < self.payload_log.length(g):
                    # The snapshot doesn't reach the floor the follower
                    # needs (applier lagging behind compaction — cannot
                    # happen through the RaftDB path, which compacts only
                    # below its own applied index); don't send garbage.
                    continue
                self._snap_sent[(g, d)] = self._tick_no
                batch_for(d).snapshots.append(SnapshotRec(
                    group=g, last_idx=last_idx,
                    last_term=self.payload_log.term_of(g, last_idx),
                    term=term_g, blob=blob))
                # Resume replication above the transfer; see
                # set_peer_progress for why this is safe if it is lost.
                self.state = set_peer_progress(
                    self.state, g, d, last_idx + 1)
                self.metrics.snapshots_sent += 1
        self._snap_due = []

        # Proposal forwarding: anything still queued while we are not the
        # leader goes to the leader hint, and is tracked for retry until
        # its commit is observed (see _fwd above).
        role = info.role
        hint = info.leader_hint
        deadline = self._tick_no + 4 * cfg.election_ticks
        with self._prop_lock:
            for g in range(cfg.num_groups):
                expired = [p for (p, d) in self._fwd[g]
                           if d <= self._tick_no]
                if expired:
                    self._fwd[g] = [(p, d) for (p, d) in self._fwd[g]
                                    if d > self._tick_no]
                    self._props[g].extendleft(reversed(expired))
                h = int(hint[g])
                if role[g] != LEADER and h >= 0 and h != self.self_id \
                        and self._props[g]:
                    fwd = list(self._props[g])
                    self._props[g].clear()
                    for p in fwd:
                        batch_for(h).proposals.append(
                            ProposalRec(group=g, payload=p))
                        self._fwd[g].append((p, deadline))

        for dst0, batch in batches.items():
            self.transport.send(dst0 + 1, batch)
            self.metrics.msgs_sent += (len(batch.votes)
                                       + len(batch.appends)
                                       + len(batch.proposals)
                                       + len(batch.snapshots))

    def _publish_phase(self, info) -> None:
        for g in range(self.cfg.num_groups):
            c = int(info.commit[g])
            while self._applied[g] < c:
                idx = self._applied[g] + 1
                data = self.payload_log.get(g, idx)
                if data and self._fwd[g]:
                    # Forwarded proposal observed committed: retire it
                    # (exact match — envelope ids are unique).
                    for k, (p, _) in enumerate(self._fwd[g]):
                        if p == data:
                            del self._fwd[g][k]
                            break
                sql = self._decode_entry(g, data)
                if sql is not None:
                    self.commit_q.put((g, idx, sql))
                self._applied[g] += 1
                self.metrics.commits += 1
